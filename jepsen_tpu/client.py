"""Client protocol: how workers apply operations to the system under test
(reference: jepsen.client, client.clj:8-36).

Lifecycle: open(test, node) -> connected client; setup(test) once for DB
state; invoke(test, op) -> completion op (type ok/fail/info per the
determinacy rules, core.clj:271-304); teardown(test); close(test).
open/close must not affect logical DB state.
"""

from __future__ import annotations

from .history import Op


class Client:
    def open(self, test, node) -> "Client":
        """Connect to `node`; returns a ready client (often a new
        instance). Must not alter logical state."""
        return self

    def close(self, test) -> None:
        """Release the connection. Must not alter logical state."""

    def setup(self, test) -> None:
        """One-time database state setup."""

    def invoke(self, test, op: Op) -> Op:
        """Apply op, returning the completion (op.with_(type=...)).
        Raise for indeterminate outcomes — the worker records :info."""
        raise NotImplementedError

    def teardown(self, test) -> None:
        """Clean up database state."""


class Noop(Client):
    """Does nothing successfully (client.clj:28-36)."""

    def invoke(self, test, op):
        return op.with_(type="ok")


noop = Noop()


class Validating(Client):
    """Wraps a client, asserting invoke() returns a well-formed completion
    (the worker also validates; this gives clearer errors in client unit
    tests)."""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test, node):
        return Validating(self.client.open(test, node))

    def close(self, test):
        self.client.close(test)

    def setup(self, test):
        self.client.setup(test)

    def teardown(self, test):
        self.client.teardown(test)

    def invoke(self, test, op):
        completion = self.client.invoke(test, op)
        assert isinstance(completion, Op), completion
        assert completion.type in ("ok", "fail", "info"), completion
        assert completion.process == op.process, completion
        assert completion.f == op.f, completion
        return completion
