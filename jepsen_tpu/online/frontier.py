"""Incremental transactional cycle checking: an edge-insert frontier
over checker/cycle.

The frontier ingests ops one at a time (``append``) and produces, on
demand (``advance``), the Adya classification of everything seen so
far. It maintains the dependency structure incrementally — per-key
micro-op slots and per-key edge lists, recomputed only for keys the
new ops touched — and then runs the EXACT batch classifier
(``checker/cycle/anomalies.classify``) over the assembled matrices,
with the per-component closure jobs memoized across advances through
``classify``'s journal hook (the same content-hash keys
``store.AnalysisJournal`` uses). A weakly-connected component no new
edge touched hashes to the same closure job as last advance and is
reused; only dirty components re-square on the supervised ladder.

Bit-identity contract: ``advance()`` returns exactly what
``CycleChecker.check(test, history[:n], opts)`` returns for the same
prefix, minus the "supervision" telemetry delta and the store-side
timeline rendering (observability, not verdict). The per-key edge
functions, the mixed-mode key check, the classifier, the witness
recovery, and the first-failing-key error selection are all the batch
code's own — shared, not transcribed — so the streaming and batch
paths cannot drift apart.
"""

from __future__ import annotations

import numpy as np

from ..checker.cycle import CycleChecker, checker as cycle_checker
from ..checker.cycle import deps as _deps
from ..checker.cycle.anomalies import classify
from ..checker.cycle.deps import DepGraph, IllegalInference
from ..history import ops as _ops

__all__ = ["ClosureMemo", "CycleFrontier"]


class ClosureMemo:
    """A duck-typed ``store.AnalysisJournal`` for ``classify``'s
    journal hook: per-component closure results keyed by content hash,
    held in memory for the frontier's lifetime and optionally written
    through to a real journal (so a resumed watch session reloads
    them from disk)."""

    def __init__(self, journal=None):
        self._mem: dict = {}
        self._journal = journal

    def get(self, kind: str, key):
        r = self._mem.get((kind, str(key)))
        if r is None and self._journal is not None:
            r = self._journal.get(kind, key)
        return r

    def contains(self, kind: str, key) -> bool:
        return self.get(kind, key) is not None

    def record(self, kind: str, key, result) -> None:
        self._mem[(kind, str(key))] = result
        if self._journal is not None:
            self._journal.record(kind, key, result)

    def __len__(self) -> int:
        return len(self._mem)


class CycleFrontier:
    """Streaming frontier over one (possibly keyed) transactional
    history.

    checker      the CycleChecker whose verdicts to stream (anomalies,
                 version order, realtime flavor, engine pin); default
                 ``cycle.checker()``
    journal      optional store.AnalysisJournal the closure memo
                 writes through to (resume support)
    history_key  the independent history_key, as in
                 ``CycleChecker.check`` opts (None for a global
                 stream: register ops lift against key 0)
    """

    def __init__(self, checker: CycleChecker | None = None, *,
                 journal=None, history_key=None):
        self.checker = checker if checker is not None else cycle_checker()
        self.memo = ClosureMemo(journal)
        self.history_key = history_key
        self.ops: list = []        # every appended op, coerced to Op
        self._nodes: list = []     # completion Op per graph node
        self._slots: dict = {}     # key -> {"appends","writes","reads"}
        self._key_order: list = [] # first-touch key order (= extract's)
        self._dirty: set = set()
        self._edges: dict = {}     # key -> {rel: [(i, j)]} | {"error": info}
        self.checked = 0           # prefix length of the last advance
        self.verdict: dict | None = None

    def __len__(self) -> int:
        return len(self.ops)

    @property
    def pending(self) -> int:
        """Ops appended since the last advance."""
        return len(self.ops) - self.checked

    def append(self, op) -> None:
        """Ingest one op: update the per-key slots and mark the keys
        it touches dirty. Non-ok and non-transactional ops join the
        prefix but add no node (exactly as ``deps.txns_of`` skips
        them)."""
        (o,) = _ops([op])
        o = self.checker._unwrap(o)
        self.ops.append(o)
        txns = _deps.txns_of([o], key=self.history_key)
        if not txns:
            return
        (_, t), = txns
        i = len(self._nodes)
        self._nodes.append(o)
        for m in t:
            k = _deps.mop.key(m)
            slot = self._slots.get(k)
            if slot is None:
                slot = {"appends": [], "writes": [], "reads": []}
                self._slots[k] = slot
                self._key_order.append(k)
            if _deps.mop.is_append(m):
                slot["appends"].append((i, _deps.mop.value(m)))
            elif _deps.mop.is_write(m):
                slot["writes"].append((i, _deps.mop.value(m)))
            else:
                slot["reads"].append((i, _deps.mop.value(m)))
            self._dirty.add(k)

    def extend(self, ops) -> None:
        for op in ops:
            self.append(op)

    def advance(self) -> dict:
        """Classify the current prefix; returns (and stores in
        ``.verdict``) the batch-identical result dict."""
        self.checked = len(self.ops)
        c = self.checker
        if c.realtime:
            # realtime edges are dense over ALL node pairs — no
            # incremental structure helps; defer to the batch extract
            try:
                g = c.graph(self.ops, key=self.history_key)
            except IllegalInference as e:
                self.verdict = {"valid": "unknown", "error": e.info}
                return self.verdict
        else:
            g = self._graph()
            if g is None:
                self.verdict = {"valid": "unknown",
                                "error": self._first_error()}
                return self.verdict
        r = classify(g, c.anomalies, realtime=c.realtime, engine=c.engine,
                     max_witnesses=c.max_witnesses, journal=self.memo)
        self.verdict = {"valid": not r["anomaly-types"], **r}
        return self.verdict

    # -- incremental graph maintenance ------------------------------------

    def _key_edges(self, k) -> dict:
        """Recompute one key's edge lists through the batch inference
        functions (deps._append_key_edges / _register_key_edges)."""
        c = self.checker
        slot = self._slots[k]
        edges: dict = {r: [] for r in _deps.RELATIONS}

        def add(rel, i, j):
            # mirrors extract()'s add: drop _INIT endpoints, self-loops
            if i is not _deps._INIT and j is not _deps._INIT and i != j:
                edges[rel].append((i, j))

        try:
            reads_lists = any(isinstance(v, (list, tuple))
                              for _, v in slot["reads"])
            if slot["appends"] or reads_lists:
                if slot["writes"]:
                    raise IllegalInference(
                        f"key {k!r} saw both append/list-read and write "
                        f"micro-ops", key=k)
                _deps._append_key_edges(k, slot["appends"], slot["reads"],
                                        add)
            elif slot["writes"] or slot["reads"]:
                _deps._register_key_edges(
                    k, slot["writes"], slot["reads"], add,
                    version_order=c.version_order,
                    init_values=c.init_values)
        except IllegalInference as e:
            return {"error": e.info}
        return edges

    def _graph(self) -> DepGraph | None:
        """The prefix's dependency graph, recomputing edges only for
        dirty keys; None when any key's inference fails (the prefix is
        uncheckable, matching ``extract`` raising)."""
        for k in self._dirty:
            self._edges[k] = self._key_edges(k)
        self._dirty.clear()
        if any("error" in e for e in self._edges.values()):
            return None
        n = len(self._nodes)
        adj = {r: np.zeros((n, n), dtype=bool) for r in _deps.RELATIONS}
        for e in self._edges.values():
            for rel, ij in e.items():
                for i, j in ij:
                    adj[rel][i, j] = True
        return DepGraph(ops=list(self._nodes), adj=adj)

    def _first_error(self) -> dict:
        """The error the batch extract would raise: its per_key loop
        runs in first-touch key order and raises at the first failing
        key, so pick that key's error."""
        for k in self._key_order:
            e = self._edges.get(k)
            if e is not None and "error" in e:
                return e["error"]
        raise AssertionError("no key error recorded")
