"""The `jepsen-tpu watch` runner: point a streaming checker at a WAL
or foreign trace.

Wires the pieces end to end: trace ingest (ingest.iter_trace) →
workload rehydration + checker (the serve registry's workload table,
so watch verdicts are the same computation the daemon and the one-shot
CLI produce) → frontier (stream.frontier_for) → StreamSession with an
optional state dir holding the crash-safe verdict log and the closure/
per-key memo journal. Each new verdict prints as one JSON line; the
exit code is 1 iff the final verdict is a definite falsification
(unknown passes, as in the test subcommand)."""

from __future__ import annotations

import json
import logging
import os
import signal
import threading

from ..serve.registry import WORKLOAD_FACTORIES
from . import ingest
from .stream import (MEMO_JOURNAL_FILE, VERDICT_LOG_FILE, StreamSession,
                     VerdictLog, frontier_for)

log = logging.getLogger("jepsen_tpu.online.watch")

__all__ = ["run_watch"]


def _emit_record(rec) -> None:
    v = rec.get("verdict") or {}
    out = {"prefix": rec["prefix"], "digest": rec["digest"],
           "valid": v.get("valid")}
    for k in ("anomaly-types", "failures", "error"):
        if v.get(k):
            out[k] = v[k]
    print(json.dumps(out, default=str), flush=True)


def run_watch(opts: dict) -> int:
    trace = opts["trace"]
    workload_name = opts.get("workload") or "cycle"
    factory = WORKLOAD_FACTORIES.get(workload_name)
    if factory is None:
        raise ValueError(f"unknown workload {workload_name!r} "
                         f"(known: {sorted(WORKLOAD_FACTORIES)})")
    spec = factory()
    rehydrate = spec.get("rehydrate")

    journal = None
    verdict_log = None
    state_dir = opts.get("state_dir")
    if state_dir:
        from .. import store

        os.makedirs(state_dir, exist_ok=True)
        journal = store.AnalysisJournal(
            None, path=os.path.join(state_dir, MEMO_JOURNAL_FILE))
        verdict_log = VerdictLog(os.path.join(state_dir, VERDICT_LOG_FILE))

    deadline_ms = opts.get("deadline_ms")
    frontier = frontier_for(
        spec["checker"], test={"name": "watch"}, journal=journal,
        window_budget_s=(max(1, int(deadline_ms)) / 1000.0
                         if deadline_ms is not None else None))
    if frontier is None:
        raise ValueError(
            f"workload {workload_name!r} has no streaming frontier")

    stop = threading.Event()
    try:  # graceful stop: first SIGTERM ends the tail, verdicts stay
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
    except ValueError:  # not the main thread (tests drive run_watch)
        pass

    source = ingest.iter_trace(
        trace, follow=bool(opts.get("follow")),
        poll_s=opts.get("poll") or 0.05, stop=stop)
    if rehydrate is not None:
        source = (rehydrate(o) for o in source)

    session = StreamSession(
        source, frontier, window=opts.get("window") or 256,
        verdict_log=verdict_log, emit=_emit_record,
        abort_on_invalid=bool(opts.get("abort_on_invalid")),
        max_ops=opts.get("max_ops"))
    try:
        final = session.run()
    except KeyboardInterrupt:
        stop.set()
        final = session.last_verdict
    finally:
        if journal is not None:
            journal.close()
        if verdict_log is not None:
            verdict_log.close()
    if session.aborted and session.abort_info:
        log.warning("watch: stream falsified at prefix %d (%s)",
                    session.abort_info["prefix"],
                    ", ".join(session.abort_info["anomaly-types"]) or "?")
    return 1 if (isinstance(final, dict)
                 and final.get("valid") is False) else 0
