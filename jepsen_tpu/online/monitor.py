"""In-run online monitoring: stream the live history through a
frontier and abort doomed runs early.

``core.run_case`` starts a ``RunMonitor`` when the test map carries an
``online`` entry (True, or an options dict). The monitor thread polls
the in-memory history (the same list ``core.conj_op`` appends to,
under its lock), feeds the frontier matching the test's checker
(``stream.frontier_for``), and advances every ``window`` new ops. On
a definite ``valid: False`` it records the abort under
``test["_online_abort"]`` and sets ``test["_drain"]`` — the exact
generator gate the SIGTERM drain path uses (core.DrainSignal) — so
workers finish their in-flight ops and the run winds down cleanly
through the normal recovery phases, with the batch analysis still run
over everything that happened. ``core.analyze`` surfaces the abort as
``results["online-abort"]``.

The monitor is strictly advisory: any exception disables it (logged),
never the run, and its verdicts never substitute for the batch
analysis — early abort changes WHEN the run stops, not what the
checker concludes about the ops that ran.
"""

from __future__ import annotations

import logging
import threading

from .stream import frontier_for

log = logging.getLogger("jepsen_tpu.online.monitor")

__all__ = ["RunMonitor"]

DEFAULT_WINDOW = 128


class RunMonitor:
    """Poll a live test's history through a streaming frontier."""

    def __init__(self, test, *, window: int | None = None,
                 poll_s: float = 0.05):
        cfg = test.get("online")
        cfg = cfg if isinstance(cfg, dict) else {}
        self.test = test
        self.window = int(window or cfg.get("window") or DEFAULT_WINDOW)
        self.poll_s = float(cfg.get("poll_s") or poll_s)
        self.frontier = frontier_for(test.get("checker"), test=test)
        self.aborted = False
        self.abort_info: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def supported(self) -> bool:
        return self.frontier is not None

    def start(self) -> "RunMonitor":
        if not self.supported:
            log.info("online monitor: checker %s has no streaming "
                     "frontier; monitoring disabled",
                     type(self.test.get("checker")).__name__)
            return self
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="jepsen online monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- internals ---------------------------------------------------------

    def _snapshot(self, seen: int) -> list:
        hist = self.test.get("_history")
        lock = self.test.get("_history_lock")
        if hist is None or lock is None:
            return []
        with lock:
            return list(hist[seen:])

    def _loop(self) -> None:
        seen = 0
        try:
            while not self._stop.is_set():
                new = self._snapshot(seen)
                seen += len(new)
                self.frontier.extend(new)
                if self.frontier.pending >= self.window:
                    if self._advance():
                        return
                else:
                    self._stop.wait(self.poll_s)
            # final look on shutdown: one last advance over whatever
            # arrived, so short runs still get a streamed verdict
            new = self._snapshot(seen)
            self.frontier.extend(new)
            if self.frontier.pending:
                self._advance()
        except Exception:  # noqa: BLE001 — advisory, never kills the run
            log.warning("online monitor died; run continues unmonitored",
                        exc_info=True)

    def _advance(self) -> bool:
        """One frontier advance; True when the run was aborted."""
        v = self.frontier.advance()
        if not (isinstance(v, dict) and v.get("valid") is False):
            return False
        self.aborted = True
        self.abort_info = {
            "op-count": int(self.frontier.checked),
            "anomaly-types":
                v.get("anomaly-types")
                or sorted(map(str, v.get("failures") or [])),
        }
        self.test["_online_abort"] = self.abort_info
        log.warning("online monitor: anomaly at op %d (%s); draining run",
                    self.abort_info["op-count"],
                    ", ".join(self.abort_info["anomaly-types"]) or "?")
        drain = self.test.get("_drain")
        if drain is not None:
            self.test["_preempted_by_monitor"] = True
            drain.set()
        return True
