"""Foreign trace ingest: map external history formats onto the WAL op
schema so traces from anywhere stream through the same checkers.

Two adapters (plus the native WAL):

* **Jepsen EDN histories** — the reference checker's on-disk format: a
  vector (or stream) of op maps, ``{:type :invoke, :f :txn, :value
  [[:append 9 1]], :process 0, :time ..., :index ...}``, possibly
  tagged ``#jepsen.history.Op{...}``. A small self-contained EDN
  reader handles the subset real histories use (nil/booleans/numbers/
  strings/keywords/symbols/vectors/lists/sets/maps/tagged literals/
  comments); keywords become plain strings, which lands ``:append`` /
  ``:r`` / ``:w`` exactly on this repo's ``txn`` micro-op constants
  and ``:invoke``/``:ok``/... on its op types.

* **OTLP-ish span-log JSONL** — one span per line with
  ``startTimeUnixNano``/``endTimeUnixNano``, a ``status.code``, and
  ``jepsen.*`` attributes (either OTLP's ``[{"key", "value":
  {"intValue": ...}}]`` list shape or a plain dict). Each span becomes
  an invoke at its start and a completion at its end (OK → ok, ERROR →
  fail, otherwise info), interleaved across spans by timestamp — trace
  validation of unmodified systems in the OmniLink spirit.

``iter_trace`` sniffs the format and yields ``history.Op`` records
reindexed 0..n-1, exactly as ``store.load_wal_history`` would index a
native WAL; ``--follow`` tailing is only meaningful for the native WAL
(foreign trace files are complete artifacts).
"""

from __future__ import annotations

import json
import logging
import os

from ..history import Op

log = logging.getLogger("jepsen_tpu.online.ingest")

__all__ = ["EDNError", "read_edn", "read_edn_all", "edn_ops", "span_ops",
           "detect_format", "iter_trace"]


# ---------------------------------------------------------------------------
# EDN reader

class EDNError(ValueError):
    """Malformed EDN input."""


_DELIMS = {"(": ")", "[": "]", "{": "}"}
_WS = " \t\n\r\f\v,"


class _EDNReader:
    def __init__(self, text: str):
        self.s = text
        self.i = 0
        self.n = len(text)

    def _skip_ws(self) -> None:
        while self.i < self.n:
            c = self.s[self.i]
            if c in _WS:
                self.i += 1
            elif c == ";":  # comment to end of line
                while self.i < self.n and self.s[self.i] != "\n":
                    self.i += 1
            else:
                return

    def at_end(self) -> bool:
        self._skip_ws()
        return self.i >= self.n

    def read(self):
        self._skip_ws()
        if self.i >= self.n:
            raise EDNError("unexpected end of input")
        c = self.s[self.i]
        if c in _DELIMS:
            return self._read_coll(c)
        if c == "}" or c == ")" or c == "]":
            raise EDNError(f"unexpected {c!r} at {self.i}")
        if c == '"':
            return self._read_string()
        if c == ":":
            self.i += 1
            return self._read_symbol_token()
        if c == "\\":
            return self._read_char()
        if c == "#":
            return self._read_dispatch()
        if c == "^":  # metadata: read and discard, return the value
            self.i += 1
            self.read()
            return self.read()
        return self._read_atom()

    def _read_coll(self, opener: str):
        closer = _DELIMS[opener]
        self.i += 1
        items = []
        while True:
            self._skip_ws()
            if self.i >= self.n:
                raise EDNError(f"unclosed {opener!r}")
            if self.s[self.i] == closer:
                self.i += 1
                break
            items.append(self.read())
        if opener == "{":
            if len(items) % 2:
                raise EDNError("map literal with odd number of forms")
            out = {}
            for k, v in zip(items[::2], items[1::2]):
                out[_freeze(k)] = v
            return out
        return items

    def _read_dispatch(self):
        self.i += 1
        if self.i < self.n and self.s[self.i] == "{":  # set
            return self._read_set()
        if self.i < self.n and self.s[self.i] == "_":  # discard form
            self.i += 1
            self.read()
            return self.read()
        # tagged literal: #inst "...", #jepsen.history.Op{...} — the
        # tag is dropped, the wrapped form is the value
        self._read_symbol_token()
        return self.read()

    def _read_set(self):
        items = []
        self.i += 1
        while True:
            self._skip_ws()
            if self.i >= self.n:
                raise EDNError("unclosed set literal")
            if self.s[self.i] == "}":
                self.i += 1
                return items
            items.append(self.read())

    def _read_string(self) -> str:
        self.i += 1
        out = []
        while self.i < self.n:
            c = self.s[self.i]
            if c == '"':
                self.i += 1
                return "".join(out)
            if c == "\\":
                self.i += 1
                if self.i >= self.n:
                    break
                e = self.s[self.i]
                out.append({"n": "\n", "t": "\t", "r": "\r",
                            '"': '"', "\\": "\\"}.get(e, e))
            else:
                out.append(c)
            self.i += 1
        raise EDNError("unclosed string")

    def _read_char(self) -> str:
        self.i += 1
        start = self.i
        while (self.i < self.n and self.s[self.i] not in _WS
               and self.s[self.i] not in "()[]{}\";"):
            self.i += 1
        name = self.s[start:self.i]
        return {"newline": "\n", "space": " ", "tab": "\t",
                "return": "\r"}.get(name, name[:1])

    def _read_symbol_token(self) -> str:
        start = self.i
        while (self.i < self.n and self.s[self.i] not in _WS
               and self.s[self.i] not in "()[]{}\";"):
            self.i += 1
        if self.i == start:
            raise EDNError(f"empty token at {start}")
        return self.s[start:self.i]

    def _read_atom(self):
        tok = self._read_symbol_token()
        if tok == "nil":
            return None
        if tok == "true":
            return True
        if tok == "false":
            return False
        try:
            return int(tok.rstrip("N"))
        except ValueError:
            pass
        try:
            return float(tok.rstrip("M"))
        except ValueError:
            pass
        return tok  # bare symbol


def _freeze(v):
    """Map keys must hash: EDN collection keys become tuples."""
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


def read_edn(text: str):
    """The first EDN form in ``text``."""
    return _EDNReader(text).read()


def read_edn_all(text: str) -> list:
    """Every top-level EDN form in ``text``."""
    r = _EDNReader(text)
    out = []
    while not r.at_end():
        out.append(r.read())
    return out


#: the op-map keys that survive into the WAL schema
_OP_KEYS = ("process", "type", "f", "value", "time", "index", "error")


def edn_ops(text: str) -> list[dict]:
    """A Jepsen EDN history as WAL-schema op dicts, in file order. The
    history may be one enclosing vector of op maps or a stream of
    top-level maps (one per line)."""
    forms = read_edn_all(text)
    if len(forms) == 1 and isinstance(forms[0], list):
        forms = forms[0]
    out = []
    for m in forms:
        if not isinstance(m, dict):
            raise EDNError(f"expected an op map, got {type(m).__name__}")
        out.append({k: m[k] for k in _OP_KEYS if m.get(k) is not None})
    return out


# ---------------------------------------------------------------------------
# OTLP-ish span logs

_STATUS_TYPES = {
    "STATUS_CODE_OK": "ok",
    "OK": "ok",
    "STATUS_CODE_ERROR": "fail",
    "ERROR": "fail",
}


def _span_attrs(span: dict) -> dict:
    """Span attributes as a flat dict, accepting both OTLP's
    ``[{"key", "value": {"intValue": ...}}]`` list shape and a plain
    mapping."""
    raw = span.get("attributes") or {}
    if isinstance(raw, dict):
        return dict(raw)
    out = {}
    for a in raw:
        v = a.get("value")
        if isinstance(v, dict):  # {"intValue": "3"} / {"stringValue": ..}
            for kind, x in v.items():
                v = int(x) if kind == "intValue" else x
                break
        out[a.get("key")] = v
    return out


def _attr_value(attrs: dict, key: str):
    """A jepsen.* attribute, JSON-decoding string payloads (span
    exporters stringify structured values)."""
    v = attrs.get(key)
    if isinstance(v, str):
        try:
            return json.loads(v)
        except ValueError:
            return v
    return v


def span_ops(lines) -> list[dict]:
    """An OTLP-ish span-log (an iterable of JSONL lines) as WAL-schema
    op dicts: every span contributes an invoke at its start and a
    completion at its end, ordered by timestamp (ties: completions
    after invocations, then span arrival order)."""
    events = []  # (time, phase, arrival, op-dict)
    for arrival, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            span = json.loads(line)
        except ValueError:
            log.warning("span log: dropping unparseable line %r", line[:80])
            continue
        attrs = _span_attrs(span)
        process = _attr_value(attrs, "jepsen.process")
        if process is None:
            process = span.get("spanId") or arrival
        f = _attr_value(attrs, "jepsen.f") or span.get("name")
        value = _attr_value(attrs, "jepsen.value")
        t0 = int(span.get("startTimeUnixNano") or 0)
        t1 = int(span.get("endTimeUnixNano") or t0)
        status = ((span.get("status") or {}).get("code")
                  or span.get("statusCode") or "")
        ctype = _STATUS_TYPES.get(str(status).upper(), "info")
        completion_value = _attr_value(attrs, "jepsen.value.ok")
        if completion_value is None:
            completion_value = value
        events.append((t0, 0, arrival, {
            "process": process, "type": "invoke", "f": f,
            "value": value, "time": t0}))
        completion = {"process": process, "type": ctype, "f": f,
                      "value": completion_value, "time": t1}
        err = _attr_value(attrs, "jepsen.error")
        if err is not None:
            completion["error"] = err
        events.append((t1, 1, arrival, completion))
    events.sort(key=lambda e: e[:3])
    return [e[3] for e in events]


# ---------------------------------------------------------------------------
# Format sniffing + the unified trace iterator

def detect_format(path: str) -> str:
    """"wal", "edn", or "spans", by extension then first-record
    shape."""
    if path.endswith(".edn"):
        return "edn"
    first = ""
    try:
        with open(path) as f:
            for line in f:
                if line.strip():
                    first = line.strip()
                    break
    except OSError:
        pass
    if first:
        try:
            rec = json.loads(first)
        except ValueError:
            return "edn"
        if isinstance(rec, dict):
            if "startTimeUnixNano" in rec or "spanId" in rec \
                    or "attributes" in rec:
                return "spans"
            if "type" in rec and "process" in rec:
                return "wal"
    return "wal"


def iter_trace(path: str, *, follow: bool = False, poll_s: float = 0.05,
               stop=None, fmt: str | None = None):
    """Yield ``Op`` records from a WAL file or foreign trace, indexed
    0..n-1 — the shape every batch checker and frontier consumes.
    ``follow`` tails native WALs; foreign formats are read whole (a
    follow request on them degrades to the batch read with a
    warning)."""
    fmt = fmt or detect_format(path)
    if fmt == "wal":
        from .. import store

        yield from store.follow_wal(path, follow=follow, poll_s=poll_s,
                                    stop=stop)
        return
    if follow:
        log.warning("--follow is only meaningful for native WALs; "
                    "reading %s trace %s whole", fmt, path)
    if fmt == "edn":
        with open(path) as f:
            dicts = edn_ops(f.read())
    elif fmt == "spans":
        with open(path) as f:
            dicts = span_ops(f)
    else:
        raise ValueError(f"unknown trace format {fmt!r}")
    for i, d in enumerate(dicts):
        yield Op.from_dict(dict(d)).with_(index=i)


def trace_exists(path: str) -> bool:
    return os.path.exists(path)
