"""Online streaming checker: verdicts during the run, on traces from
anywhere.

Every other checker in this repo is batch-only — verdicts arrive after
the run ends, even though the WAL streams every op durably as it lands
and the analysis journal already memoizes per-key/per-component
results. This package closes that loop (ROADMAP item 3):

frontier.py  incremental transactional cycle checking: per-key edge
             maintenance under appended ops, with only dirty
             weakly-connected components re-squared (classify's
             content-hash closure memo); verdicts bit-identical to
             CycleChecker.check on every prefix.
wgl.py       windowed per-key streaming advance of the independent
             linearizable (WGL) checker: dirty keys re-check in one
             packed check_batch window, verdicts recombine through
             independent.combine_results.
ingest.py    foreign trace adapters — Jepsen EDN histories and
             OTLP-ish span-log JSONL — mapped onto the WAL op schema.
stream.py    the StreamSession: deterministic window boundaries, a
             crash-safe fsync'd verdict log (SIGKILL/resume emits each
             verdict exactly once), bounded lag, early abort.
monitor.py   in-run monitoring: core.run_case streams the live
             history and drains doomed runs via the test["_drain"]
             gate the SIGTERM path already honors.
client.py    a WAL stream as a serve-queue client: window snapshots
             submitted to the resident daemon, packed across
             concurrent streams by independent.pack_check.
watch.py     the `jepsen-tpu watch <wal-or-trace> [--follow]` CLI.
"""

from .client import QueueStreamClient  # noqa: F401
from .frontier import ClosureMemo, CycleFrontier  # noqa: F401
from .ingest import edn_ops, iter_trace, read_edn, span_ops  # noqa: F401
from .stream import (StreamSession, VerdictLog,  # noqa: F401
                     frontier_for)
from .wgl import WGLFrontier  # noqa: F401
