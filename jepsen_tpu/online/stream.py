"""The streaming verdict session: source → frontier → durable verdict
log.

A ``StreamSession`` pulls ops from any iterator (a tailed WAL, a
foreign trace, an in-memory history), feeds a frontier (CycleFrontier
or WGLFrontier), and advances it at deterministic prefix boundaries —
every ``window`` ops and once at stream end — so the set of checked
prefixes is a pure function of the stream, never of timing. Each
advance emits a verdict record ``{"prefix", "digest", "verdict"}``.

Crash safety is the WAL discipline turned on the checker itself: every
emission is appended (flushed + fsync'd) to a ``VerdictLog`` BEFORE
the emit callback fires, keyed by (prefix length, content digest of
the prefix). A SIGKILL'd session that resumes over the same stream
re-derives the same boundaries, finds the already-logged prefixes, and
skips both the re-check and the re-emission — no duplicated verdicts,
no missed ones, and the final verdict is bit-identical to an
uninterrupted run (advances are pure functions of the prefix).

Bounded lag: between advances the frontier only buffers, so verdict
lag is bounded by the window size (plus one advance's compute). The
early-abort contract: a definite ``valid: False`` sets ``.aborted``
and (with ``abort_on_invalid``) stops consuming — for both anomaly
flavors checked here, invalidity of a prefix is monotone (a dependency
cycle never un-happens; an unlinearizable completed prefix stays
unlinearizable under extension), so aborting early never contradicts
the full-history verdict.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os

log = logging.getLogger("jepsen_tpu.online.stream")

__all__ = ["VerdictLog", "StreamSession", "frontier_for"]

VERDICT_LOG_FILE = "verdicts.jsonl"
MEMO_JOURNAL_FILE = "analysis.ckpt.jsonl"


def _op_digest_update(h, o) -> None:
    """Fold one op's verdict-relevant identity into a running digest —
    the same field set independent._journal_key hashes."""
    h.update(repr((o.process, o.type, o.f, o.value,
                   o.index, o.error)).encode())


class VerdictLog:
    """Append-only JSONL ledger of emitted streaming verdicts.

    Each line is ``{"prefix": n, "digest": d, "verdict": ...}``;
    loading tolerates a torn tail (the store JSONL discipline), and
    ``record`` fsyncs before returning so an acknowledged emission
    survives any kill. Duplicate records are dropped on both write and
    load — the (prefix, digest) pair is the emission's identity."""

    def __init__(self, path: str):
        from ..store import _terminate_torn_tail

        self._path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._seen: dict = {}
        try:
            with open(path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        rec = json.loads(line)
                        self._seen[(int(rec["prefix"]), rec["digest"])] = \
                            rec.get("verdict")
                    except (ValueError, KeyError, TypeError):
                        log.warning("verdict log: dropping torn line %r",
                                    line[:80])
        except FileNotFoundError:
            pass
        self._f = open(path, "a")
        _terminate_torn_tail(self._f, path)

    @property
    def path(self) -> str:
        return self._path

    def __len__(self) -> int:
        return len(self._seen)

    def contains(self, prefix: int, digest: str) -> bool:
        return (prefix, digest) in self._seen

    def get(self, prefix: int, digest: str):
        return self._seen.get((prefix, digest))

    def record(self, prefix: int, digest: str, verdict) -> bool:
        """Append one emission; returns False (and writes nothing) for
        a duplicate."""
        from ..store import _json_default, _json_keys

        if (prefix, digest) in self._seen:
            return False
        self._seen[(prefix, digest)] = verdict
        self._f.write(json.dumps(
            {"prefix": prefix, "digest": digest,
             "verdict": _json_keys(verdict)}, default=_json_default))
        self._f.write("\n")
        self._f.flush()
        os.fsync(self._f.fileno())
        return True

    def entries(self) -> list:
        """[(prefix, digest, verdict)] sorted by prefix."""
        return sorted((p, d, v) for (p, d), v in self._seen.items())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


class StreamSession:
    """Drive one frontier over one op stream.

    source            an iterator of Ops (store.follow_wal,
                      ingest.iter_trace, or any history)
    frontier          CycleFrontier / WGLFrontier (anything with
                      append/advance/.verdict)
    window            advance every `window` ops (and at stream end)
    verdict_log       optional VerdictLog for crash-safe emission
    emit              optional callback(record) per NEW emission
    abort_on_invalid  stop consuming at the first definite False
    max_ops           stop after this many ops (deterministic end for
                      follow-mode tests/benches)
    """

    def __init__(self, source, frontier, *, window: int = 256,
                 verdict_log: VerdictLog | None = None, emit=None,
                 abort_on_invalid: bool = False, max_ops=None):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.source = source
        self.frontier = frontier
        self.window = window
        self.verdict_log = verdict_log
        self.emit = emit
        self.abort_on_invalid = abort_on_invalid
        self.max_ops = max_ops
        self.aborted = False
        self.abort_info: dict | None = None
        self.consumed = 0
        self.last_verdict: dict | None = None
        self._digest = hashlib.sha1()

    def run(self):
        """Consume the stream; returns the final verdict (the one for
        the longest checked prefix)."""
        n = 0
        for op in self.source:
            self.frontier.append(op)
            _op_digest_update(self._digest, self.frontier.ops[-1])
            n += 1
            if n % self.window == 0:
                self._checkpoint(n)
                if self.aborted and self.abort_on_invalid:
                    break
            if self.max_ops is not None and n >= self.max_ops:
                break
        self.consumed = n
        if n and n % self.window and not (self.aborted
                                          and self.abort_on_invalid):
            self._checkpoint(n)
        return self.last_verdict

    def _checkpoint(self, n: int) -> None:
        digest = self._digest.hexdigest()[:16]
        verdict = None
        if self.verdict_log is not None:
            verdict = self.verdict_log.get(n, digest)
        replayed = verdict is not None
        if not replayed:
            verdict = self.frontier.advance()
        self.last_verdict = verdict
        rec = {"prefix": n, "digest": digest, "verdict": verdict}
        if not replayed:
            if self.verdict_log is not None:
                self.verdict_log.record(n, digest, verdict)
            if self.emit is not None:
                self.emit(rec)
        if isinstance(verdict, dict) and verdict.get("valid") is False:
            self.aborted = True
            if self.abort_info is None:
                self.abort_info = {
                    "prefix": n,
                    "anomaly-types":
                        verdict.get("anomaly-types")
                        or sorted(map(str, verdict.get("failures") or [])),
                }


def frontier_for(checker, *, test=None, journal=None,
                 window_budget_s=None):
    """The streaming frontier matching a batch checker, or None when
    the checker has no streaming form. Dispatch mirrors the batch
    composition: a CycleChecker streams through the incremental cycle
    frontier; an IndependentChecker streams through the windowed
    per-key frontier (whatever its sub-checker — P-compositionality is
    the licence, not the sub-checker's type). ``window_budget_s``
    bounds each WGL advance's wall clock (unsupported frontiers ignore
    it): past the budget the advance commits ``unknown: deadline`` for
    the keys that didn't fit instead of stalling the stream."""
    from ..checker.cycle import CycleChecker
    from ..independent import IndependentChecker
    from .frontier import CycleFrontier
    from .wgl import WGLFrontier

    if isinstance(checker, CycleChecker):
        return CycleFrontier(checker, journal=journal)
    if isinstance(checker, IndependentChecker):
        return WGLFrontier(checker, test=test, journal=journal,
                           window_budget_s=window_budget_s)
    return None
