"""A WAL stream as a serve-queue client.

The resident daemon's queue (serve/queue.py) doesn't care where a
history came from — so a live WAL (or a foreign trace) can act as just
another client: ``QueueStreamClient`` follows a stream and submits a
prefix snapshot every ``window`` ops. Each submission is a complete,
independently-checkable history (the daemon is stateless per job), and
because the daemon packs every batch through
``independent.pack_check``, window lanes from MANY concurrent streams
ride the same device launches — cross-stream packing for free, with
each stream's verdicts still bit-identical to one-shot checks
(P-compositionality).
"""

from __future__ import annotations

import logging
import random
import time

from ..history import Op

log = logging.getLogger("jepsen_tpu.online.client")

__all__ = ["QueueStreamClient"]


class QueueStreamClient:
    """Submit prefix snapshots of an op stream to a DurableQueue.

    queue     a serve.DurableQueue (or anything with its submit())
    client    the client id submissions are attributed (and weighted)
              under
    workload  the daemon workload name that rehydrates + checks the
              ops ("register", "cycle", ...)
    window    ops per submission boundary
    weight    the client's weighted-round-robin share
    backoff_base_s / backoff_cap_s / seed
              QueueFull handling: a full queue mid-stream is
              backpressure, not an error — submission retries under
              capped exponential backoff with seeded jitter, never
              sleeping less than the queue's retry_after_s hint.
    """

    def __init__(self, queue, client: str, workload: str = "register", *,
                 window: int = 256, weight: int = 1,
                 backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 30.0, seed: int = 0):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.queue = queue
        self.client = str(client)
        self.workload = workload
        self.window = window
        self.weight = weight
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.job_ids: list = []
        self.consumed = 0
        self.backoffs = 0  # QueueFull rejections absorbed
        self._rng = random.Random(seed)

    def submit_prefix(self, ops) -> str:
        """Submit one snapshot; returns its durable job id. A full
        queue is absorbed here: retry under capped expo backoff
        (honoring the daemon's retry_after_s hint, jittered UP so a
        fleet of streams doesn't re-converge on the same instant)
        rather than surfacing QueueFull mid-stream."""
        from ..serve.queue import QueueFull

        history = [o.to_dict() if isinstance(o, Op) else dict(o)
                   for o in ops]
        attempt = 0
        while True:
            try:
                job_id = self.queue.submit(self.client, self.workload,
                                           history, weight=self.weight)
                break
            except QueueFull as e:
                delay = min(self.backoff_cap_s,
                            max(e.retry_after_s,
                                self.backoff_base_s * (2 ** attempt)))
                delay *= 1.0 + 0.5 * self._rng.random()  # [1.0, 1.5)
                self.backoffs += 1
                attempt += 1
                log.warning("queue full (%d pending); stream %s "
                            "backing off %.2fs (attempt %d)",
                            e.pending, self.client, delay, attempt)
                time.sleep(delay)
        self.job_ids.append(job_id)
        return job_id

    def stream(self, source, *, max_ops=None) -> list:
        """Consume a stream, submitting at every window boundary and
        once at stream end; returns the submitted job ids in order.
        The LAST id's verdict is the stream's final verdict."""
        buf: list = []
        n = 0
        for op in source:
            buf.append(op)
            n += 1
            if n % self.window == 0:
                self.submit_prefix(buf)
            if max_ops is not None and n >= max_ops:
                break
        if n % self.window:
            self.submit_prefix(buf)
        self.consumed = n
        return self.job_ids

    def final_verdict(self, timeout: float | None = None):
        """Block for the last submission's verdict."""
        if not self.job_ids:
            return None
        return self.queue.wait_for_verdict(self.job_ids[-1],
                                           timeout=timeout)
