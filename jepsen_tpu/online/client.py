"""A WAL stream as a serve-queue client.

The resident daemon's queue (serve/queue.py) doesn't care where a
history came from — so a live WAL (or a foreign trace) can act as just
another client: ``QueueStreamClient`` follows a stream and submits a
prefix snapshot every ``window`` ops. Each submission is a complete,
independently-checkable history (the daemon is stateless per job), and
because the daemon packs every batch through
``independent.pack_check``, window lanes from MANY concurrent streams
ride the same device launches — cross-stream packing for free, with
each stream's verdicts still bit-identical to one-shot checks
(P-compositionality).
"""

from __future__ import annotations

import logging

from ..history import Op

log = logging.getLogger("jepsen_tpu.online.client")

__all__ = ["QueueStreamClient"]


class QueueStreamClient:
    """Submit prefix snapshots of an op stream to a DurableQueue.

    queue     a serve.DurableQueue (or anything with its submit())
    client    the client id submissions are attributed (and weighted)
              under
    workload  the daemon workload name that rehydrates + checks the
              ops ("register", "cycle", ...)
    window    ops per submission boundary
    weight    the client's weighted-round-robin share
    """

    def __init__(self, queue, client: str, workload: str = "register", *,
                 window: int = 256, weight: int = 1):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.queue = queue
        self.client = str(client)
        self.workload = workload
        self.window = window
        self.weight = weight
        self.job_ids: list = []
        self.consumed = 0

    def submit_prefix(self, ops) -> str:
        """Submit one snapshot; returns its durable job id."""
        history = [o.to_dict() if isinstance(o, Op) else dict(o)
                   for o in ops]
        job_id = self.queue.submit(self.client, self.workload, history,
                                   weight=self.weight)
        self.job_ids.append(job_id)
        return job_id

    def stream(self, source, *, max_ops=None) -> list:
        """Consume a stream, submitting at every window boundary and
        once at stream end; returns the submitted job ids in order.
        The LAST id's verdict is the stream's final verdict."""
        buf: list = []
        n = 0
        for op in source:
            buf.append(op)
            n += 1
            if n % self.window == 0:
                self.submit_prefix(buf)
            if max_ops is not None and n >= max_ops:
                break
        if n % self.window:
            self.submit_prefix(buf)
        self.consumed = n
        return self.job_ids

    def final_verdict(self, timeout: float | None = None):
        """Block for the last submission's verdict."""
        if not self.job_ids:
            return None
        return self.queue.wait_for_verdict(self.job_ids[-1],
                                           timeout=timeout)
