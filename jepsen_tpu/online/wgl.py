"""Windowed WGL frontier: per-independent-key streaming advance of the
linearizable checker.

The frontier ingests a keyed (KVTuple-valued) history op by op and, on
each ``advance``, re-checks ONLY the keys whose subhistory actually
changed since their last verdict — every dirty key's subhistory goes
through the wrapped sub-checker in one ``check_batch`` call (the same
cross-key window packing ``independent.IndependentChecker`` and the
serve daemon's ``pack_check`` use), and the per-key verdicts recombine
through ``independent.combine_results``, THE recombination. Unchanged
keys keep their memoized verdicts, identified by
``independent._journal_key`` — the exact per-key content identity the
``store.AnalysisJournal`` "independent-key" kind journals — so a
frontier backed by a journal resumes across process kills.

Bit-identity contract: ``advance()`` returns what
``IndependentChecker.check(test, history[:n], {})`` returns for the
same prefix, minus "supervision" telemetry (whose shape legitimately
differs — the streaming path ran fewer, smaller launches) and store
artifacts. P-compositionality licenses the reuse: a key's verdict
depends only on its own subhistory, never on which batch its lane
rode in.
"""

from __future__ import annotations

import logging

from .. import independent as indep
from ..checker import check_safe
from ..history import ops as _ops

log = logging.getLogger("jepsen_tpu.online.wgl")

__all__ = ["WGLFrontier"]


class WGLFrontier:
    """Streaming frontier over one keyed history.

    checker  an ``independent.IndependentChecker`` (e.g. the serve
             registry's register workload: independent over the WGL
             linearizable search); its wrapped sub-checker does the
             per-key work, batched through ``check_batch`` when it has
             one
    test     the test map handed to the sub-checker (model, name, ...)
    journal  optional store.AnalysisJournal to write per-key verdicts
             through to ("independent-key" kind, resume support)
    window_budget_s
             optional wall-clock budget per ``advance``: each check
             runs with ``test["deadline"]`` stamped that far in the
             future, so the supervisor salvages what fit and fills the
             rest with ``unknown: deadline`` instead of letting one
             slow window stall the whole stream
    """

    def __init__(self, checker: indep.IndependentChecker, *, test=None,
                 journal=None, window_budget_s: float | None = None):
        if not isinstance(checker, indep.IndependentChecker):
            raise TypeError(
                f"WGLFrontier wants an IndependentChecker, got "
                f"{type(checker).__name__}")
        self.checker = checker
        self.test = test or {}
        self.journal = journal
        self.window_budget_s = window_budget_s
        self.ops: list = []
        self._keys: set = set()
        self._dirty: set = set()
        self._global_dirty = False  # a non-tuple op joins EVERY subhistory
        self._verdicts: dict = {}   # key -> verdict for its current sub
        self._jkeys: dict = {}      # key -> _journal_key of that verdict
        self.checked = 0
        self.verdict: dict | None = None

    def __len__(self) -> int:
        return len(self.ops)

    @property
    def pending(self) -> int:
        return len(self.ops) - self.checked

    def append(self, op) -> None:
        (o,) = _ops([op])
        self.ops.append(o)
        if indep.is_tuple(o.value):
            self._keys.add(o.value.key)
            self._dirty.add(o.value.key)
        else:
            self._global_dirty = True

    def extend(self, ops) -> None:
        for op in ops:
            self.append(op)

    def advance(self) -> dict:
        """Re-check dirty keys, recombine everything, return (and
        store in ``.verdict``) the batch-identical result dict."""
        self.checked = len(self.ops)
        dirty = set(self._keys) if self._global_dirty else set(self._dirty)
        self._dirty.clear()
        self._global_dirty = False

        todo = []  # (key, subhistory, journal key, per-item opts)
        for k in sorted(dirty, key=str):
            sub = indep.subhistory(k, self.ops)
            jk = indep._journal_key(k, sub)
            if self._jkeys.get(k) == jk:
                continue  # marked dirty, but content-identical
            if self.journal is not None:
                r = self.journal.get("independent-key", jk)
                if r is not None:
                    self._verdicts[k], self._jkeys[k] = r, jk
                    continue
            todo.append((k, sub, jk,
                         {"subdirectory": [indep.DIR, str(k)],
                          "history_key": k}))
        if todo:
            for (k, _sub, jk, _o), r in zip(todo, self._check(todo)):
                self._verdicts[k] = r
                if (isinstance(r, dict) and r.get("valid") == "unknown"
                        and r.get("error") == "deadline"):
                    # budget expiry is transient: keep the key dirty
                    # and unmemoized so the next advance retries it
                    self._dirty.add(k)
                    self._jkeys.pop(k, None)
                    continue
                self._jkeys[k] = jk
                if self.journal is not None:
                    self.journal.record("independent-key", jk, r)
        self.verdict = indep.combine_results(dict(self._verdicts))
        return self.verdict

    def _check(self, todo) -> list:
        """One batched pass over the dirty keys' window — the same
        batch-else-per-key structure IndependentChecker.check runs.
        A window budget stamps a fresh absolute deadline per pass."""
        import time as _t

        test = self.test
        if self.window_budget_s is not None:
            test = {**test,
                    "deadline": _t.monotonic() + self.window_budget_s}
        sub_checker = self.checker.checker
        if len(todo) > 1 and hasattr(sub_checker, "check_batch"):
            try:
                return sub_checker.check_batch(
                    test, [(sub, o) for _, sub, _, o in todo])
            except Exception:  # noqa: BLE001 — degrade to per-key path
                log.warning("batched window check failed; falling back "
                            "to per-key", exc_info=True)
        return [check_safe(sub_checker, test, sub, o)
                for _, sub, _, o in todo]
