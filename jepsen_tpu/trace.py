"""Distributed-tracing spans for test clients and nemeses.

The reference's dgraph suite wraps client and nemesis work in
OpenCensus spans exported to a Jaeger collector
(/root/reference/dgraph/src/jepsen/dgraph/trace.clj:1-73: `tracing`
configures a sampler + exporter, `with-trace` opens a scoped span,
`context` exposes span/trace ids, `annotate!`/`attribute!` decorate the
current span). This module is the framework-native equivalent: spans
are plain dicts collected per-thread into a process-global buffer and
exported as JSONL (one span per line, Jaeger-thrift-shaped fields) to
whatever path `tracing` was given — no collector daemon needed, and the
file drops straight into the run's store directory so the web browser
serves it next to jepsen.log.

When tracing is disabled (endpoint None — trace.clj's neverSample
path), `with_trace` still runs its body but records nothing; the
overhead is one thread-local check.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field

_state = threading.local()
_lock = threading.Lock()
_endpoint: str | None = None
_sink = None  # persistent append handle for the JSONL endpoint
# Bounded: the file is the durable record; the in-memory tail exists for
# drain() (tests, post-run analysis) and must not grow with run length.
_buffer: collections.deque = collections.deque(maxlen=4096)
_ids = iter(range(1, 1 << 62))


def sampler(enable) -> bool:
    """Sampling is on iff a tracing endpoint was provided
    (trace.clj:9-14: alwaysSample / neverSample)."""
    return bool(enable)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_us: int
    end_us: int | None = None
    annotations: list = field(default_factory=list)
    attributes: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "traceID": self.trace_id,
            "spanID": self.span_id,
            "parentSpanID": self.parent_id,
            "operationName": self.name,
            "startTime": self.start_us,
            "duration": (self.end_us or self.start_us) - self.start_us,
            "logs": self.annotations,
            "tags": self.attributes,
            "process": {"serviceName": "jepsen"},
        }


def _spans() -> list:
    st = getattr(_state, "stack", None)
    if st is None:
        st = _state.stack = []
    return st


def _next_id() -> str:
    with _lock:
        return "%016x" % next(_ids)


def tracing(endpoint) -> dict:
    """Configure tracing: `endpoint` is a JSONL file path (or None to
    disable). Returns the config map like trace.clj:36-41."""
    global _endpoint, _sink
    with _lock:
        if _sink is not None:
            _sink.close()
            _sink = None
        _endpoint = endpoint if endpoint else None
    return {
        "endpoint": _endpoint,
        "config": sampler(_endpoint),
        "exporter": exporter(_endpoint),
    }


def exporter(endpoint) -> str | None:
    """Registers the exporter: ensures the directory exists and opens
    one persistent append handle, so span export is a single buffered
    write — not an open/close per span (trace.clj:26-33 registers the
    Jaeger exporter once, for the same reason)."""
    global _sink
    if not endpoint:
        return None
    d = os.path.dirname(os.path.abspath(endpoint))
    os.makedirs(d, exist_ok=True)
    with _lock:
        if _sink is None or _sink.name != endpoint:
            try:
                _sink = open(endpoint, "a")
            except OSError:
                _sink = None
    return endpoint


def enabled() -> bool:
    return _endpoint is not None


@contextlib.contextmanager
def with_trace(name: str):
    """Run the body inside a named span (trace.clj:43-53). Nested calls
    parent correctly; the span is exported when it closes."""
    if not enabled():
        yield None
        return
    stack = _spans()
    parent = stack[-1] if stack else None
    span = Span(
        name=name,
        trace_id=parent.trace_id if parent else _next_id(),
        span_id=_next_id(),
        parent_id=parent.span_id if parent else None,
        start_us=int(time.time() * 1e6),
    )
    stack.append(span)
    try:
        yield span
    finally:
        span.end_us = int(time.time() * 1e6)
        stack.pop()
        _export(span)


def context() -> dict:
    """Span/trace ids of the current span (trace.clj:55-62); zeros when
    not inside a span, matching OpenCensus's blank context."""
    stack = _spans()
    if not stack:
        return {"span_id": "0" * 16, "trace_id": "0" * 16}
    return {"span_id": stack[-1].span_id, "trace_id": stack[-1].trace_id}


def annotate(message: str) -> None:
    """Add a timestamped log to the current span (trace.clj:60-64)."""
    stack = _spans()
    if stack:
        stack[-1].annotations.append(
            {"timestamp": int(time.time() * 1e6), "fields": str(message)}
        )


def attribute(k, v) -> None:
    """Set a string key/value tag on the current span. Both must be
    strings — trace.clj:66-73's AttributeValue has the same rule, and
    enforcing it here keeps traces portable to real Jaeger. With no
    span open (tracing disabled, or outside with_trace) this is a
    no-op, so instrumented client code is safe on untraced runs."""
    stack = _spans()
    if not stack:
        return
    if not isinstance(k, str) or not isinstance(v, str):
        raise TypeError("trace attributes must be strings")
    stack[-1].attributes[k] = v


def _export(span: Span) -> None:
    d = span.to_dict()
    line = json.dumps(d) + "\n"
    with _lock:
        _buffer.append(d)
        if _sink is not None:
            try:
                _sink.write(line)
                _sink.flush()
            except (OSError, ValueError):
                pass


def drain() -> list:
    """Return and clear the in-memory span tail (tests, analysis)."""
    with _lock:
        out = list(_buffer)
        _buffer.clear()
    return out
