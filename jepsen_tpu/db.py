"""Database lifecycle protocols (reference: jepsen.db, db.clj:8-67)."""

from __future__ import annotations

import logging

from .util import real_pmap

log = logging.getLogger("jepsen_tpu.db")

CYCLE_TRIES = 3


class SetupFailed(Exception):
    """Raise from DB.setup to request a teardown+setup retry
    (db.clj ::setup-failed)."""


class DB:
    def setup(self, test, node) -> None:
        """Set up the database on this node."""

    def teardown(self, test, node) -> None:
        """Tear down the database on this node."""


class Primary:
    """Mixin: one-time setup on a single (first) node (db.clj:12-13)."""

    def setup_primary(self, test, node) -> None:
        raise NotImplementedError

    def primaries(self, test) -> list:
        """Nodes currently believed to be primaries (db.clj:18-22).
        Single-leader systems should override with a real leader probe;
        the default — the setup_primary node — matches the reference's
        degenerate case."""
        nodes = test.get("nodes") or []
        return nodes[:1]


class Process:
    """Mixin: the DB can report whether its process runs on a node
    (db.clj ::Process). alive() answers True/False, or None when the
    node has no record of the process at all (e.g. no pidfile)."""

    def alive(self, test, node):
        raise NotImplementedError


class Kill(Process):
    """Mixin: the DB's process can be killed and restarted on demand
    (db.clj ::Kill). kill() must be crash-like (SIGKILL, no graceful
    shutdown); start() must be idempotent — starting a running node is
    a no-op, so heal phases can blanket-restart."""

    def kill(self, test, node) -> None:
        raise NotImplementedError

    def start(self, test, node) -> None:
        raise NotImplementedError


class Pause(Process):
    """Mixin: the DB's process can be paused (SIGSTOP) and resumed
    (SIGCONT) (db.clj ::Pause). Both must be idempotent for the same
    reason Kill.start is."""

    def pause(self, test, node) -> None:
        raise NotImplementedError

    def resume(self, test, node) -> None:
        raise NotImplementedError


class LogFiles:
    """Mixin: per-node log file paths to snarf at test end (db.clj:15-16)."""

    def log_files(self, test, node) -> list:
        return []


class Noop(DB):
    pass


noop = Noop()


def cycle(test) -> None:
    """Tear down then set up the DB on all nodes concurrently; retry the
    whole cycle up to CYCLE_TRIES times on SetupFailed (db.clj:24-67)."""
    db = test["db"]
    nodes = test["nodes"]
    tries = CYCLE_TRIES
    while True:
        log.info("Tearing down DB")
        def safe_teardown(node):
            try:
                db.teardown(test, node)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                log.warning("teardown failed on %s", node, exc_info=True)
        real_pmap(safe_teardown, nodes)

        try:
            log.info("Setting up DB")
            real_pmap(lambda node: db.setup(test, node), nodes)
            if isinstance(db, Primary) and nodes:
                log.info("Setting up primary %s", nodes[0])
                db.setup_primary(test, nodes[0])
            return
        except SetupFailed:
            tries -= 1
            if tries <= 0:
                raise
            log.warning("Unable to set up database; retrying...")
