"""Database lifecycle protocols (reference: jepsen.db, db.clj:8-67)."""

from __future__ import annotations

import logging

from .util import real_pmap

log = logging.getLogger("jepsen_tpu.db")

CYCLE_TRIES = 3


class SetupFailed(Exception):
    """Raise from DB.setup to request a teardown+setup retry
    (db.clj ::setup-failed)."""


class DB:
    def setup(self, test, node) -> None:
        """Set up the database on this node."""

    def teardown(self, test, node) -> None:
        """Tear down the database on this node."""


class Primary:
    """Mixin: one-time setup on a single (first) node (db.clj:12-13)."""

    def setup_primary(self, test, node) -> None:
        raise NotImplementedError


class LogFiles:
    """Mixin: per-node log file paths to snarf at test end (db.clj:15-16)."""

    def log_files(self, test, node) -> list:
        return []


class Noop(DB):
    pass


noop = Noop()


def cycle(test) -> None:
    """Tear down then set up the DB on all nodes concurrently; retry the
    whole cycle up to CYCLE_TRIES times on SetupFailed (db.clj:24-67)."""
    db = test["db"]
    nodes = test["nodes"]
    tries = CYCLE_TRIES
    while True:
        log.info("Tearing down DB")
        def safe_teardown(node):
            try:
                db.teardown(test, node)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                log.warning("teardown failed on %s", node, exc_info=True)
        real_pmap(safe_teardown, nodes)

        try:
            log.info("Setting up DB")
            real_pmap(lambda node: db.setup(test, node), nodes)
            if isinstance(db, Primary) and nodes:
                log.info("Setting up primary %s", nodes[0])
                db.setup_primary(test, nodes[0])
            return
        except SetupFailed:
            tries -= 1
            if tries <= 0:
                raise
            log.warning("Unable to set up database; retrying...")
