"""One shared setup for CPU-hosted virtual device meshes.

Three places need an n-device mesh without TPU hardware — the test
suite (tests/conftest.py), bench.py's CPU fallback, and the mesh
doctor (tools/mesh_doctor.py, promoted from
``__graft_entry__.dryrun_multichip``) — and before this module each
hand-rolled the same fragile dance: set ``JAX_PLATFORMS=cpu``, splice
``--xla_force_host_platform_device_count=N`` into ``XLA_FLAGS``
*before* the first jax import, then pin ``jax_platforms`` again
*after* import because this image's sitecustomize registers an
experimental TPU platform plugin that resets it (and initializing
that backend can hang when the TPU tunnel is down).

``force_host_device_count`` is the one copy of that dance. It also
fixes the SIGILL warning spam the MULTICHIP_r0x dry-run tails showed:
XLA's CPU backend logs a feature-mismatch warning ("... could lead to
execution errors such as SIGILL") for every persisted-cache executable
compiled under a different host CPU feature set. The forced-CPU runs
share the default persistent compile cache with whatever host built it
last, so the helper keys the cache directory by a digest of this
host's CPU features — reuse stays within identical hosts, and the
mismatch warnings (which were pure noise: the entries recompile) never
trigger. An operator-set ``JEPSEN_TPU_COMPILE_CACHE`` is respected
untouched.

Import stays jax-free; jax is imported (and pinned) inside the call.
"""

from __future__ import annotations

import hashlib
import os
import re
import sys

_COUNT_FLAG = "--xla_force_host_platform_device_count"
_CACHE_ENV = "JEPSEN_TPU_COMPILE_CACHE"


def host_feature_digest() -> str:
    """A short digest of this host's CPU feature set (the ``flags``
    line of /proc/cpuinfo, falling back to the machine arch), so
    compile-cache directories can be keyed per feature set."""
    feats = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    if not feats:
        import platform

        feats = platform.machine()
    return hashlib.sha1(feats.encode()).hexdigest()[:12]


def _isolate_cpu_compile_cache() -> None:
    """Point the persistent compile cache at a per-host-feature-set
    subdirectory unless the operator pinned one explicitly."""
    if os.environ.get(_CACHE_ENV):
        return
    base = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "jepsen-tpu", "xla-cache")
    if str(base).lower() in ("", "0", "off", "none"):
        return
    os.environ[_CACHE_ENV] = os.path.join(
        base, f"cpu-{host_feature_digest()}")


def force_host_device_count(n: int, *, import_jax: bool = True):
    """Force an ``n``-device virtual CPU mesh for this process.

    Must run before jax initializes its backends; the flag is read at
    backend init. When jax is already imported AND initialized with
    fewer devices, raises rather than silently running on the wrong
    mesh. Returns the jax module when ``import_jax`` (the default) so
    call sites can do ``jax = hostdev.force_host_device_count(8)``.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    opt = f"{_COUNT_FLAG}={n}"
    if _COUNT_FLAG in flags:
        # replace any pre-existing count (e.g. a prior conftest) rather
        # than keeping a stale one
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", opt, flags)
    else:
        flags = (flags + " " + opt).strip()
    os.environ["XLA_FLAGS"] = flags
    _isolate_cpu_compile_cache()
    already = "jax" in sys.modules
    if not import_jax and not already:
        return None
    import jax

    # the env var alone is NOT enough in this image: sitecustomize
    # registers an experimental TPU platform plugin and resets
    # jax_platforms — the config.update takes precedence
    jax.config.update("jax_platforms", "cpu")
    if already and len(jax.devices()) < n:
        raise RuntimeError(
            f"jax initialized before force_host_device_count({n}); "
            f"have {len(jax.devices())} devices — run in a fresh process")
    return jax
