"""Key-space sharding: lift single-key workloads to many independent keys
(reference: jepsen.independent, independent.clj).

This is the framework's scale-out axis (SURVEY.md SS2.4): expensive checks
(linearizability) stay tractable because each key's subhistory is short,
and the per-key checks are embarrassingly parallel — on the TPU path the
keys dimension is exactly what gets vmapped/sharded across devices.

Values are wrapped in KVTuple(key, v); subhistories keep every op whose
value is NOT a tuple for a different key (so nemesis/info ops appear in
every subhistory), unwrapping matching tuples (independent.clj:234-245).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Iterable, NamedTuple

from . import generator as gen
from .checker import Checker, check_safe, merge_valid
from .history import op as to_op
from .util import bounded_pmap, bounded_pmap_processes

DIR = "independent"


class KVTuple(NamedTuple):
    """A kv tuple (independent.clj:21-29)."""

    key: object
    value: object


def tuple_(k, v) -> KVTuple:
    return KVTuple(k, v)


def is_tuple(v) -> bool:
    return isinstance(v, KVTuple)


def _wrap(o: dict, k) -> dict:
    o = dict(o)
    o["value"] = KVTuple(k, o.get("value"))
    return o


class SequentialGenerator(gen.Generator):
    """One key at a time: serve ops from fgen(k1) until exhausted, then
    move to k2 ... (independent.clj:31-64)."""

    def __init__(self, keys: Iterable, fgen: Callable):
        self._keys = iter(keys)
        self._fgen = fgen
        self._lock = threading.Lock()
        self._current = None  # (k, gen)
        self._done = False
        self._advance()

    def _advance(self):
        try:
            k = next(self._keys)
            self._current = (k, gen.to_gen(self._fgen(k)))
        except StopIteration:
            self._current = None
            self._done = True

    def op(self, test, process):
        while True:
            with self._lock:
                if self._done:
                    return None
                k, g = self._current
            o = g.op(test, process)
            if o is not None:
                return _wrap(o, k)
            with self._lock:
                if not self._done and self._current[0] == k:
                    self._advance()


def sequential_generator(keys, fgen) -> SequentialGenerator:
    return SequentialGenerator(keys, fgen)


class ConcurrentGenerator(gen.Generator):
    """n threads per key, thread-count/n keys in flight at once; each
    group of n contiguous threads works through keys, rebinding *threads*
    so barriers inside per-key generators span exactly that group
    (independent.clj:66-220). Requires concurrency to be a multiple of n;
    the nemesis never enters sub-generators."""

    def __init__(self, n: int, keys: Iterable, fgen: Callable):
        assert n > 0 and isinstance(n, int)
        self.n = n
        self._keys = iter(keys)
        self._fgen = fgen
        self._lock = threading.Lock()
        self._state = None  # {"active": [...], "group_threads": [...]}

    def _next_key(self):
        try:
            k = next(self._keys)
            return (k, gen.to_gen(self._fgen(k)))
        except StopIteration:
            return None

    def _init(self, test):
        threads = gen.current_threads()
        if threads is None:
            threads = list(range(test["concurrency"]))
        threads = [t for t in threads if isinstance(t, int)]
        thread_count = len(threads)
        assert sorted(threads) == list(range(thread_count)), (
            "concurrent_generator expects integer threads 0..n"
        )
        group_size = self.n
        group_count = thread_count // group_size
        assert group_size <= thread_count, (
            f"with {thread_count} worker threads, cannot run a key with "
            f"{group_size} threads concurrently; raise concurrency"
        )
        assert thread_count == group_size * group_count, (
            f"{thread_count} threads cannot be split into groups of "
            f"{group_size}; make concurrency a multiple of {group_size}"
        )
        self._state = {
            "active": [self._next_key() for _ in range(group_count)],
            "group_threads": [
                threads[g * group_size : (g + 1) * group_size]
                for g in range(group_count)
            ],
        }

    def op(self, test, process):
        with self._lock:
            if self._state is None:
                self._init(test)
            s = self._state
        thread = gen.process_to_thread(test, process)
        assert isinstance(thread, int), (
            f"only integer worker threads may draw from "
            f"concurrent_generator, got {thread!r}"
        )
        group = thread // self.n
        while True:
            with self._lock:
                pair = s["active"][group]
            if pair is None:
                return None
            k, g = pair
            with gen.with_threads(s["group_threads"][group]):
                o = g.op(test, process)
            if o is not None:
                return _wrap(o, k)
            with self._lock:
                if s["active"][group] is pair:
                    s["active"][group] = self._next_key()


def concurrent_generator(n, keys, fgen) -> ConcurrentGenerator:
    return ConcurrentGenerator(n, keys, fgen)


def history_keys(history) -> set:
    """All keys appearing in tuple values (independent.clj:222-232)."""
    out = set()
    for o in history:
        v = to_op(o).value
        if is_tuple(v):
            out.add(v.key)
    return out


def subhistory(k, history) -> list:
    """Ops without a *different* key's tuple value, tuples unwrapped
    (independent.clj:234-245)."""
    out = []
    for o in history:
        o = to_op(o)
        v = o.value
        if not is_tuple(v):
            out.append(o)
        elif v.key == k:
            out.append(o.with_(value=v.value))
    return out


def _merge_supervision(results) -> dict:
    """Aggregate per-key "supervision" telemetry deltas into one
    top-level dict. check_batch attaches ONE shared dict object to
    every item of a batch (the pass was one supervised run), so dedup
    by object identity before summing; the per-key fallback path
    attaches genuinely distinct deltas, which sum normally."""
    seen: list = []
    for r in results:
        d = r.get("supervision") if isinstance(r, dict) else None
        if d is not None and not any(d is s for s in seen):
            seen.append(d)
    out: dict = {}
    for d in seen:
        for k, v in d.items():
            if isinstance(v, dict):  # per_engine: {engine: {kind: n}}
                tgt = out.setdefault(k, {})
                for eng, kinds in v.items():
                    et = tgt.setdefault(eng, {})
                    for kind, n in kinds.items():
                        et[kind] = et.get(kind, 0) + n
            else:
                out[k] = out.get(k, 0) + v
    return out


class IndependentChecker(Checker):
    """Lift a checker over v to one over [k v] tuples: check each key's
    subhistory (in parallel), merge validities, list failing keys
    (independent.clj:247-298).

    processes=True fans the per-key checks over a process pool instead
    of threads — the pure-Python search fallbacks (host WGL, the linear
    engine) are CPU-bound, so the default thread pool serializes them
    behind the GIL (the reference's bounded-pmap runs on a JVM where
    threads really run in parallel, independent.clj:269-287). The
    process path ships each worker only the picklable slice of the test
    map; file-writing sub-checkers still run fine because artifact
    paths derive from test name/start_time, which are plain strings."""

    def __init__(self, checker: Checker, processes: bool = False):
        self.checker = checker
        self.processes = processes

    def check(self, test, history, opts=None) -> dict:
        opts = dict(opts or {})
        history = list(history)
        ks = sorted(history_keys(history), key=str)

        # Resumable analysis: with an AnalysisJournal attached
        # (core.analyze), per-key verdicts journaled by a previous —
        # possibly killed — analysis pass are reused and their keys
        # skipped entirely. Journal identity covers the subhistory
        # CONTENT, not just the key, so a key whose history grew (a
        # resumed run) re-checks instead of reusing a stale verdict.
        journal = (test or {}).get("_analysis_journal")
        journaled: dict = {}
        jkeys: dict = {}
        if journal is not None:
            remaining = []
            for k in ks:
                jk = _journal_key(k, subhistory(k, history))
                jkeys[k] = jk
                r = journal.get("independent-key", jk)
                if r is not None:
                    journaled[k] = r
                else:
                    remaining.append(k)
            if journaled:
                from .checker import supervisor as sup_mod

                sup_mod.get().telemetry.record(
                    "journal_skips", len(journaled))
                logging.getLogger("jepsen_tpu.independent").info(
                    "analysis journal: skipping %d finished key(s), "
                    "%d to check", len(journaled), len(remaining))
            ks = remaining

        def check_key(k):
            sub = subhistory(k, history)
            subdir = list(opts.get("subdirectory") or []) + [DIR, str(k)]
            r = check_safe(
                self.checker,
                test,
                sub,
                {**opts, "subdirectory": subdir, "history_key": k},
            )
            self._write_artifacts(test, subdir, sub, r)
            return k, r

        # Batched fast path: a sub-checker exposing check_batch (the
        # linearizable checker) gets ALL per-key subhistories in one
        # call, so its batch engines see the whole key space at once
        # instead of one launch per key — which is what lets the
        # measured-crossover router (checker/calibrate.py) weigh the
        # REAL lane count against the dispatch round trip: wide key
        # spaces (and the pcomp micro-lanes they decompose into) clear
        # the calibrated bar and ride the pallas pipeline whole, while
        # narrow ones stay on native triage. Any failure falls back to
        # the per-key path, whose check_safe wrapper degrades per-key
        # errors to unknown.
        results = None
        if len(ks) > 1 and hasattr(self.checker, "check_batch"):
            payload = []
            for k in ks:
                sub = subhistory(k, history)
                subdir = (list(opts.get("subdirectory") or [])
                          + [DIR, str(k)])
                payload.append((k, sub, {**opts, "subdirectory": subdir,
                                         "history_key": k}))
            try:
                rs = self.checker.check_batch(
                    test, [(sub, o) for _, sub, o in payload])
            except Exception:  # noqa: BLE001 — degrade to per-key path
                logging.getLogger("jepsen_tpu.independent").warning(
                    "batched check failed; falling back to per-key",
                    exc_info=True)
            else:
                results = {}
                for (k, sub, o), r in zip(payload, rs):
                    self._write_artifacts(test, o["subdirectory"], sub, r)
                    results[k] = r

        if results is None and self.processes and len(ks) > 1:
            # workers only use their own subhistory — shipping the full
            # test history (or other recorded bulk) to every worker
            # would serialize O(keys × |history|)
            lite = _picklable_map({
                k: v for k, v in (test or {}).items()
                if k not in ("history", "active_histories")
            })
            payloads = []
            for k in ks:
                sub = subhistory(k, history)
                subdir = (list(opts.get("subdirectory") or [])
                          + [DIR, str(k)])
                payloads.append((
                    self.checker, lite, sub,
                    {**_picklable_map(opts), "subdirectory": subdir,
                     "history_key": k},
                    k,
                ))
            pairs = bounded_pmap_processes(_check_payload, payloads)
            results = {}
            for (k, r), payload in zip(pairs, payloads):
                self._write_artifacts(test, payload[3]["subdirectory"],
                                      payload[2], r)
                results[k] = r
        elif results is None:
            results = dict(bounded_pmap(check_key, ks))
        if journal is not None:
            for k, r in results.items():
                journal.record("independent-key", jkeys[k], r)
            results = {**journaled, **results}
        return combine_results(results)

    @staticmethod
    def _write_artifacts(test, subdir, sub, result) -> None:
        """Persist per-key history + results under the test's store dir
        (independent.clj:269-287), when a store is attached."""
        try:
            from . import store

            if test and test.get("start_time"):
                store.write_edn(test, subdir + ["results.edn"], result)
                store.write_history_txt(test, subdir + ["history.txt"], sub)
        except Exception:  # noqa: BLE001 - artifact writing is best-effort
            pass


def combine_results(results: dict) -> dict:
    """Fold per-key result dicts into one independent-checker verdict:
    merged validity, failing keys, aggregated supervision telemetry,
    and the unioned anomaly taxonomy. This is THE recombination — both
    IndependentChecker.check and the resident daemon's cross-run packer
    (pack_check) produce their verdicts through it, which is what makes
    a packed verdict bit-identical to a one-shot one.

    Only definite falsifications are failures; "unknown" keys are
    excluded, as in the reference (independent.clj:283-291, where
    :unknown is truthy)."""
    failures = [k for k, r in results.items() if r["valid"] is False]
    out = {
        "valid": merge_valid(r["valid"] for r in results.values()),
        "results": results,
        "failures": failures,
    }
    sup = _merge_supervision(results.values())
    if sup:
        out["supervision"] = sup
    # cycle-checker results: union the per-key anomaly taxonomy so
    # the top level answers "which anomalies did ANY key show"
    anomaly_types = sorted({
        t for r in results.values() if isinstance(r, dict)
        for t in r.get("anomaly-types") or ()
    })
    if anomaly_types:
        out["anomaly-types"] = anomaly_types
    return out


def pack_check(checker: "IndependentChecker", test, jobs,
               opts=None) -> list[dict]:
    """Cross-run batch packing: check MANY independent histories in
    one batched engine pass. `jobs` is a list of histories (each the
    full keyed history of one submitted run); every job's per-key
    subhistories flatten into ONE check_batch call on the wrapped
    sub-checker, so the batch engines see the union of all runs' key
    lanes at once — P-compositionality (Horn & Kroening) makes the
    per-key verdicts independent of which run a lane arrived with,
    which is what lets the resident daemon pack strangers' work into
    shared device batches. Each job's verdict recombines through
    combine_results, so it is bit-identical to what
    IndependentChecker.check would return for that history alone.

    Falls back to sequential per-job check() when the sub-checker has
    no check_batch or the batched pass fails."""
    opts = dict(opts or {})
    jobs = [list(h) for h in jobs]
    if hasattr(checker.checker, "check_batch"):
        payload = []  # flat (job_idx, key, subhistory, per_item_opts)
        job_keys: list = []
        for j, history in enumerate(jobs):
            ks = sorted(history_keys(history), key=str)
            job_keys.append(ks)
            for k in ks:
                sub = subhistory(k, history)
                subdir = (list(opts.get("subdirectory") or [])
                          + [DIR, str(k)])
                payload.append((j, k, sub,
                                {**opts, "subdirectory": subdir,
                                 "history_key": k}))
        try:
            rs = checker.checker.check_batch(
                test, [(sub, o) for _, _, sub, o in payload])
        except Exception:  # noqa: BLE001 — degrade to per-job path
            logging.getLogger("jepsen_tpu.independent").warning(
                "packed cross-run check failed; falling back to "
                "per-job checks", exc_info=True)
        else:
            per_job: list = [dict() for _ in jobs]
            for (j, k, _sub, _o), r in zip(payload, rs):
                per_job[j][k] = r
            return [combine_results(res) for res in per_job]
    return [checker.check(test, h, opts) for h in jobs]


def _journal_key(k, sub) -> str:
    """A stable journal identity for one key's analysis: the key plus
    a digest of its subhistory's verdict-relevant fields. Anything that
    changes the check's input changes the identity."""
    import hashlib

    h = hashlib.sha1()
    for o in sub:
        h.update(repr((o.process, o.type, o.f, o.value,
                       o.index, o.error)).encode())
    return f"{k}#{len(sub)}#{h.hexdigest()[:16]}"


def _picklable_map(m: dict) -> dict:
    """The subset of a dict whose values survive pickling — what a
    process-pool worker can receive (clients, remotes, generators, and
    live sockets don't; names, models, and options do)."""
    import pickle

    out = {}
    for k, v in m.items():
        try:
            pickle.dumps(v)
        except Exception:  # noqa: BLE001 — unpicklable: drop
            continue
        out[k] = v
    return out


def _check_payload(payload):
    """Process-pool worker: run one key's check (module-level so it
    pickles)."""
    chk, test, sub, opts, k = payload
    return k, check_safe(chk, test, sub, opts)


def checker(c: Checker, processes: bool = False) -> IndependentChecker:
    return IndependentChecker(c, processes=processes)
