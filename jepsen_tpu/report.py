"""Report helpers (reference: jepsen.report, report.clj:7-16): bind
stdout to a file for a block of code."""

from __future__ import annotations

import contextlib
import os
import sys


@contextlib.contextmanager
def to(filename: str):
    """Redirect stdout into `filename` for the duration of the block,
    creating parent directories; prints a pointer to the report when
    done (report.clj:7-16)."""
    os.makedirs(os.path.dirname(os.path.abspath(filename)), exist_ok=True)
    with open(filename, "w") as w:
        with contextlib.redirect_stdout(w):
            yield w
    print("Report written to", filename, file=sys.stderr)
