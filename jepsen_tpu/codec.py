"""Value serialization for clients stashing structured values inside
databases (reference: jepsen.codec, codec.clj:9-29 — EDN over bytes;
here JSON over UTF-8, the ecosystem-native equivalent).

None encodes to zero bytes and zero bytes decode to None, exactly like
the reference's nil round-trip. Tuples survive a round-trip as lists
(JSON has one sequence type), which matches how histories and the store
already normalize values."""

from __future__ import annotations

import json


def encode(obj) -> bytes:
    """Serialize an object to bytes (codec.clj:9-15)."""
    if obj is None:
        return b""
    return json.dumps(obj).encode("utf-8")


def decode(data) -> object:
    """Deserialize bytes (or str/bytearray/memoryview) to an object
    (codec.clj:17-29)."""
    if data is None:
        return None
    if isinstance(data, str):
        data = data.encode("utf-8")
    data = bytes(data)
    if not data:
        return None
    return json.loads(data.decode("utf-8"))
