"""Persistent storage for test runs and later analysis (reference:
jepsen.store, store.clj).

Layout parity with the reference (store.clj:125-154, 302-328):

    store/<test-name>/<start-time>/
        jepsen.log       engine log for the run          (store.clj:398-418)
        history.txt      human-readable op log           (store.clj:340-357)
        history.jsonl    one JSON op per line (the EDN history analog)
        history.npz      TensorHistory — the TPU-native flat encoding;
                         this replaces test.fressian as the machine
                         snapshot (SURVEY.md SS7.1: one flat format for
                         store, checker input, and wire)
        test.json        serializable test-map snapshot  (store.clj:167-175)
        results.json     analysis results                (store.clj:336-339)
    store/current        symlink -> the running test     (store.clj:302-328)
    store/latest         symlink -> the newest saved test
    store/<name>/latest  symlink -> the newest run of that test

Unlike the reference there is no opaque binary snapshot (fressian,
store.clj:28-123): every artifact is JSON, text, or the npz tensor, all
reloadable without the defining code. `load()` + `jepsen_tpu.cli`'s
`analyze` subcommand re-check a stored history with fresh checkers and no
cluster (cli.clj:366-397 semantics).
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import shutil
import threading
from typing import Any, Iterable

from .history import Op, TensorHistory

BASE_DIR = "store"

log = logging.getLogger("jepsen_tpu.store")

#: test-map keys that hold live objects and never serialize
#: (store.clj:167-172), plus engine internals.
DEFAULT_NONSERIALIZABLE_KEYS = {
    "db",
    "os",
    "net",
    "client",
    "checker",
    "nemesis",
    "generator",
    "model",
    "remote",
    "ssh",
    "barrier",
    "active_histories",
    "schema",
}


def nonserializable_keys(test) -> set:
    """Default nonserializable keys plus the test's own
    (store.clj:174-179), plus every "_"-prefixed engine-internal key."""
    ks = set(DEFAULT_NONSERIALIZABLE_KEYS)
    ks.update(test.get("nonserializable_keys", ()))
    ks.update(k for k in test if isinstance(k, str) and k.startswith("_"))
    return ks


def time_str(t) -> str:
    """Render a start-time as a directory name (the reference's
    :basic-date-time local format, store.clj:131-141)."""
    if isinstance(t, str):
        return t
    if isinstance(t, datetime.datetime):
        return t.strftime("%Y%m%dT%H%M%S.%f")[:-3]
    raise TypeError(f"can't render start_time {t!r}")


def base_dir(test=None) -> str:
    """The store root; override per-test with :store_dir."""
    if test is not None and test.get("store_dir"):
        return str(test["store_dir"])
    return BASE_DIR


def _flatten(args) -> list:
    out = []
    for a in args:
        if a is None:
            continue
        if isinstance(a, (list, tuple)):
            out.extend(_flatten(a))
        else:
            out.append(str(a))
    return out


def path(test, *args) -> str:
    """The directory for a test's results; extra args name a file inside
    it. Nested lists flatten; None components are ignored
    (store.clj:125-147)."""
    assert test.get("name"), "test needs a :name to have a store path"
    assert test.get("start_time"), "test needs a :start_time"
    d = os.path.join(
        base_dir(test), str(test["name"]), time_str(test["start_time"])
    )
    return os.path.join(d, *_flatten(args)) if args else d


def path_(test, *args) -> str:
    """path(), but ensures the containing directory exists
    (store.clj:149-154)."""
    p = path(test, *args)
    os.makedirs(os.path.dirname(p) if args else p, exist_ok=True)
    return p


# ---------------------------------------------------------------------------
# Writers

def atomic_write_json(p: str, value, rotate_prev: bool = False) -> str:
    """Crash-consistent JSON write: temp → flush+fsync → rename, so a
    SIGKILL at any instant leaves either the old file or the new one,
    never a torn half-write. With ``rotate_prev`` the previous current
    file is rotated to ``.prev`` first (the RunCheckpoint discipline).
    This is the single write primitive the checkpoint, the serve work
    queue, and the AOT bundle manifest all share."""
    os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(_json_keys(value), f, default=_json_default)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    if rotate_prev and os.path.exists(p):
        os.replace(p, p + ".prev")
    os.replace(tmp, p)
    return p


def read_json_dict(p: str) -> dict | None:
    """Best-effort read-back of an atomic_write_json file: the dict, or
    None for missing/torn/non-dict content. The serve queue, the
    sacrificial runner, and replay tooling all want the same 'a disk
    that lies must not wedge us' posture, so it lives here."""
    try:
        with open(p) as f:
            v = json.load(f)
        return v if isinstance(v, dict) else None
    except (OSError, ValueError):
        return None


def _json_keys(v):
    """json's default= hook never applies to dict KEYS — independent-
    checker results are keyed by arbitrary workload keys (e.g. tuples),
    so stringify any non-primitive key up front."""
    if isinstance(v, dict):
        return {
            k if isinstance(k, (str, int, float, bool)) or k is None else str(k):
            _json_keys(x)
            for k, x in v.items()
        }
    if isinstance(v, (list, tuple)):
        return [_json_keys(x) for x in v]
    return v


def _json_default(o):
    if isinstance(o, datetime.datetime):
        return o.isoformat()
    if isinstance(o, Op):
        return o.to_dict()
    if isinstance(o, (set, frozenset)):
        return sorted(o, key=repr)
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    if hasattr(o, "item"):  # numpy scalars
        return o.item()
    if hasattr(o, "tolist"):  # numpy arrays
        return o.tolist()
    return repr(o)


def write_json(test, subpath, value) -> str:
    """Write any value as pretty JSON under the test dir."""
    p = path_(test, subpath)
    with open(p, "w") as f:
        json.dump(_json_keys(value), f, indent=1, default=_json_default)
        f.write("\n")
    return p


# independent.py historically calls this write_edn (the reference writes
# results.edn); the on-disk format here is JSON.
write_edn = write_json


#: incremental-durability sidecar: one JSON op per line, appended as ops
#: land during the run (vs history.jsonl, written once at save_1)
WAL_FILE = "history.wal.jsonl"

#: when HistoryWAL calls os.fsync: every op / nemesis ops + close / close
WAL_FSYNC_POLICIES = ("op", "nemesis", "close")


def _terminate_torn_tail(f, p: str) -> None:
    """A mid-write kill can leave an append-mode JSONL file without a
    trailing newline; the next append would glue onto the torn line and
    corrupt BOTH records. Terminate the tail so the torn line stays an
    isolated, droppable parse failure."""
    try:
        size = os.path.getsize(p)
        if size:
            with open(p, "rb") as r:
                r.seek(size - 1)
                if r.read(1) != b"\n":
                    f.write("\n")
                    f.flush()
    except OSError:
        pass


class HistoryWAL:
    """Append-only JSONL write-ahead log of the live history.

    ``run_case`` opens one per run and ``core.conj_op`` appends every op
    (invocations AND completions) the moment it lands, each line flushed
    so a SIGKILL'd run leaves an analyzable partial history on disk for
    ``load_history`` to fall back to — the in-memory history plus a
    final ``store.write_history`` is otherwise all-or-nothing. A torn
    final line (killed mid-write) is expected and tolerated on load.

    Every line is stamped with a **session epoch** (``_epoch``): a
    resumed run reopens the same file in append mode under epoch
    last+1, so ``load_history`` can reindex deterministically across
    sessions instead of colliding op indices. The stamp is an engine
    key, stripped before ops are rebuilt.

    The fsync policy is configurable (``test["wal_fsync"]`` or the
    ``fsync`` argument): ``"op"`` fsyncs every line (maximum
    durability, slowest), ``"nemesis"`` (the default) fsyncs lines the
    nemesis lands — fault boundaries are always durable without paying
    per-op fsync — and ``"close"`` only on close. Every policy still
    flushes each line to the OS, so only an OS/power crash (not a mere
    process SIGKILL) can lose un-fsynced ops.

    Appends are serialized by a lock: client workers and the nemesis
    land ops concurrently. A failed append disables the WAL rather than
    failing the run — durability is best-effort, the verdict is not."""

    def __init__(self, test, fsync: str | None = None):
        policy = fsync or (test or {}).get("wal_fsync") or "nemesis"
        if policy not in WAL_FSYNC_POLICIES:
            raise ValueError(
                f"wal_fsync must be one of {WAL_FSYNC_POLICIES}, "
                f"got {policy!r}")
        self.fsync_policy = policy
        self._path = path_(test, WAL_FILE)
        self._lock = threading.Lock()
        self.epoch = self._next_epoch(self._path)
        self._f = open(self._path, "a")
        _terminate_torn_tail(self._f, self._path)

    @staticmethod
    def _next_epoch(p: str) -> int:
        """One past the last parseable line's epoch; 0 for a fresh file.
        A nonempty file with no parseable line still advances (a prior
        session existed, even if only its torn tail survives)."""
        try:
            if not os.path.exists(p) or os.path.getsize(p) == 0:
                return 0
        except OSError:
            return 0
        last = None
        with open(p) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    last = json.loads(line)
                except ValueError:
                    continue
        if not isinstance(last, dict):
            return 1
        try:
            return int(last.get("_epoch", 0)) + 1
        except (TypeError, ValueError):
            return 1

    @staticmethod
    def follow(p: str, *, poll_s: float = 0.05, stop=None):
        """Tail-follow reader over a (possibly live) WAL file: yields
        reindexed Ops as lines land, holding a torn tail back until a
        resumed writer terminates it. Delegates to ``follow_wal`` —
        the same parse/stitch logic ``load_wal_history`` batch-reads
        with."""
        return follow_wal(p, follow=True, poll_s=poll_s, stop=stop)

    def append(self, op: Op) -> None:
        with self._lock:
            if self._f is None:
                return
            try:
                rec = op.to_dict()
                rec["_epoch"] = self.epoch
                self._f.write(json.dumps(rec, default=_json_default))
                self._f.write("\n")
                self._f.flush()
                if self.fsync_policy == "op" or (
                    self.fsync_policy == "nemesis"
                    and op.process == "nemesis"
                ):
                    os.fsync(self._f.fileno())
            except Exception:  # noqa: BLE001 — best-effort durability
                log.warning("history WAL append failed; disabling",
                            exc_info=True)
                try:
                    self._f.close()
                except Exception:  # noqa: BLE001
                    pass
                self._f = None

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                except (OSError, ValueError):
                    pass
                self._f.close()
                self._f = None


#: crash-consistent snapshot of live run state, written periodically
CKPT_FILE = "run.ckpt.json"


class RunCheckpoint:
    """Crash-consistent run-state snapshots for preemption-tolerant
    runs: generator cursors/rng states, the nemesis active-fault
    ledger, the process table, the WAL session epoch, and a wall-clock
    anchor (core.checkpoint_state assembles the dict; this class only
    guarantees durability).

    write() goes temp → flush+fsync → rotate current→``.prev`` →
    rename temp→current, so a SIGKILL at ANY instant leaves the new
    checkpoint, the previous good one, or both — never zero. load()
    validates the current file and falls back to ``.prev`` on a
    torn/truncated/missing current; a stale ``.tmp`` leftover is
    ignored and overwritten by the next write."""

    def __init__(self, test):
        self._path = path_(test, CKPT_FILE)
        self._lock = threading.Lock()

    @property
    def path(self) -> str:
        return self._path

    def write(self, state: dict) -> str:
        with self._lock:
            return atomic_write_json(self._path, state, rotate_prev=True)

    def load(self) -> dict | None:
        """The newest readable checkpoint, or None when neither the
        current file nor .prev parses."""
        for p in (self._path, self._path + ".prev"):
            try:
                with open(p) as f:
                    state = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(state, dict):
                return state
        return None


def load_checkpoint(test) -> dict | None:
    """The newest readable run checkpoint for a test dir, or None."""
    return RunCheckpoint(test).load()


#: append-only journal of finished analysis units (resumable analysis)
ANALYSIS_CKPT_FILE = "analysis.ckpt.jsonl"


class AnalysisJournal:
    """Append-only JSONL journal of completed analysis verdicts, so
    re-running analysis of a huge history skips finished work: the
    independent checker journals per-key linearizability verdicts
    ("independent-key") and the cycle checker journals per-component
    closure results ("closure") as they complete.

    Each line is ``{"kind", "key", "result"}``; keys are stringified
    for a stable JSON identity. Loading tolerates a torn tail (a kill
    mid-append loses at most the line being written). Journaled results
    round-trip through JSON — Ops inside come back as plain dicts — so
    consumers treat them as opaque verdicts, not live objects."""

    def __init__(self, test, path: str | None = None):
        """Open a test's journal, or — with an explicit ``path`` — a
        free-standing one (the online watch sessions keep theirs in a
        state dir with no test map at all)."""
        if path is None:
            self._path = path_(test, ANALYSIS_CKPT_FILE)
        else:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._path = path
        self._lock = threading.Lock()
        self._done: dict = {}
        try:
            with open(self._path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        rec = json.loads(line)
                        self._done[(rec["kind"], rec["key"])] = \
                            rec.get("result")
                    except (ValueError, KeyError, TypeError):
                        log.warning(
                            "analysis journal: dropping torn line %r",
                            line[:80])
        except FileNotFoundError:
            pass
        self._f = open(self._path, "a")
        _terminate_torn_tail(self._f, self._path)

    @property
    def path(self) -> str:
        return self._path

    def __len__(self) -> int:
        return len(self._done)

    def contains(self, kind: str, key) -> bool:
        return (kind, str(key)) in self._done

    def get(self, kind: str, key):
        return self._done.get((kind, str(key)))

    def record(self, kind: str, key, result) -> None:
        key = str(key)
        with self._lock:
            if (kind, key) in self._done:
                return
            self._done[(kind, key)] = result
            if self._f is None:
                return
            try:
                self._f.write(json.dumps(
                    {"kind": kind, "key": key,
                     "result": _json_keys(result)},
                    default=_json_default))
                self._f.write("\n")
                self._f.flush()
            except Exception:  # noqa: BLE001 — journal is best-effort
                log.warning("analysis journal append failed; disabling",
                            exc_info=True)
                try:
                    self._f.close()
                except Exception:  # noqa: BLE001
                    pass
                self._f = None

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def write_history_txt(test, subpath, history: Iterable[Op]) -> str:
    """history.txt: one tab-separated line per op (util/pwrite-history!
    format, util.clj:184-206)."""
    p = path_(test, subpath)
    with open(p, "w") as f:
        for o in history:
            f.write(str(o))
            f.write("\n")
    return p


def write_history(test) -> None:
    """Write history.txt + history.jsonl (+ history.npz when the test
    carries a tensor schema) — store.clj:340-357."""
    hist = test.get("history") or []
    write_history_txt(test, "history.txt", hist)
    p = path_(test, "history.jsonl")
    with open(p, "w") as f:
        for o in hist:
            f.write(json.dumps(o.to_dict(), default=_json_default))
            f.write("\n")
    schema = test.get("schema")
    if schema is not None:
        try:
            TensorHistory.encode(hist, schema).save(path_(test, "history.npz"))
        except Exception:  # noqa: BLE001 — tensor snapshot is best-effort
            log.warning("couldn't write history.npz", exc_info=True)


def write_test(test) -> str:
    """test.json: the serializable slice of the test map (the fressian
    snapshot analog, store.clj:359-366)."""
    drop = nonserializable_keys(test)
    snap = {k: v for k, v in test.items() if k not in drop and k != "history"}
    snap["start_time"] = time_str(test["start_time"])
    return write_json(test, "test.json", snap)


def write_results(test) -> str:
    """results.json (store.clj:336-339)."""
    return write_json(test, "results.json", test.get("results"))


# ---------------------------------------------------------------------------
# Symlinks

def update_symlink(test, dest_parts: list) -> None:
    """Symlink base_dir/<dest_parts...> -> the test dir, replacing any
    existing link (store.clj:302-313)."""
    src = path(test)
    if not os.path.exists(src):
        return
    dest = os.path.join(base_dir(test), *dest_parts)
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    try:
        if os.path.islink(dest) or os.path.exists(dest):
            os.remove(dest)
        os.symlink(os.path.relpath(src, os.path.dirname(dest)), dest)
    except OSError:
        log.warning("couldn't update symlink %s", dest, exc_info=True)


def update_current_symlink(test) -> None:
    update_symlink(test, ["current"])


def update_symlinks(test) -> None:
    """current, latest, and <name>/latest (store.clj:315-328)."""
    for dest in (["current"], ["latest"], [str(test["name"]), "latest"]):
        update_symlink(test, dest)


# ---------------------------------------------------------------------------
# Save phases (core.clj:636 calls save_1 post-run; analyze! calls save_2)

def save_1(test) -> dict:
    """Phase 1, after the run: history + test snapshot + symlinks
    (store.clj:367-379)."""
    write_history(test)
    write_test(test)
    update_symlinks(test)
    return test


def save_2(test) -> dict:
    """Phase 2, after analysis: results + refreshed test snapshot.
    Unlike the reference (store.clj:381-392), the history is NOT
    rewritten: core.run() indexes the history BEFORE save_1 writes it,
    analysis doesn't mutate it further, and rewriting a 10k+-op history
    twice per run is wasted I/O. (If you call save_1 with an unindexed
    history yourself, index it first — this phase won't fix it up.)"""
    write_results(test)
    write_test(test)
    update_symlinks(test)
    return test


# ---------------------------------------------------------------------------
# Loading

def tests(name=None, store_dir=None) -> dict:
    """With no name: {test-name: {time-str: dir}}. With a name:
    {time-str: dir} (store.clj:241-266)."""
    root = store_dir or BASE_DIR
    if name is None:
        out = {}
        if os.path.isdir(root):
            for n in sorted(os.listdir(root)):
                if n in ("latest", "current"):
                    continue
                if os.path.isdir(os.path.join(root, n)):
                    out[n] = tests(n, store_dir=root)
        return out
    d = os.path.join(root, str(name))
    out = {}
    if os.path.isdir(d):
        for t in sorted(os.listdir(d)):
            full = os.path.join(d, t)
            if t != "latest" and os.path.isdir(full):
                out[t] = full
    return out


def load_history(test) -> list[Op]:
    """Reload a run's history, preferring the jsonl form. A run that
    died before save_1 (SIGKILL, OOM, power) leaves no history.jsonl —
    fall back to the WAL the run appended as ops landed, tolerating a
    torn final line."""
    p = path(test, "history.jsonl")
    if os.path.exists(p):
        with open(p) as f:
            return [Op.from_dict(json.loads(line)) for line in f if line.strip()]
    p = path(test, "history.npz")
    if os.path.exists(p):
        return TensorHistory.load(p).decode()
    p = path(test, WAL_FILE)
    if os.path.exists(p):
        return load_wal_history(test)
    raise FileNotFoundError(f"no stored history under {path(test)}")


def _parse_wal_line(line: str) -> tuple[int, Op] | None:
    """One WAL line as an (epoch, op) pair, or None for a torn/blank
    line. Strips the "_"-prefixed engine stamps before the op is
    rebuilt (Op.from_dict would otherwise shelve them under .extra)."""
    if not line.strip():
        return None
    try:
        rec = json.loads(line)
        epoch = int(rec.pop("_epoch", 0))
        for k in [k for k in rec
                  if isinstance(k, str) and k.startswith("_")]:
            del rec[k]
        return (epoch, Op.from_dict(rec))
    except (ValueError, KeyError, TypeError, AttributeError):
        # torn tail from a mid-write kill: salvage the prefix
        log.warning("WAL: dropping unparseable line %r", line[:80])
        return None


def _parse_wal(p: str) -> list[tuple[int, Op]]:
    """(epoch, op) pairs from a WAL file, tolerating a torn tail."""
    out = []
    with open(p) as f:
        for line in f:
            pair = _parse_wal_line(line)
            if pair is not None:
                out.append(pair)
    return out


def _stitch_wal(pairs: list[tuple[int, Op]]) -> list[Op]:
    """Stitch (epoch, op) pairs into one history, reindexed 0..n-1.
    Stable sort by session epoch first (arrival order preserved within
    an epoch), so a run appended across resume sessions gets monotonic,
    collision-free indices — WAL lines land BEFORE history finalization
    assigns indices (index=-1), and pairs/checkers require monotonic
    ones."""
    pairs = sorted(pairs, key=lambda pair: pair[0])
    return [o.with_(index=i) for i, (_, o) in enumerate(pairs)]


def load_wal_history(test) -> list[Op]:
    """The salvageable ops of a run's WAL, stitched and reindexed.
    Returns [] when no WAL exists."""
    p = path(test, WAL_FILE)
    if not os.path.exists(p):
        return []
    return _stitch_wal(_parse_wal(p))


def follow_wal(p: str, *, follow: bool = False, poll_s: float = 0.05,
               stop=None):
    """Iterate a WAL file's salvageable ops, reindexed exactly as
    ``load_wal_history`` stitches them (same per-line salvage, same
    epoch-stable order — a WAL only ever appends, and every session's
    epoch exceeds its predecessors', so file order IS stitch order).

    With ``follow=False`` this is the one-shot batch read. With
    ``follow=True`` the iterator tails the file: it keeps polling for
    appended lines (surviving the file not existing yet) until ``stop``
    (a threading.Event) is set. Only newline-terminated records are
    yielded while tailing — a torn tail from a mid-write kill is held
    back, and becomes visible the moment a resumed session's
    ``HistoryWAL`` terminates it (or is dropped by its parse failure),
    matching the batch reader's salvage behavior."""
    if not follow:
        if os.path.exists(p):
            yield from _stitch_wal(_parse_wal(p))
        return
    import time as _time

    f = None
    buf = b""
    idx = 0
    try:
        while True:
            if f is None:
                try:
                    f = open(p, "rb")
                except OSError:
                    f = None
            progressed = False
            if f is not None:
                chunk = f.read()
                if chunk:
                    progressed = True
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        pair = _parse_wal_line(
                            line.decode("utf-8", "replace"))
                        if pair is None:
                            continue
                        yield pair[1].with_(index=idx)
                        idx += 1
            if stop is not None and stop.is_set():
                return
            if not progressed:
                _time.sleep(poll_s)
    finally:
        if f is not None:
            f.close()


def follow_wal_history(test, *, follow: bool = False, poll_s: float = 0.05,
                       stop=None):
    """``follow_wal`` over a test's own WAL path."""
    return follow_wal(path(test, WAL_FILE), follow=follow, poll_s=poll_s,
                      stop=stop)


def load(name, time_s, store_dir=None) -> dict:
    """Load a stored test by name and time: the test.json snapshot with
    its history attached (store.clj:177-184)."""
    test = {"name": name, "start_time": time_s}
    if store_dir:
        test["store_dir"] = store_dir
    p = path(test, "test.json")
    if os.path.exists(p):
        with open(p) as f:
            snap = json.load(f)
        snap.pop("store_dir", None)
        test.update(snap)
        test["name"], test["start_time"] = name, time_s
        if store_dir:
            test["store_dir"] = store_dir
    test["history"] = load_history(test)
    return test


def load_results(name, time_s, store_dir=None) -> Any:
    """Load only results.json (store.clj:224-233)."""
    test = {"name": name, "start_time": time_s}
    if store_dir:
        test["store_dir"] = store_dir
    with open(path(test, "results.json")) as f:
        return json.load(f)


def _resolve_latest(store_dir=None):
    root = store_dir or BASE_DIR
    link = os.path.join(root, "latest")
    # Trust the symlink only while it resolves — delete() can leave it
    # dangling; fall back to scanning.
    if os.path.islink(link) and os.path.isdir(os.path.realpath(link)):
        target = os.path.realpath(link)
        time_s = os.path.basename(target)
        name = os.path.basename(os.path.dirname(target))
        return name, time_s
    newest = None
    for name, runs in tests(store_dir=root).items():
        for t in runs:
            if newest is None or t > newest[1]:
                newest = (name, t)
    return newest


def latest(store_dir=None) -> dict | None:
    """Load the most recent test (store.clj:291-300)."""
    found = _resolve_latest(store_dir)
    if found is None:
        return None
    return load(found[0], found[1], store_dir=store_dir)


def delete(name=None, time_s=None, store_dir=None) -> None:
    """Delete all tests / all runs of a test / one run
    (store.clj:420-437)."""
    root = store_dir or BASE_DIR
    if name is None:
        for n in list(tests(store_dir=root)):
            delete(n, store_dir=root)
    elif time_s is None:
        d = os.path.join(root, str(name))
        if os.path.isdir(d):
            shutil.rmtree(d)
    else:
        d = os.path.join(root, str(name), time_s)
        if os.path.isdir(d):
            shutil.rmtree(d)
    _prune_dangling_symlinks(root)


def _prune_dangling_symlinks(root) -> None:
    """Drop latest/current links left dangling by delete()."""
    candidates = [os.path.join(root, "latest"), os.path.join(root, "current")]
    if os.path.isdir(root):
        candidates += [
            os.path.join(root, n, "latest")
            for n in os.listdir(root)
            if os.path.isdir(os.path.join(root, n))
        ]
    for link in candidates:
        if os.path.islink(link) and not os.path.isdir(os.path.realpath(link)):
            try:
                os.remove(link)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Logging (store.clj:394-418): a file handler on the framework's root
# logger for the duration of the run.

_LOG_FORMAT = "%(asctime)s\t%(levelname)s\t[%(threadName)s] %(name)s: %(message)s"


def start_logging(test) -> None:
    if not (test.get("name") and test.get("start_time")):
        return
    handler = logging.FileHandler(path_(test, "jepsen.log"))
    handler.setFormatter(logging.Formatter(_LOG_FORMAT))
    root = logging.getLogger("jepsen_tpu")
    test["_log_prev_level"] = root.level
    if root.getEffectiveLevel() > logging.INFO:
        root.setLevel(logging.INFO)
    root.addHandler(handler)
    test["_log_handler"] = handler
    update_current_symlink(test)


def stop_logging(test) -> None:
    handler = test.pop("_log_handler", None)
    if handler is not None:
        root = logging.getLogger("jepsen_tpu")
        root.removeHandler(handler)
        handler.close()
        prev = test.pop("_log_prev_level", None)
        if prev is not None:
            root.setLevel(prev)
