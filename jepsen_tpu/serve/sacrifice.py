"""Sacrificial execution of one suspected-poison job.

``python -m jepsen_tpu.serve.sacrifice <queue_dir> <job_id>``

The daemon's crash-blame record (serve/queue.py's attempt ledger)
names the jobs in flight when a previous process died; re-running one
of those in the daemon itself risks the same death. This module IS the
containment boundary: it rehydrates and checks exactly one job in a
fresh process and commits the verdict straight into the queue
directory with the same atomic-rename discipline, so a SIGKILL, OOM,
or FATAL XLA abort takes this child and nothing else. The parent
notices the commit (or its absence) on the disk — the verdict file
stays the single commit point regardless of which process wrote it.

Deliberately NOT a DurableQueue client: opening the queue would run
recovery, and recovery quarantines unanswered jobs whose attempts are
spent — including the very attempt this process is here to make.
"""

from __future__ import annotations

import logging
import os
import sys
import time

log = logging.getLogger("jepsen_tpu.serve.sacrifice")


def run_one(queue_dir: str, job_id: str) -> int:
    from .. import store
    from ..checker import check_safe
    from .daemon import _jsonable
    from .queue import JOBS_DIR, VERDICTS_DIR, DurableQueue
    from .registry import EngineRegistry, load_extra_workloads

    load_extra_workloads()
    spec = store.read_json_dict(
        os.path.join(queue_dir, JOBS_DIR, job_id + ".json"))
    if spec is None:
        log.error("no readable spec for %s", job_id)
        return 2
    verdict_path = os.path.join(queue_dir, VERDICTS_DIR,
                                job_id + ".json")
    if os.path.exists(verdict_path):
        return 0  # already committed by someone; nothing to do
    registry = EngineRegistry()
    wl = registry.workload(spec["workload"])
    test: dict = {"name": f"serve-{spec['workload']}"}
    remaining = DurableQueue.remaining_s(spec)
    verdict = None
    if remaining is not None:
        if remaining <= 0:
            verdict = {"valid": "unknown", "error": "deadline"}
        else:
            test["deadline"] = time.monotonic() + remaining
    if verdict is None:
        from ..history import Op, index as index_history

        ops = [Op.from_dict(d) for d in spec["history"]]
        if wl["rehydrate"] is not None:
            ops = [wl["rehydrate"](o) for o in ops]
        verdict = check_safe(wl["checker"], test, index_history(ops))
    store.atomic_write_json(verdict_path,
                            {"id": job_id, "verdict": _jsonable(verdict)})
    return 0


def main(argv: list) -> int:
    if len(argv) != 2:
        print("usage: python -m jepsen_tpu.serve.sacrifice "
              "<queue_dir> <job_id>", file=sys.stderr)
        return 2
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    return run_one(argv[0], argv[1])


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
