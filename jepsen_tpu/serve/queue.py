"""The durable work queue: exactly-once verdicts across SIGKILL.

Layout under one queue directory::

    jobs/<id>.json        the job spec (client, workload, history, seq)
    verdicts/<id>.json    the committed verdict

Both sides are written with the store module's write-temp → fsync →
rename discipline (``store.atomic_write_json``), so a kill at any
instant leaves each file either absent or complete — never torn. The
**verdict file is the commit point**: a job is done iff its verdict
file exists. A daemon SIGKILL'd mid-check restarts, rescans ``jobs/``,
finds the spec still unanswered, and re-runs it — re-running is safe
because checking is pure (same history, same verdict bits) and the
single atomic verdict write means the client can never observe two
answers. Nothing is ever lost (the spec was durable before submit
acknowledged) and nothing is double-verdicted (one file, one rename).

Admission control: ``max_pending`` bounds the backlog; past it,
``submit`` raises ``QueueFull`` carrying a retry-after hint instead of
buffering toward OOM — the daemon maps it to HTTP 429.

Fairness: ``take_batch`` drains clients weighted-round-robin — each
round, every client with waiting jobs contributes up to its weight in
submission order — so one chatty client cannot starve the rest, while
a client that paid for weight w gets w shares of every round.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from .. import store

log = logging.getLogger("jepsen_tpu.serve.queue")

JOBS_DIR = "jobs"
VERDICTS_DIR = "verdicts"

DEFAULT_MAX_PENDING = 256
DEFAULT_RETRY_AFTER_S = 5.0


class QueueFull(Exception):
    """Admission refused: the backlog is at max_pending."""

    def __init__(self, pending: int, retry_after_s: float):
        super().__init__(
            f"queue full ({pending} pending); retry in {retry_after_s}s")
        self.pending = pending
        self.retry_after_s = retry_after_s


class DurableQueue:
    def __init__(self, root: str, max_pending: int = DEFAULT_MAX_PENDING,
                 retry_after_s: float = DEFAULT_RETRY_AFTER_S):
        self.root = os.path.abspath(root)
        self.max_pending = max_pending
        self.retry_after_s = retry_after_s
        self._jobs_dir = os.path.join(self.root, JOBS_DIR)
        self._verdicts_dir = os.path.join(self.root, VERDICTS_DIR)
        os.makedirs(self._jobs_dir, exist_ok=True)
        os.makedirs(self._verdicts_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # crash recovery is just a directory scan: specs without
        # verdicts are the backlog, in submission (seq) order
        self._jobs: dict = {}      # id -> spec dict
        self._done: set = set()    # ids with committed verdicts
        self._seq = 0
        self._recover()

    # -- recovery ----------------------------------------------------------

    @staticmethod
    def _read_json(p: str):
        try:
            with open(p) as f:
                v = json.load(f)
            return v if isinstance(v, dict) else None
        except (OSError, ValueError):
            return None

    def _recover(self) -> None:
        """Rebuild in-memory state from the directories. ``.tmp``
        leftovers from a mid-rename kill are ignored (and later
        overwritten); an unparseable spec is quarantined by skipping —
        atomic writes should make that impossible, but a disk that
        lies must not wedge the daemon."""
        for fn in os.listdir(self._verdicts_dir):
            if fn.endswith(".json"):
                self._done.add(fn[:-5])
        n_stale = 0
        for fn in sorted(os.listdir(self._jobs_dir)):
            if not fn.endswith(".json"):
                continue
            spec = self._read_json(os.path.join(self._jobs_dir, fn))
            if spec is None or "id" not in spec:
                log.warning("queue recovery: skipping unreadable %s", fn)
                continue
            self._jobs[spec["id"]] = spec
            self._seq = max(self._seq, int(spec.get("seq", 0)) + 1)
            if spec["id"] not in self._done:
                n_stale += 1
        if n_stale:
            log.info("queue recovery: %d unanswered job(s) re-enqueued",
                     n_stale)

    # -- submission --------------------------------------------------------

    def pending_ids(self) -> list:
        with self._lock:
            return self._pending_ids_locked()

    def _pending_ids_locked(self) -> list:
        return sorted((j["id"] for j in self._jobs.values()
                       if j["id"] not in self._done),
                      key=lambda i: self._jobs[i].get("seq", 0))

    def submit(self, client: str, workload: str, history: list,
               weight: int = 1) -> str:
        """Durably enqueue one history. The spec hits disk (fsync'd)
        BEFORE the id is returned, so an acknowledged submission
        survives any kill. Raises QueueFull past max_pending."""
        with self._lock:
            pending = len(self._pending_ids_locked())
            if pending >= self.max_pending:
                raise QueueFull(pending, self.retry_after_s)
            seq = self._seq
            self._seq += 1
            job_id = f"{seq:08d}-{client}"
            spec = {"id": job_id, "seq": seq, "client": str(client),
                    "workload": str(workload),
                    "weight": max(1, int(weight)),
                    "history": list(history)}
            store.atomic_write_json(
                os.path.join(self._jobs_dir, job_id + ".json"), spec)
            self._jobs[job_id] = spec
            self._cv.notify_all()
        return job_id

    # -- scheduling --------------------------------------------------------

    def take_batch(self, max_jobs: int = 64) -> list:
        """Up to max_jobs pending specs, weighted round-robin across
        clients: rounds visit every client with waiting jobs (sorted
        for determinism) and take up to `weight` jobs each, oldest
        first. Jobs stay pending until commit() — a crash between
        take and commit re-runs them."""
        with self._lock:
            by_client: dict = {}
            for jid in self._pending_ids_locked():
                by_client.setdefault(
                    self._jobs[jid]["client"], []).append(jid)
            out: list = []
            while by_client and len(out) < max_jobs:
                for client in sorted(by_client):
                    lane = by_client.get(client)
                    if not lane:
                        by_client.pop(client, None)
                        continue
                    w = self._jobs[lane[0]].get("weight", 1)
                    for _ in range(max(1, int(w))):
                        if not lane or len(out) >= max_jobs:
                            break
                        out.append(self._jobs[lane.pop(0)])
                    if not lane:
                        by_client.pop(client, None)
            return out

    def wait_for_work(self, timeout: float | None = None) -> bool:
        """Block until at least one job is pending (or timeout)."""
        with self._lock:
            if self._pending_ids_locked():
                return True
            self._cv.wait(timeout)
            return bool(self._pending_ids_locked())

    # -- commit / read-back ------------------------------------------------

    def commit(self, job_id: str, verdict) -> None:
        """Atomically publish the verdict — THE commit point. A
        duplicate commit (crash replay racing a finished write) is a
        no-op: the first rename won."""
        with self._lock:
            if job_id in self._done:
                return
            store.atomic_write_json(
                os.path.join(self._verdicts_dir, job_id + ".json"),
                {"id": job_id, "verdict": verdict})
            self._done.add(job_id)
            self._cv.notify_all()

    def verdict(self, job_id: str):
        """The committed verdict dict, or None while pending. Unknown
        ids raise KeyError."""
        with self._lock:
            known = job_id in self._jobs
        if not known:
            # a verdict may outlive its spec in a pruned queue; check
            # disk before declaring the id unknown
            rec = self._read_json(
                os.path.join(self._verdicts_dir, job_id + ".json"))
            if rec is None:
                raise KeyError(job_id)
            return rec.get("verdict")
        rec = self._read_json(
            os.path.join(self._verdicts_dir, job_id + ".json"))
        return None if rec is None else rec.get("verdict")

    def wait_for_verdict(self, job_id: str, timeout: float | None = None):
        """Long-poll one verdict; None on timeout."""
        import time as _t

        deadline = None if timeout is None else _t.monotonic() + timeout
        with self._lock:
            while job_id not in self._done:
                if job_id not in self._jobs:
                    raise KeyError(job_id)
                remaining = (None if deadline is None
                             else deadline - _t.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(remaining)
        return self.verdict(job_id)

    def wait_for_commit_after(self, known: set,
                              timeout: float | None = None) -> list:
        """Ids committed that aren't in `known` — the verdict-stream
        endpoint's tail-follow primitive."""
        import time as _t

        deadline = None if timeout is None else _t.monotonic() + timeout
        with self._lock:
            while True:
                fresh = sorted(self._done - known)
                if fresh:
                    return fresh
                remaining = (None if deadline is None
                             else deadline - _t.monotonic())
                if remaining is not None and remaining <= 0:
                    return []
                self._cv.wait(remaining)

    def stats(self) -> dict:
        with self._lock:
            pending = self._pending_ids_locked()
            per_client: dict = {}
            for jid in pending:
                c = self._jobs[jid]["client"]
                per_client[c] = per_client.get(c, 0) + 1
            return {"pending": len(pending), "done": len(self._done),
                    "max_pending": self.max_pending,
                    "pending_per_client": per_client}
