"""The durable work queue: exactly-once verdicts across SIGKILL.

Layout under one queue directory::

    jobs/<id>.json        the job spec (client, workload, history, seq)
    verdicts/<id>.json    the committed verdict
    attempts.json         the attempt ledger + in-flight blame record

All of it is written with the store module's write-temp → fsync →
rename discipline (``store.atomic_write_json``), so a kill at any
instant leaves each file either absent or complete — never torn. The
**verdict file is the commit point**: a job is done iff its verdict
file exists. A daemon SIGKILL'd mid-check restarts, rescans ``jobs/``,
finds the spec still unanswered, and re-runs it — re-running is safe
because checking is pure (same history, same verdict bits) and the
single atomic verdict write means the client can never observe two
answers. Nothing is ever lost (the spec was durable before submit
acknowledged) and nothing is double-verdicted (one file, one rename).

Re-running is safe — but not always SURVIVABLE: a history that OOMs
the process, wedges a compile, or outright SIGKILLs the daemon would
be re-enqueued forever, a crash loop fed by its own recovery. The
**attempt ledger** bounds that: ``begin_attempts`` bumps each job's
attempt count and records the batch as in-flight, fsynced BEFORE
execution starts, so an attempt the job never survives still counts.
At recovery, any unanswered job with ``max_attempts`` recorded
attempts is dead-lettered — an ``{"valid": "unknown", "error":
"quarantined"}`` verdict committed through the one true commit point —
and jobs named in-flight by the previous process (the crash *blame*)
become suspects: ``take_batch`` skips them, so healthy work flows
first, and the daemon runs them last in a sacrificial subprocess.

Admission control: ``max_pending`` bounds the backlog; past it,
``submit`` raises ``QueueFull`` carrying a retry-after hint instead of
buffering toward OOM — the daemon maps it to HTTP 429.

Fairness: ``take_batch`` drains clients weighted-round-robin — each
round, every client with waiting jobs contributes up to its weight in
submission order — so one chatty client cannot starve the rest, while
a client that paid for weight w gets w shares of every round.
"""

from __future__ import annotations

import logging
import os
import threading

from .. import store

log = logging.getLogger("jepsen_tpu.serve.queue")

JOBS_DIR = "jobs"
VERDICTS_DIR = "verdicts"
ATTEMPTS_FILE = "attempts.json"

DEFAULT_MAX_PENDING = 256
DEFAULT_RETRY_AFTER_S = 5.0
DEFAULT_MAX_ATTEMPTS = 3

#: the dead-letter verdict every quarantined job commits
QUARANTINED_VERDICT = {"valid": "unknown", "error": "quarantined"}


class QueueFull(Exception):
    """Admission refused: the backlog is at max_pending."""

    def __init__(self, pending: int, retry_after_s: float):
        super().__init__(
            f"queue full ({pending} pending); retry in {retry_after_s}s")
        self.pending = pending
        self.retry_after_s = retry_after_s


class DurableQueue:
    def __init__(self, root: str, max_pending: int = DEFAULT_MAX_PENDING,
                 retry_after_s: float = DEFAULT_RETRY_AFTER_S,
                 max_attempts: int = DEFAULT_MAX_ATTEMPTS):
        self.root = os.path.abspath(root)
        self.max_pending = max_pending
        self.retry_after_s = retry_after_s
        self.max_attempts = max(1, int(max_attempts))
        self._jobs_dir = os.path.join(self.root, JOBS_DIR)
        self._verdicts_dir = os.path.join(self.root, VERDICTS_DIR)
        self._attempts_path = os.path.join(self.root, ATTEMPTS_FILE)
        os.makedirs(self._jobs_dir, exist_ok=True)
        os.makedirs(self._verdicts_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # crash recovery is just a directory scan: specs without
        # verdicts are the backlog, in submission (seq) order
        self._jobs: dict = {}        # id -> spec dict
        self._done: set = set()      # ids with committed verdicts
        self._attempts: dict = {}    # id -> attempts begun (durable)
        self._suspects: set = set()  # blamed in-flight by a dead run
        self._quarantined: set = set()  # dead-lettered ids
        self._seq = 0
        self._recover()

    # -- recovery ----------------------------------------------------------

    @staticmethod
    def _read_json(p: str):
        return store.read_json_dict(p)

    def _recover(self) -> None:
        """Rebuild in-memory state from the directories. ``.tmp``
        leftovers from a mid-rename kill are ignored (and later
        overwritten); an unparseable spec is quarantined by skipping —
        atomic writes should make that impossible, but a disk that
        lies must not wedge the daemon.

        The attempt ledger closes the crash loop: unanswered jobs
        that already burned ``max_attempts`` are dead-lettered here
        (the quarantine verdict commits through the normal commit
        point), and jobs the dead process had in flight become
        *suspects* — deferred by ``take_batch`` so a poison job can't
        take the healthy backlog down with it again."""
        for fn in os.listdir(self._verdicts_dir):
            if fn.endswith(".json"):
                self._done.add(fn[:-5])
        n_stale = 0
        for fn in sorted(os.listdir(self._jobs_dir)):
            if not fn.endswith(".json"):
                continue
            spec = self._read_json(os.path.join(self._jobs_dir, fn))
            if spec is None or "id" not in spec:
                log.warning("queue recovery: skipping unreadable %s", fn)
                continue
            self._jobs[spec["id"]] = spec
            self._seq = max(self._seq, int(spec.get("seq", 0)) + 1)
            if spec["id"] not in self._done:
                n_stale += 1
        ledger = self._read_json(self._attempts_path) or {}
        attempts = ledger.get("attempts")
        if isinstance(attempts, dict):
            self._attempts = {str(k): int(v) for k, v in attempts.items()
                              if str(k) in self._jobs}
        for jid in ledger.get("in_flight") or []:
            if jid in self._jobs and jid not in self._done:
                self._suspects.add(jid)
        with self._lock:  # _commit_locked notifies the condvar
            for jid, n in sorted(self._attempts.items()):
                if n < self.max_attempts:
                    continue
                self._quarantined.add(jid)
                if jid not in self._done:
                    log.warning("queue recovery: quarantining %s after "
                                "%d attempt(s)", jid, n)
                    self._commit_locked(jid, dict(QUARANTINED_VERDICT))
                self._suspects.discard(jid)
        if n_stale:
            log.info("queue recovery: %d unanswered job(s) re-enqueued"
                     " (%d suspect)", n_stale, len(self._suspects))

    # -- submission --------------------------------------------------------

    def pending_ids(self) -> list:
        with self._lock:
            return self._pending_ids_locked()

    def _pending_ids_locked(self) -> list:
        return sorted((j["id"] for j in self._jobs.values()
                       if j["id"] not in self._done),
                      key=lambda i: self._jobs[i].get("seq", 0))

    def submit(self, client: str, workload: str, history: list,
               weight: int = 1, deadline_ms: int | None = None) -> str:
        """Durably enqueue one history. The spec hits disk (fsync'd)
        BEFORE the id is returned, so an acknowledged submission
        survives any kill. Raises QueueFull past max_pending.

        ``deadline_ms`` is the client's total verdict budget, anchored
        at submission wall time (``submitted_at``) so a restarted
        daemon measures the same deadline the client was promised."""
        import time as _t

        with self._lock:
            pending = len(self._pending_ids_locked())
            if pending >= self.max_pending:
                raise QueueFull(pending, self.retry_after_s)
            seq = self._seq
            self._seq += 1
            job_id = f"{seq:08d}-{client}"
            spec = {"id": job_id, "seq": seq, "client": str(client),
                    "workload": str(workload),
                    "weight": max(1, int(weight)),
                    "history": list(history)}
            if deadline_ms is not None:
                spec["deadline_ms"] = max(1, int(deadline_ms))
                spec["submitted_at"] = _t.time()
            store.atomic_write_json(
                os.path.join(self._jobs_dir, job_id + ".json"), spec)
            self._jobs[job_id] = spec
            self._cv.notify_all()
        return job_id

    @staticmethod
    def remaining_s(spec: dict, now: float | None = None):
        """Seconds left on a spec's deadline (negative when expired),
        or None for the default no-deadline contract."""
        import time as _t

        if spec.get("deadline_ms") is None:
            return None
        anchor = float(spec.get("submitted_at") or 0.0)
        now = _t.time() if now is None else now
        return anchor + spec["deadline_ms"] / 1000.0 - now

    # -- scheduling --------------------------------------------------------

    def take_batch(self, max_jobs: int = 64) -> list:
        """Up to max_jobs pending specs, weighted round-robin across
        clients: rounds visit every client with waiting jobs (sorted
        for determinism) and take up to `weight` jobs each, oldest
        first. Jobs stay pending until commit() — a crash between
        take and commit re-runs them. Suspects (jobs blamed for a
        previous crash) are skipped: the daemon runs them LAST, in a
        sacrificial subprocess, once the healthy backlog has drained
        (``take_suspect``)."""
        with self._lock:
            by_client: dict = {}
            for jid in self._pending_ids_locked():
                if jid in self._suspects:
                    continue
                by_client.setdefault(
                    self._jobs[jid]["client"], []).append(jid)
            out: list = []
            while by_client and len(out) < max_jobs:
                for client in sorted(by_client):
                    lane = by_client.get(client)
                    if not lane:
                        by_client.pop(client, None)
                        continue
                    w = self._jobs[lane[0]].get("weight", 1)
                    for _ in range(max(1, int(w))):
                        if not lane or len(out) >= max_jobs:
                            break
                        out.append(self._jobs[lane.pop(0)])
                    if not lane:
                        by_client.pop(client, None)
            return out

    def take_suspect(self):
        """The oldest pending suspect spec, or None. Suspects are the
        jobs a dead daemon blamed (in flight when it died); the caller
        runs them in a sacrificial subprocess, never in-process."""
        with self._lock:
            for jid in self._pending_ids_locked():
                if jid in self._suspects:
                    return self._jobs[jid]
            return None

    def suspect_ids(self) -> list:
        with self._lock:
            return sorted(j for j in self._suspects
                          if j not in self._done)

    # -- the attempt ledger ------------------------------------------------

    def begin_attempts(self, ids: list) -> None:
        """Durably charge one attempt to every job in `ids` and blame
        them as in flight — fsynced BEFORE execution starts, so an
        attempt the process does not survive still counts (the whole
        point: SIGKILL'd attempts are the ones that matter). One
        ledger write covers the batch."""
        with self._lock:
            for jid in ids:
                self._attempts[jid] = self._attempts.get(jid, 0) + 1
            store.atomic_write_json(self._attempts_path, {
                "attempts": dict(self._attempts),
                "in_flight": list(ids)})

    def attempts_of(self, job_id: str) -> int:
        with self._lock:
            return self._attempts.get(job_id, 0)

    def quarantine(self, job_id: str) -> None:
        """Dead-letter a job: commit the quarantine verdict through
        the normal commit point and stop scheduling it."""
        log.warning("quarantining %s after %d attempt(s)", job_id,
                    self._attempts.get(job_id, 0))
        with self._lock:
            self._quarantined.add(job_id)
            self._commit_locked(job_id, dict(QUARANTINED_VERDICT))

    def quarantined_ids(self) -> list:
        with self._lock:
            return sorted(self._quarantined)

    def refresh_done(self, job_id: str) -> bool:
        """Notice a verdict committed by ANOTHER process (the
        sacrificial subprocess writes through its own queue handle):
        re-check the disk and absorb the commit. True iff done."""
        with self._lock:
            if job_id in self._done:
                return True
            rec = self._read_json(
                os.path.join(self._verdicts_dir, job_id + ".json"))
            if rec is None:
                return False
            self._done.add(job_id)
            self._suspects.discard(job_id)
            self._cv.notify_all()
            return True

    def wait_for_work(self, timeout: float | None = None) -> bool:
        """Block until at least one job is pending (or timeout)."""
        with self._lock:
            if self._pending_ids_locked():
                return True
            self._cv.wait(timeout)
            return bool(self._pending_ids_locked())

    # -- commit / read-back ------------------------------------------------

    def commit(self, job_id: str, verdict) -> None:
        """Atomically publish the verdict — THE commit point. A
        duplicate commit (crash replay racing a finished write) is a
        no-op: the first rename won."""
        with self._lock:
            self._commit_locked(job_id, verdict)

    def _commit_locked(self, job_id: str, verdict) -> None:
        if job_id in self._done:
            return
        store.atomic_write_json(
            os.path.join(self._verdicts_dir, job_id + ".json"),
            {"id": job_id, "verdict": verdict})
        self._done.add(job_id)
        self._suspects.discard(job_id)
        self._cv.notify_all()

    def verdict(self, job_id: str):
        """The committed verdict dict, or None while pending. Unknown
        ids raise KeyError."""
        with self._lock:
            known = job_id in self._jobs
        if not known:
            # a verdict may outlive its spec in a pruned queue; check
            # disk before declaring the id unknown
            rec = self._read_json(
                os.path.join(self._verdicts_dir, job_id + ".json"))
            if rec is None:
                raise KeyError(job_id)
            return rec.get("verdict")
        rec = self._read_json(
            os.path.join(self._verdicts_dir, job_id + ".json"))
        return None if rec is None else rec.get("verdict")

    def wait_for_verdict(self, job_id: str, timeout: float | None = None):
        """Long-poll one verdict; None on timeout."""
        import time as _t

        deadline = None if timeout is None else _t.monotonic() + timeout
        with self._lock:
            while job_id not in self._done:
                if job_id not in self._jobs:
                    raise KeyError(job_id)
                remaining = (None if deadline is None
                             else deadline - _t.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(remaining)
        return self.verdict(job_id)

    def wait_for_commit_after(self, known: set,
                              timeout: float | None = None) -> list:
        """Ids committed that aren't in `known` — the verdict-stream
        endpoint's tail-follow primitive."""
        import time as _t

        deadline = None if timeout is None else _t.monotonic() + timeout
        with self._lock:
            while True:
                fresh = sorted(self._done - known)
                if fresh:
                    return fresh
                remaining = (None if deadline is None
                             else deadline - _t.monotonic())
                if remaining is not None and remaining <= 0:
                    return []
                self._cv.wait(remaining)

    def stats(self) -> dict:
        with self._lock:
            pending = self._pending_ids_locked()
            per_client: dict = {}
            for jid in pending:
                c = self._jobs[jid]["client"]
                per_client[c] = per_client.get(c, 0) + 1
            return {"pending": len(pending), "done": len(self._done),
                    "max_pending": self.max_pending,
                    "pending_per_client": per_client,
                    "max_attempts": self.max_attempts,
                    "suspects": sorted(j for j in self._suspects
                                       if j not in self._done),
                    "quarantined": sorted(self._quarantined)}
