"""Resident verdict service: AOT-warmed engines behind a crash-safe,
backpressured check queue.

Every one-shot CLI run pays compile + calibration + arena setup per
invocation — fatal for short histories (cold compile regressed 3.3s →
8.8s as engines multiplied, ROADMAP item 1). This package keeps the
engines resident instead:

bundle.py    the AOT engine bundle: a version-stamped manifest
             (jax/backend/code digests) co-located with a pinned JAX
             persistent-compile-cache directory plus the persisted
             Calibration, so a warm daemon start skips both the
             multi-second compiles and the crossover re-measurement.
             A stale fingerprint rebuilds — never a wrong verdict.
registry.py  the session-scoped engine registry: one process-wide set
             of supervisors, breakers, arenas, and workload checkers
             shared across every queued request, with a combined
             health snapshot for the readiness endpoint.
queue.py     the durable work queue: job specs and verdicts as
             atomically-renamed JSON files (the store write-temp →
             fsync → rename discipline), so a SIGKILL'd daemon
             restarts with no lost and no double-verdicted work;
             weighted round-robin fairness across clients; bounded
             admission (reject-with-retry-after, not OOM).
daemon.py    the HTTP front end (`jepsen-tpu serve --daemon`):
             submit/verdict/stream endpoints, health/readiness wired
             to breaker and HBM state, cross-run batch packing of
             independent-key lanes (independent.pack_check), and
             SIGTERM graceful drain reusing core.DrainSignal.
"""

from .bundle import EngineBundle  # noqa: F401
from .queue import DurableQueue, QueueFull  # noqa: F401
from .registry import EngineRegistry  # noqa: F401


def __getattr__(name):
    # a live WAL is just another queue client (online/client.py); the
    # import stays lazy so serve/ itself remains checker-import-free
    if name == "QueueStreamClient":
        from ..online.client import QueueStreamClient

        return QueueStreamClient
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
