"""The resident verdict daemon: HTTP front end + check worker.

``jepsen-tpu serve --daemon`` mounts this beside the web-UI serve
subcommand. The daemon owns one EngineRegistry (warmed through the
AOT bundle), one DurableQueue, and one worker thread that drains the
queue in weighted-round-robin batches:

* jobs of a **packable** workload (independent-key histories) are
  cross-run batch packed — MANY clients' histories flatten into ONE
  batched engine pass via ``independent.pack_check``, which
  P-compositionality licenses (each key lane's verdict is independent
  of which run it arrived with) and the measured-crossover router
  prices (pooled lanes clear the pallas bar sooner than any one run's
  would);
* other workloads check per job through ``checker.check_safe``.

Endpoints (stdlib ThreadingHTTPServer, the web.py idiom)::

    POST /submit            {client, workload, history, weight?} -> {id}
                            429 + Retry-After when the queue is full,
                            503 + Retry-After while draining
    GET  /verdict/<id>      the committed verdict; 202 while pending
                            (?wait=SECONDS long-polls)
    GET  /stream            JSONL of verdicts as they commit
    GET  /healthz           liveness (200 while the process serves) +
                            the device mesh topology
    GET  /readyz            readiness: breaker + HBM + bundle state;
                            503 while draining
    GET  /stats             queue depth, per-client backlog, telemetry

SIGTERM drains via core.DrainSignal (the PR-5 machinery): the first
signal closes admission (submits get 503), lets the worker finish and
commit its in-flight batch — unanswered specs stay durable for the
next start — and exits 143; a second SIGTERM force-exits.

Failure containment (the attempt ledger in serve/queue.py):

* every batch charges its jobs one durable attempt BEFORE checking
  begins, so a history that SIGKILLs the daemon still burns attempts;
* after a crash, the blamed in-flight jobs are *suspects*: the worker
  drains the healthy backlog first (bit-identical verdicts — suspects
  never share a pack with healthy jobs), then re-runs each suspect in
  a **sacrificial subprocess** (serve/sacrifice.py) under capped
  exponential backoff, and quarantines it once ``max_attempts`` is
  spent — an ``unknown: quarantined`` verdict through the normal
  commit point;
* a job submitted with ``deadline_ms`` checks with the remaining
  budget stamped on its test (the supervisor's budget plumbing);
  expiry commits ``unknown: deadline`` instead of hanging;
* the worker thread itself is supervised: an uncaught exception is
  logged, counted, and the loop restarts under backoff — /healthz
  reports liveness and the last death cause.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

from .. import store
from ..checker import check_safe
from ..history import index as index_history, Op

log = logging.getLogger("jepsen_tpu.serve.daemon")

#: worker pacing knobs (env so the chaos driver can widen the window
#: between batches without patching code)
BATCH_MAX_ENV = "JEPSEN_TPU_SERVE_BATCH_MAX"
PACE_ENV = "JEPSEN_TPU_SERVE_PACE_S"
#: containment knobs
MAX_ATTEMPTS_ENV = "JEPSEN_TPU_SERVE_MAX_ATTEMPTS"
SUSPECT_BACKOFF_ENV = "JEPSEN_TPU_SERVE_SUSPECT_BACKOFF_S"
SUSPECT_TIMEOUT_ENV = "JEPSEN_TPU_SERVE_SUSPECT_TIMEOUT_S"
SUSPECT_BACKOFF_CAP_S = 30.0
DEFAULT_SUSPECT_TIMEOUT_S = 600.0


def _jsonable(v):
    """Verdicts normalized exactly as store.write_json persists them
    (results.json round trip), so a daemon verdict compares bit-for-
    bit against a one-shot run's stored results."""
    return json.loads(json.dumps(store._json_keys(v),
                                 default=store._json_default))


class VerdictDaemon:
    """Queue + registry + the single check worker."""

    def __init__(self, queue, registry, batch_max: int = 64,
                 pace_s: float = 0.0):
        self.queue = queue
        self.registry = registry
        self.batch_max = int(
            os.environ.get(BATCH_MAX_ENV) or batch_max)
        self.pace_s = float(os.environ.get(PACE_ENV) or pace_s)
        self.draining = threading.Event()
        self.ready = threading.Event()
        self._worker_lock = threading.Lock()
        self.worker_deaths = 0
        self.last_death: dict | None = None
        self._worker = threading.Thread(
            target=self._run_guarded, name="serve verdict worker",
            daemon=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._worker.start()

    def worker_state(self) -> dict:
        """Liveness + death history for /healthz: a daemon whose
        worker died silently used to accept jobs it would never run."""
        with self._worker_lock:
            return {"alive": self._worker.is_alive(),
                    "deaths": self.worker_deaths,
                    "last_death": self.last_death}

    def ensure_worker(self) -> None:
        """Respawn the worker thread if it is outright dead (the guard
        loop catches Exceptions, so this only fires on the exotic
        ways a thread dies for real). Called from request handlers —
        accepting a job implies someone will run it."""
        with self._worker_lock:
            if self._worker.is_alive() or self.draining.is_set() \
                    or not self.ready.is_set():
                return
            log.error("verdict worker thread is dead; respawning")
            self._worker = threading.Thread(
                target=self._run_guarded, name="serve verdict worker",
                daemon=True)
            self._worker.start()

    def drain(self) -> bool:
        """First-SIGTERM hook: close admission, let the in-flight
        batch commit, stop. Always initiates (returns True)."""
        self.draining.set()
        with self.queue._cv:
            self.queue._cv.notify_all()
        return True

    def join(self, timeout: float | None = None) -> None:
        self._worker.join(timeout)

    # -- the check loop ----------------------------------------------------

    def _rehydrate(self, spec) -> list:
        wl = self.registry.workload(spec["workload"])
        ops = [Op.from_dict(d) for d in spec["history"]]
        if wl["rehydrate"] is not None:
            ops = [wl["rehydrate"](o) for o in ops]
        return index_history(ops)

    def _check_group(self, workload: str, specs: list) -> list:
        """Verdicts for one workload's batch of specs, aligned. The
        test stub carries no start_time, so checkers write no
        artifacts — the verdict file is the daemon's artifact."""
        wl = self.registry.workload(workload)
        test = {"name": f"serve-{workload}"}
        histories = [self._rehydrate(s) for s in specs]
        if wl.get("packable") and len(histories) > 1:
            from .. import independent

            return independent.pack_check(wl["checker"], test, histories)
        return [check_safe(wl["checker"], test, h) for h in histories]

    def _run_guarded(self) -> None:
        """The worker thread body: _run() under a crash guard. An
        uncaught exception is a worker death — logged, counted for
        /healthz, and the loop restarts under capped backoff instead
        of leaving a daemon that accepts jobs it will never run."""
        while True:
            try:
                self._run()
                return  # clean drain exit
            except Exception as e:  # noqa: BLE001 — anything else is
                #                     a thread death we must survive
                with self._worker_lock:
                    self.worker_deaths += 1
                    deaths = self.worker_deaths
                    self.last_death = {
                        "error": f"{type(e).__name__}: {e}",
                        "time": time.time()}
                log.exception("verdict worker died (death #%d); "
                              "restarting", deaths)
                if self.draining.is_set():
                    return
                time.sleep(min(5.0, 0.1 * (2 ** min(deaths, 6))))

    def _check_deadline_spec(self, spec, remaining: float) -> None:
        """One deadline'd job, checked individually — NEVER packed (a
        pack shares one launch; a tight deadline must not drag sibling
        jobs to unknown) — with the remaining budget stamped on the
        test, which the linearizable checker threads into
        Supervisor.call/run as a hard budget. Partial per-key results
        are salvaged; expiry commits ``unknown: deadline``."""
        workload = spec["workload"]
        wl = self.registry.workload(workload)
        test = {"name": f"serve-{workload}",
                "deadline": time.monotonic() + remaining}
        try:
            h = self._rehydrate(spec)
            verdict = check_safe(wl["checker"], test, h)
        except Exception:  # noqa: BLE001
            log.exception("workload %s deadline job failed", workload)
            verdict = {"valid": "unknown",
                       "error": f"workload {workload} failed"}
        self.queue.commit(spec["id"], _jsonable(verdict))

    def _handle_suspect(self) -> bool:
        """Run ONE suspect (a job blamed for a previous crash) in a
        sacrificial subprocess, or quarantine it when its attempts are
        spent. Returns True when a suspect was handled."""
        spec = self.queue.take_suspect()
        if spec is None:
            return False
        jid = spec["id"]
        n = self.queue.attempts_of(jid)
        if n >= self.queue.max_attempts:
            self.queue.quarantine(jid)
            return True
        # capped exponential backoff on the attempt number: a poison
        # job must not turn the restart loop into a tight crash loop
        base = float(os.environ.get(SUSPECT_BACKOFF_ENV) or 1.0)
        time.sleep(min(SUSPECT_BACKOFF_CAP_S,
                       base * (2 ** max(0, n - 1))))
        self.queue.begin_attempts([jid])
        self._run_sacrificial(spec)
        if not self.queue.refresh_done(jid) \
                and self.queue.attempts_of(jid) >= self.queue.max_attempts:
            self.queue.quarantine(jid)
        return True

    def _run_sacrificial(self, spec) -> None:
        """python -m jepsen_tpu.serve.sacrifice <queue> <id>: the
        subprocess rehydrates and checks the job, committing its
        verdict straight to the queue directory — a SIGKILL, OOM, or
        FATAL abort takes the child, not the daemon."""
        import subprocess
        import sys

        jid = spec["id"]
        remaining = self.queue.remaining_s(spec)
        timeout = float(os.environ.get(SUSPECT_TIMEOUT_ENV)
                        or DEFAULT_SUSPECT_TIMEOUT_S)
        if remaining is not None:
            timeout = min(timeout, max(1.0, remaining))
        log.warning("running suspect %s in a sacrificial subprocess "
                    "(attempt %d/%d)", jid, self.queue.attempts_of(jid),
                    self.queue.max_attempts)
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "jepsen_tpu.serve.sacrifice",
                 self.queue.root, jid],
                capture_output=True, text=True, timeout=timeout)
            if proc.returncode != 0:
                log.warning("sacrificial check of %s died rc=%s: %s",
                            jid, proc.returncode,
                            (proc.stderr or "")[-500:])
        except subprocess.TimeoutExpired:
            log.warning("sacrificial check of %s timed out after %.1fs",
                        jid, timeout)
        except OSError as e:
            log.warning("sacrificial check of %s failed to launch: %s",
                        jid, e)

    def _run(self) -> None:
        self.ready.set()
        while True:
            if not self.queue.wait_for_work(timeout=0.5):
                if self.draining.is_set():
                    return
                continue
            batch = self.queue.take_batch(self.batch_max)
            if not batch:
                if self.draining.is_set():
                    # suspects stay durable (and blamed) for the next
                    # start; drain must not wait out their backoff
                    return
                if not self._handle_suspect():
                    time.sleep(0.05)
                continue
            # the durable attempt ledger: one fsync charges the whole
            # batch BEFORE checking starts, so an attempt the process
            # does not survive still counts (and names its suspects)
            self.queue.begin_attempts([s["id"] for s in batch])
            by_workload: dict = {}
            now = time.time()
            for spec in batch:
                remaining = self.queue.remaining_s(spec, now)
                if remaining is None:
                    by_workload.setdefault(
                        spec["workload"], []).append(spec)
                elif remaining <= 0:
                    log.warning("job %s deadline expired before "
                                "checking began", spec["id"])
                    self.queue.commit(spec["id"], {"valid": "unknown",
                                                   "error": "deadline"})
                else:
                    self._check_deadline_spec(spec, remaining)
            for workload, specs in by_workload.items():
                try:
                    verdicts = self._check_group(workload, specs)
                except Exception:  # noqa: BLE001 — a broken workload
                    #               must not wedge the whole queue
                    log.exception("workload %s batch failed", workload)
                    verdicts = [{"valid": "unknown",
                                 "error": f"workload {workload} failed"}
                                for _ in specs]
                for spec, verdict in zip(specs, verdicts):
                    self.queue.commit(spec["id"], _jsonable(verdict))
            if self.pace_s:
                time.sleep(self.pace_s)
            if self.draining.is_set():
                # in-flight work committed; leftover specs stay
                # durable for the next start
                return


class _Handler(BaseHTTPRequestHandler):
    daemon_obj: VerdictDaemon = None  # set by serve()
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.debug("%s %s", self.address_string(), fmt % args)

    def _send_json(self, code: int, payload, extra_headers=()) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    # -- POST /submit ------------------------------------------------------

    def do_POST(self):  # noqa: N802
        try:
            self._post()
        except BrokenPipeError:
            pass
        except Exception:  # noqa: BLE001
            log.exception("error serving %s", self.path)
            self._send_json(500, {"error": "internal error"})

    def _post(self):
        d = self.daemon_obj
        path = urlparse(self.path).path
        if path != "/submit":
            return self._send_json(404, {"error": "not found"})
        if d.draining.is_set():
            return self._send_json(
                503, {"error": "draining",
                      "retry_after_s": d.queue.retry_after_s},
                [("Retry-After", str(int(d.queue.retry_after_s) or 1))])
        try:
            n = int(self.headers.get("Content-Length", 0))
            spec = json.loads(self.rfile.read(n))
            client = str(spec["client"])
            workload = str(spec["workload"])
            history = spec["history"]
            weight = int(spec.get("weight", 1))
            deadline_ms = spec.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = int(deadline_ms)
                assert deadline_ms > 0
            assert isinstance(history, list)
        except Exception:  # noqa: BLE001 — malformed submission
            return self._send_json(400, {"error": "bad submission"})
        try:
            d.registry.workload(workload)
        except KeyError:
            return self._send_json(
                400, {"error": f"unknown workload {workload!r}",
                      "workloads": d.registry.known_workloads()})
        from .queue import QueueFull

        d.ensure_worker()  # accepting a job implies someone runs it
        try:
            job_id = d.queue.submit(client, workload, history,
                                    weight=weight,
                                    deadline_ms=deadline_ms)
        except QueueFull as e:
            # bounded-queue backpressure: reject with a retry hint
            # rather than buffering toward OOM
            return self._send_json(
                429, {"error": "queue full", "pending": e.pending,
                      "retry_after_s": e.retry_after_s},
                [("Retry-After", str(int(e.retry_after_s) or 1))])
        return self._send_json(200, {"id": job_id})

    # -- GETs --------------------------------------------------------------

    def do_GET(self):  # noqa: N802
        try:
            self._get()
        except BrokenPipeError:
            pass
        except Exception:  # noqa: BLE001
            log.exception("error serving %s", self.path)
            self._send_json(500, {"error": "internal error"})

    def _get(self):
        d = self.daemon_obj
        url = urlparse(self.path)
        path = url.path
        if path == "/healthz":
            from .registry import EngineRegistry

            d.ensure_worker()
            worker = d.worker_state()
            # a drained worker exits on purpose; only an unexpected
            # death flips liveness
            ok = worker["alive"] or d.draining.is_set()
            return self._send_json(
                200, {"ok": ok,
                      "mesh": EngineRegistry.mesh_topology(),
                      "worker": worker,
                      "quarantined": d.queue.quarantined_ids()})
        if path == "/readyz":
            health = d.registry.health()
            health["draining"] = d.draining.is_set()
            code = 503 if (d.draining.is_set()
                           or not d.ready.is_set()) else 200
            return self._send_json(code, health)
        if path == "/stats":
            stats = d.queue.stats()
            stats["draining"] = d.draining.is_set()
            stats["supervision"] = \
                d.registry.supervisor.telemetry.snapshot()
            return self._send_json(200, stats)
        if path.startswith("/verdict/"):
            job_id = unquote(path[len("/verdict/"):])
            q = parse_qs(url.query)
            wait = float(q.get("wait", ["0"])[0])
            try:
                v = (d.queue.wait_for_verdict(job_id, timeout=wait)
                     if wait > 0 else d.queue.verdict(job_id))
            except KeyError:
                return self._send_json(404, {"error": "unknown job"})
            if v is None:
                return self._send_json(202, {"id": job_id,
                                             "state": "pending"})
            return self._send_json(200, {"id": job_id, "verdict": v})
        if path == "/stream":
            return self._stream()
        return self._send_json(404, {"error": "not found"})

    def _stream(self):
        """Stream verdicts as they commit, one JSON object per line,
        until the daemon drains (or the client hangs up). Starts from
        the already-committed set so a reconnecting client misses
        nothing."""
        d = self.daemon_obj
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonl")
        self.send_header("Connection", "close")
        self.end_headers()
        known: set = set()
        while True:
            fresh = d.queue.wait_for_commit_after(known, timeout=0.5)
            for jid in fresh:
                known.add(jid)
                rec = {"id": jid, "verdict": d.queue.verdict(jid)}
                self.wfile.write(json.dumps(rec).encode() + b"\n")
            self.wfile.flush()
            if not fresh and d.draining.is_set():
                return


def serve(queue, registry, host="127.0.0.1", port=0,
          batch_max: int = 64,
          pace_s: float = 0.0) -> tuple:
    """Start the daemon: worker + HTTP server (daemon threads).
    Returns (server, daemon); bound port at server.server_port."""
    daemon = VerdictDaemon(queue, registry, batch_max=batch_max,
                           pace_s=pace_s)
    handler = type("Handler", (_Handler,), {"daemon_obj": daemon})
    server = ThreadingHTTPServer((host, port), handler)
    daemon.start()
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="serve http")
    t.start()
    return server, daemon


def run_daemon(opts: dict) -> int:
    """The `serve --daemon` body: warm the bundle, recover the queue,
    serve until SIGTERM, drain, exit 143 (or 0 on ctrl-C)."""
    from .. import web
    from .bundle import EngineBundle
    from .queue import (DEFAULT_MAX_ATTEMPTS, DEFAULT_MAX_PENDING,
                        DurableQueue)
    from .registry import EngineRegistry, load_extra_workloads

    load_extra_workloads()
    queue_dir = opts.get("queue_dir") or os.path.join(
        opts.get("store_dir") or store.BASE_DIR, "serve-queue")
    bundle_dir = opts.get("bundle_dir")
    bundle = None
    if (bundle_dir or "").lower() not in ("off", "none", "0"):
        bundle = EngineBundle(bundle_dir or os.path.join(
            os.path.expanduser("~"), ".cache", "jepsen-tpu", "bundle"))
    registry = EngineRegistry(bundle)
    state = registry.warm()
    if state:
        log.info("engine bundle %s in %.2fs",
                 "warm" if state.get("warm") else "built",
                 state.get("elapsed_s") or 0.0)
    # Finish jax's import BEFORE the server and worker threads exist:
    # a /healthz handler importing jax (mesh_topology) concurrently
    # with the worker's first engine import races jax.numpy's partial
    # initialization, and the AttributeError is swallowed by engine
    # eligibility probes — silent routing drift, not a crash.
    EngineRegistry.mesh_topology()
    queue = DurableQueue(
        queue_dir,
        max_pending=int(opts.get("max_pending") or DEFAULT_MAX_PENDING),
        max_attempts=int(opts.get("max_attempts")
                         or os.environ.get(MAX_ATTEMPTS_ENV)
                         or DEFAULT_MAX_ATTEMPTS))
    server, daemon = serve(
        queue, registry, host=opts.get("host") or "127.0.0.1",
        port=int(opts.get("port") or 8181))
    log.info("verdict daemon on http://%s:%s/ (queue at %s)",
             opts.get("host") or "127.0.0.1", server.server_port,
             queue_dir)
    code = web.serve_until_signal(server, on_drain=daemon.drain,
                                  what="verdict daemon")
    # the drain hook closed admission; give the worker a bounded
    # window to commit its in-flight batch before the process exits
    daemon.draining.set()
    daemon.join(timeout=60)
    return code
