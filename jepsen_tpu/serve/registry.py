"""The session-scoped engine registry.

Before the resident service, every ``core.run``/``Linearizable.check``
reached the process-wide supervisors through ``checker.supervisor``'s
singletons implicitly, and nothing owned the set as a unit. The
registry lifts that ownership to a session object the daemon holds for
its whole life: ONE search supervisor, ONE closure supervisor (their
circuit breakers, telemetry, and the pallas ``_HostArena`` pool keep
state across requests — two clients hitting a quarantined engine both
ride the demoted rung instead of re-tripping it), the active AOT
bundle, and the workload table that maps submitted job specs to
checker instances.

The registry deliberately DELEGATES to the ``checker.supervisor``
singletons rather than building private Supervisors: breaker state
must be shared with any in-process one-shot check (and with
calibration's health gate), and those all route through ``get()``.
"""

from __future__ import annotations

import logging
import threading

log = logging.getLogger("jepsen_tpu.serve.registry")


def _register_workload() -> dict:
    """Keyed CAS-register histories: the independent checker over the
    linearizable search — the exact checker a one-shot
    `independent.checker(linearizable(CASRegister()))` run builds, so
    daemon verdicts and CLI verdicts are the same computation."""
    from ..checker import linearizable
    from ..independent import checker as indep_checker, tuple_
    from ..models import CASRegister

    def rehydrate(op):
        # HTTP submissions arrive as JSON: KVTuple values flattened to
        # [k, v] lists. Client ops of this workload are ALWAYS keyed,
        # so any 2-element list value on a client op rebuilds the
        # tuple; nemesis/info ops pass through.
        v = op.value
        if (op.process != "nemesis" and isinstance(v, (list, tuple))
                and len(v) == 2):
            return op.with_(value=tuple_(v[0], v[1]))
        return op

    return {"checker": indep_checker(linearizable(CASRegister(None))),
            "rehydrate": rehydrate,
            "packable": True}


def _cycle_workload() -> dict:
    """Transactional list-append histories for the cycle checker; txn
    values are JSON-native nested lists and need no rehydration."""
    from ..checker import cycle

    return {"checker": cycle.checker(),
            "rehydrate": None,
            "packable": False}


#: workload name -> spec factory; a job spec's "workload" field picks
#: one. Factories run lazily so importing serve/ stays jax-free.
WORKLOAD_FACTORIES = {
    "register": _register_workload,
    "cycle": _cycle_workload,
}

#: comma-separated module names registering extra workload factories
#: (imported for their WORKLOAD_FACTORIES side effects); the chaos
#: tests inject poison/hang workloads this way
WORKLOADS_ENV = "JEPSEN_TPU_SERVE_WORKLOADS"


def load_extra_workloads() -> list:
    """Import every module named by JEPSEN_TPU_SERVE_WORKLOADS; each
    registers its factories into WORKLOAD_FACTORIES at import time.
    Called by the daemon AND the sacrificial subprocess, so a job's
    workload exists wherever the job runs."""
    import importlib
    import os

    mods = []
    for name in (os.environ.get(WORKLOADS_ENV) or "").split(","):
        name = name.strip()
        if not name:
            continue
        try:
            mods.append(importlib.import_module(name))
        except ImportError:
            log.exception("cannot import workloads module %s", name)
    return mods


class EngineRegistry:
    """One session's shared engines + workloads + bundle state."""

    def __init__(self, bundle=None):
        self.bundle = bundle           # serve.bundle.EngineBundle | None
        self.bundle_state: dict = {}   # EngineBundle.ensure() result
        self._workloads: dict = {}
        self._lock = threading.Lock()

    # -- engines (the process-wide supervisors) ---------------------------

    @property
    def supervisor(self):
        from ..checker import supervisor as sup_mod

        return sup_mod.get()

    @property
    def closure_supervisor(self):
        from ..checker import supervisor as sup_mod

        return sup_mod.get_closure()

    # -- bundle ------------------------------------------------------------

    def warm(self) -> dict:
        """Activate + warm the bundle (no-op without one). Returns the
        ensure() result; ``elapsed_s`` is this start's cold_compile_s."""
        if self.bundle is not None:
            self.bundle_state = self.bundle.ensure()
        return self.bundle_state

    # -- workloads ---------------------------------------------------------

    def workload(self, name: str) -> dict:
        """The (cached) workload spec for a job's workload name."""
        with self._lock:
            spec = self._workloads.get(name)
            if spec is None:
                factory = WORKLOAD_FACTORIES.get(name)
                if factory is None:
                    raise KeyError(f"unknown workload {name!r}")
                spec = factory()
                self._workloads[name] = spec
            return spec

    def known_workloads(self) -> list:
        return sorted(WORKLOAD_FACTORIES)

    # -- health ------------------------------------------------------------

    @staticmethod
    def _hbm_state() -> dict | None:
        """Device memory stats when the backend exposes them (TPU HBM;
        CPU backends usually return None) — surfaced on /readyz so
        orchestrators can rotate a daemon whose HBM is fragmenting."""
        try:
            import jax

            dev = jax.devices()[0]
            stats = dev.memory_stats()
            if not stats:
                return None
            out = {k: int(v) for k, v in stats.items()
                   if k in ("bytes_in_use", "bytes_limit",
                            "peak_bytes_in_use", "largest_free_block_bytes")}
            return out or None
        except Exception:  # noqa: BLE001 — stats are optional
            return None

    _mesh_topology_cache: dict | None = None

    @classmethod
    def mesh_topology(cls) -> dict:
        """The device mesh this daemon checks on — platform, device
        count/kinds, and which mesh rungs the supervisors have
        registered — for /healthz (tools/mesh_doctor reports the same
        shape). Static per process, so computed once: /healthz is a
        liveness probe and must stay cheap."""
        if cls._mesh_topology_cache is not None:
            return cls._mesh_topology_cache
        topo: dict = {"devices": 0, "platform": None, "kinds": []}
        try:
            import jax

            devs = jax.devices()
            topo = {
                "devices": len(devs),
                "platform": str(devs[0].platform),
                "kinds": sorted({str(getattr(d, "device_kind", d))
                                 for d in devs}),
            }
        except Exception:  # noqa: BLE001 — no usable backend
            pass
        from ..checker import supervisor as sup_mod

        topo["mesh_rungs"] = {
            "wgl_mesh": "wgl_mesh" in sup_mod.get().registry,
            "closure_mesh":
                "closure_mesh" in sup_mod.get_closure().registry,
        }
        cls._mesh_topology_cache = topo
        return topo

    def health(self) -> dict:
        """The combined readiness picture: both supervisors'
        per-engine breaker state + telemetry, bundle warmth, HBM."""
        out = {
            "search": self.supervisor.health_snapshot(),
            "closure": self.closure_supervisor.health_snapshot(),
            "bundle": {
                "present": self.bundle is not None,
                "warm": bool(self.bundle_state.get("warm")),
                "elapsed_s": self.bundle_state.get("elapsed_s"),
            },
        }
        hbm = self._hbm_state()
        if hbm:
            out["hbm"] = hbm
        out["degraded"] = bool(out["search"]["degraded"]
                               or out["closure"]["degraded"])
        return out
