"""The AOT engine bundle: warm starts that skip compile AND
calibration, never at the cost of a wrong verdict.

A bundle directory holds three things:

``bundle.json``
    the manifest: a **fingerprint** (jax/jaxlib versions, backend
    platform + device kind/count, a code digest over the kernel
    modules, and the bundle format version), the persisted
    ``Calibration`` measurement (when one exists), and the list of
    shape buckets that were warmed.
``xla-cache/``
    a JAX persistent compilation cache pinned INSIDE the bundle, so
    the compiles the warm pass runs are exactly the compiles later
    checks hit.
``calibration.json``
    the calibrate module's own disk cache, pointed here while the
    bundle is active so the daemon and one-shot runs under the same
    bundle share one measurement.

Warming runs ``jit(...).lower(...).compile()``-shaped work — each
engine's minimal probe plus one compile per enumerated shape bucket
(the power-of-two pads the search kernels and the closure engine
bucket by) — through the REAL engine entry points, so the persistent
cache is populated under the very keys production checks look up. A
later process that calls ``ensure()`` against a **fresh** manifest
only replays those compiles against the disk cache (sub-second); a
**stale** manifest (any fingerprint field changed: new jax, different
device generation, edited kernel code) is rebuilt from scratch. The
fingerprint is deliberately conservative: the persistent cache already
keys on program content, so a false-stale costs seconds while a
false-fresh could at worst serve a verdict computed by old code —
which is why staleness always rebuilds and never "best-efforts".
"""

from __future__ import annotations

import atexit
import hashlib
import json
import logging
import os
import threading
import time

log = logging.getLogger("jepsen_tpu.serve.bundle")

MANIFEST_FILE = "bundle.json"
XLA_CACHE_DIR = "xla-cache"
CALIB_CACHE_FILE = "calibration.json"

#: bump on any change to what warming covers or how the manifest reads
BUNDLE_FORMAT = 1

#: modules whose source participates in the code digest — the kernel
#: and encoding code whose edits must invalidate warmed compiles
_DIGEST_MODULES = (
    "jepsen_tpu.ops",
    "jepsen_tpu.ops.wgl_tpu",
    "jepsen_tpu.ops.wgl_pallas_vec",
    "jepsen_tpu.ops.closure_tpu",
    "jepsen_tpu.models.jit",
)


def code_digest() -> str:
    """sha1 over the kernel modules' source bytes (resolved without
    importing them — digesting must not cost a jax import)."""
    import importlib.util

    h = hashlib.sha1()
    for name in _DIGEST_MODULES:
        try:
            spec = importlib.util.find_spec(name)
            origin = spec.origin if spec else None
        except (ImportError, ValueError):
            origin = None
        h.update(name.encode())
        if origin and os.path.exists(origin):
            with open(origin, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def fingerprint() -> dict:
    """Everything that can silently change what a compiled engine
    computes or how fast it runs: code, jax build, backend identity."""
    from ..checker import calibrate

    fp = {"format": BUNDLE_FORMAT, "code": code_digest()}
    fp.update(calibrate.device_fingerprint())
    try:
        import jaxlib

        fp["jaxlib"] = str(jaxlib.__version__)
    except Exception:  # noqa: BLE001 — jaxlib version is best-effort
        pass
    return fp


def default_buckets() -> dict:
    """The shape buckets the warm pass compiles, by engine family.

    ``search`` lists n_pad buckets (the power-of-two history pads of
    ops/wgl_tpu and the pallas lane kernel, min 32); ``closure`` lists
    adjacency pads (ops/closure_tpu, min 32). Kept to the small
    buckets one-shot runs and the calibration lanes actually hit —
    every extra bucket is compile seconds on the cold path for cache
    bytes the warm path may never read.

    With a multi-device backend, ``search_mesh``/``closure_mesh``
    pre-warm the shard-mapped mesh rungs too (the fingerprint's
    device-count field already invalidates these when the mesh
    changes)."""
    b: dict = {"search": [32, 64], "closure": [32, 64]}
    try:
        import jax

        if jax.device_count() > 1:
            b["search_mesh"] = [32]
            b["closure_mesh"] = [64]
    except Exception:  # noqa: BLE001 — no usable backend yet
        pass
    return b


def _probe_search_bucket(n_pad: int) -> None:
    """One real search-engine compile in the `n_pad` history bucket:
    a tiny CAS-register history padded (by op count) to land exactly
    in that bucket, run through wgl_tpu.analysis — the same jit entry
    production batches hit."""
    from ..history import entries as make_entries, index, invoke_op, ok_op
    from ..models import CASRegister
    from ..ops import wgl_tpu

    # n_pad entries pad to exactly n_pad (pow2, >= 32); each entry is
    # an invoke/ok pair. Writes of distinct values keep the search
    # trivial — warming measures compiles, not searches.
    n_entries = max(1, n_pad // 2)
    ops = []
    for i in range(n_entries):
        ops.append(invoke_op(0, "write", i))
        ops.append(ok_op(0, "write", i))
    es = make_entries(index(ops))
    wgl_tpu.analysis(CASRegister(None), es, max_steps=10_000)


def _probe_closure_bucket(pad: int) -> None:
    """One closure-engine compile in the `pad` adjacency bucket."""
    import numpy as np

    from ..ops import closure_tpu

    n = max(3, pad // 2 + 1)  # pads to exactly `pad` (pow2, >= 32)
    a = np.zeros((n, n), dtype=bool)
    a[0, 1] = a[1, 0] = True
    closure_tpu.reach(a)


def _probe_search_mesh_bucket(n_pad: int) -> None:
    """One mesh-dealt search compile in the bucket: an uneven lane
    batch sharded over every addressable device — the wgl_mesh rung's
    launch shape, through the same analysis_batch entry."""
    import jax

    from ..history import entries as make_entries, index, invoke_op, ok_op
    from ..models import CASRegister
    from ..ops import wgl_tpu

    devices = jax.devices()
    n_entries = max(1, n_pad // 2)
    ess = []
    for _ in range(2 * len(devices) + 1):
        ops = []
        for i in range(n_entries):
            ops.append(invoke_op(0, "write", i))
            ops.append(ok_op(0, "write", i))
        ess.append(make_entries(index(ops)))
    wgl_tpu.analysis_batch(CASRegister(None), ess, max_steps=10_000,
                           devices=devices)


def _probe_closure_mesh_bucket(pad: int) -> None:
    """One sharded-squaring compile in the `pad` bucket (the
    closure_mesh rung)."""
    import numpy as np

    import jax

    from ..ops import closure_tpu

    n = max(3, pad // 2 + 1)
    a = np.zeros((n, n), dtype=bool)
    a[0, 1] = a[1, 0] = True
    closure_tpu.reach_batch([a], devices=jax.devices())


class EngineBundle:
    """One bundle directory: manifest + pinned compile cache +
    persisted calibration. ``ensure()`` is the only entry point the
    daemon (and bench) need: it activates the bundle's caches, decides
    fresh-vs-stale, and warms accordingly."""

    def __init__(self, root: str, buckets: dict | None = None):
        self.root = os.path.abspath(root)
        self.buckets = buckets or default_buckets()

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_FILE)

    @property
    def xla_cache_dir(self) -> str:
        return os.path.join(self.root, XLA_CACHE_DIR)

    @property
    def calib_cache_path(self) -> str:
        return os.path.join(self.root, CALIB_CACHE_FILE)

    def load_manifest(self) -> dict | None:
        try:
            with open(self.manifest_path) as f:
                m = json.load(f)
            return m if isinstance(m, dict) else None
        except (OSError, ValueError):
            return None

    def is_fresh(self, manifest: dict | None = None) -> bool:
        """Stale on ANY fingerprint mismatch — rebuild, never a wrong
        (or wrongly-priced) verdict."""
        m = manifest if manifest is not None else self.load_manifest()
        return bool(m) and m.get("fingerprint") == fingerprint()

    # -- activation --------------------------------------------------------

    def _activate_caches(self) -> None:
        """Pin the process's persistent compile cache and calibration
        disk cache inside the bundle. The calibrate env var is only
        set when the operator hasn't pointed it elsewhere."""
        from .. import ops as ops_mod
        from ..checker import calibrate

        os.makedirs(self.xla_cache_dir, exist_ok=True)
        ops_mod.configure_compilation_cache(self.xla_cache_dir, force=True)
        os.environ.setdefault(calibrate._CACHE_ENV, self.calib_cache_path)

    def _seed_calibration(self, manifest: dict) -> None:
        """A fresh manifest's persisted Calibration becomes this
        process's measurement — the warm start skips re-measurement
        entirely (the fingerprint already vouched for the backend)."""
        from ..checker import calibrate

        mesh = manifest.get("mesh_min_n")
        if mesh is not None:
            try:
                calibrate.seed_mesh(int(mesh))
            except (TypeError, ValueError):
                log.warning("bundle mesh crossover unreadable; "
                            "will remeasure")
        c = manifest.get("calibration")
        if not isinstance(c, dict):
            return
        try:
            calibrate.seed(calibrate.Calibration(
                float(c["t_rt"]), float(c["per_lane_pallas"]),
                float(c["per_lane_native"])))
        except (KeyError, TypeError, ValueError):
            log.warning("bundle calibration unreadable; will remeasure")

    # -- warming -----------------------------------------------------------

    def _warm_engines(self) -> dict:
        """Run the bucket compiles through the real engine entry
        points. Returns {family: [buckets that warmed]}. Failures are
        contained per bucket: a bucket that can't warm simply pays its
        compile at first use, exactly as before bundles existed."""
        probes = {"search": _probe_search_bucket,
                  "closure": _probe_closure_bucket,
                  "search_mesh": _probe_search_mesh_bucket,
                  "closure_mesh": _probe_closure_mesh_bucket}
        warmed: dict = {fam: [] for fam in probes
                        if fam in self.buckets or fam in
                        ("search", "closure")}
        for fam, probe in probes.items():
            for pad in self.buckets.get(fam, ()):
                try:
                    probe(pad)
                    warmed.setdefault(fam, []).append(pad)
                except Exception:  # noqa: BLE001 — warm is best-effort
                    log.warning("%s bucket %d failed to warm", fam, pad,
                                exc_info=True)
        # the pallas lane kernel only compiles for real Mosaic — on a
        # CPU host interpret-mode "compiles" aren't cacheable wins
        try:
            import jax

            if jax.devices()[0].platform == "tpu":
                from ..ops import wgl_pallas_vec

                if wgl_pallas_vec.probe():
                    warmed["pallas"] = True
        except Exception:  # noqa: BLE001
            log.warning("pallas probe failed during warm", exc_info=True)
        return warmed

    def build(self) -> dict:
        """Cold path: warm every bucket, take (or load) the
        calibration, stamp and atomically persist the manifest."""
        from ..checker import calibrate
        from .. import store

        t0 = time.monotonic()
        warmed = self._warm_engines()
        cal = calibrate.calibration()
        manifest = {
            "fingerprint": fingerprint(),
            "buckets": warmed,
            "calibration": (None if cal is None else {
                "t_rt": cal.t_rt,
                "per_lane_pallas": cal.per_lane_pallas,
                "per_lane_native": cal.per_lane_native,
            }),
            # measured mesh-vs-single crossover (None off-TPU / on
            # 1-device backends); warm starts seed it like the
            # calibration so the mesh rung routes without re-racing
            "mesh_min_n": calibrate.measured_mesh_min_n(),
            "build_s": round(time.monotonic() - t0, 3),
        }
        store.atomic_write_json(self.manifest_path, manifest)
        log.info("engine bundle built in %.1fs at %s",
                 manifest["build_s"], self.root)
        return manifest

    def ensure(self) -> dict:
        """Activate the bundle and make it fresh. Returns
        ``{"manifest", "warm", "warm_thread", "elapsed_s"}`` where
        ``warm`` is True when a valid manifest let this start skip the
        cold build; on that path ``warm_thread`` is the background
        bucket-replay thread (join it to wait for full warmth). The
        elapsed time is the daemon's ``cold_compile_s``."""
        t0 = time.monotonic()
        self._activate_caches()
        manifest = self.load_manifest()
        warm = self.is_fresh(manifest)
        thread = None
        if warm:
            self._seed_calibration(manifest)
            # replay the bucket compiles against the pinned disk cache
            # in the background: trace+load, no XLA/Mosaic compile.
            # Any check that lands before its bucket replays compiles
            # lazily THROUGH the same disk cache, so backgrounding
            # trades nothing but eager trace time — which is exactly
            # the part a persistent cache can't save.
            thread = threading.Thread(
                target=self._warm_engines, daemon=True,
                name="bundle-warm")
            thread.start()
            # a daemon thread still tracing inside XLA when the
            # interpreter finalizes segfaults; atexit runs while the
            # runtime is whole, so the replay gets to finish (bounded)
            atexit.register(thread.join, 60)
        else:
            if manifest is not None:
                log.info("engine bundle at %s is stale; rebuilding",
                         self.root)
            manifest = self.build()
        return {"manifest": manifest, "warm": warm,
                "warm_thread": thread,
                "elapsed_s": round(time.monotonic() - t0, 3)}
