"""Bank-transfer workload: concurrent transfers between accounts while
readers snapshot all balances; under snapshot isolation every read must
show the same non-negative total (reference: jepsen/src/jepsen/tests/
bank.clj:1-178).

Test map options:
    accounts       collection of account identifiers
    total_amount   total amount allocated across accounts
    max_transfer   largest single transfer
"""

from __future__ import annotations

import random

from .. import generator as gen
from ..checker import Checker, Compose
from ..history import ops as _ops
from ..checker.perf import load_pyplot, out_path
from ..util import nanos_to_secs


def read(test, process):
    """A generator of whole-state read ops (bank.clj:20-23)."""
    return {"type": "invoke", "f": "read", "value": None}


def transfer(test, process):
    """A random transfer between two random accounts (bank.clj:25-33)."""
    accounts = test["accounts"]
    return {
        "type": "invoke",
        "f": "transfer",
        "value": {
            "from": random.choice(accounts),
            "to": random.choice(accounts),
            "amount": 1 + random.randrange(test["max_transfer"]),
        },
    }


def diff_transfer():
    """Transfers only between distinct accounts (bank.clj:35-39)."""
    return gen.filter_gen(
        lambda op: op["value"]["from"] != op["value"]["to"],
        transfer,
    )


def generator():
    """A mix of reads and transfers (bank.clj:41-44)."""
    return gen.mix([diff_transfer(), read])


def err_badness(test, err) -> float:
    """Severity score for a bank error — bigger is worse (bank.clj:46-55)."""
    t = err["type"]
    if t == "unexpected-key":
        return len(err["unexpected"])
    if t == "nil-balance":
        return len(err["nils"])
    if t == "wrong-total":
        total_amount = test["total_amount"]
        return abs((err["total"] - total_amount) / total_amount)
    if t == "negative-value":
        return -sum(err["negative"])
    return 0.0


def check_op(accounts: set, total: int, op) -> dict | None:
    """Errors in a single read's balance snapshot (bank.clj:57-83)."""
    value = op.value or {}
    ks = list(value.keys())
    balances = list(value.values())
    unexpected = [k for k in ks if k not in accounts]
    if unexpected:
        return {"type": "unexpected-key", "unexpected": unexpected, "op": op}
    nils = {k: v for k, v in value.items() if v is None}
    if nils:
        return {"type": "nil-balance", "nils": nils, "op": op}
    if sum(balances) != total:
        return {"type": "wrong-total", "total": sum(balances), "op": op}
    negative = [b for b in balances if b < 0]
    if negative:
        return {"type": "negative-value", "negative": negative, "op": op}
    return None


class BankChecker(Checker):
    """Balances must be non-negative and sum to total_amount on every
    read (bank.clj:85-117)."""

    def check(self, test, history, opts=None) -> dict:
        accounts = set(test["accounts"])
        total = test["total_amount"]
        reads = [o for o in _ops(history) if o.is_ok and o.f == "read"]
        by_type: dict = {}
        for op in reads:
            err = check_op(accounts, total, op)
            if err is not None:
                by_type.setdefault(err["type"], []).append(err)
        first_error = None
        firsts = [errs[0] for errs in by_type.values()]
        if firsts:
            first_error = min(firsts, key=lambda e: e["op"].index)
        errors = {}
        for t, errs in by_type.items():
            entry = {
                "count": len(errs),
                "first": errs[0],
                "worst": max(errs, key=lambda e: err_badness(test, e)),
                "last": errs[-1],
            }
            if t == "wrong-total":
                entry["lowest"] = min(errs, key=lambda e: e["total"])
                entry["highest"] = max(errs, key=lambda e: e["total"])
            errors[t] = entry
        return {
            "valid": not errors,
            "read-count": len(reads),
            "error-count": sum(len(v) for v in by_type.values()),
            "first-error": first_error,
            "errors": errors,
        }


def checker() -> BankChecker:
    return BankChecker()


def by_node(test, history) -> dict:
    """Group client ops by the node their process maps to
    (bank.clj:119-128)."""
    nodes = test["nodes"]
    n = len(nodes)
    out: dict = {}
    for op in history:
        if isinstance(op.process, int):
            out.setdefault(nodes[op.process % n], []).append(op)
    return out


def points(history) -> list:
    """[time_secs, total-of-accounts] per ok read (bank.clj:130-139)."""
    return [
        (
            nanos_to_secs(op.time),
            sum(v for v in (op.value or {}).values() if v is not None),
        )
        for op in history
        if op.is_ok and op.f == "read"
    ]


class BankPlotter(Checker):
    """Scatter plot of per-node account totals over time → bank.png
    (bank.clj:141-167; matplotlib instead of gnuplot)."""

    def check(self, test, history, opts=None) -> dict:
        path = out_path(test, opts or {}, "bank.png")
        totals = {
            node: points(ops) for node, ops in by_node(test, _ops(history)).items()
        }
        if path is not None:
            plt = load_pyplot()
            fig, ax = plt.subplots(figsize=(9, 5))
            for node, pts in sorted(totals.items()):
                if pts:
                    xs, ys = zip(*pts)
                    ax.scatter(xs, ys, s=12, marker="x", label=str(node))
            ax.set_xlabel("time (s)")
            ax.set_ylabel("Total of all accounts")
            ax.set_title(f"{test.get('name', 'test')} bank")
            if totals:
                ax.legend(loc="best", fontsize=8)
            fig.savefig(path, dpi=100)
            plt.close(fig)
        return {"valid": True}


def plotter() -> BankPlotter:
    return BankPlotter()


def test() -> dict:
    """Partial test bundle: defaults + generator + checkers
    (bank.clj:169-178).

    The "cycle" entry runs the transactional cycle checker
    (jepsen_tpu.checker.cycle) alongside the SI total check. Bank ops
    carry aggregate snapshots ({account: balance}) rather than micro-op
    transactions, so dependency inference sees no attributable
    versions and the entry is vacuously true on this value shape — it
    is wired here so a client recording micro-op transfer txns
    ([["r", acct, bal], ["w", acct, bal']], unique balances) gets
    G0/G1c/G-single/G2 classification with no further changes."""
    from ..checker import cycle

    return {
        "max_transfer": 5,
        "total_amount": 100,
        "accounts": list(range(8)),
        "checker": Compose({"SI": checker(), "plot": plotter(),
                            "cycle": cycle.checker()}),
        "generator": generator(),
    }
