"""Workload bundles: generator + checker (+ model) packages for standard
consistency tests, mirroring the reference's jepsen.tests.* namespaces
(SURVEY.md §2.1). Each module exposes a `test(...)`/`workload(...)`
builder returning a partial test map — callers supply the client and DB.
"""

from . import (  # noqa: F401
    adya,
    bank,
    causal,
    linearizable_register,
    list_append,
    long_fork,
)
