"""Long-fork anomaly workload (parallel snapshot isolation): single-key
write transactions plus multi-key group reads; a long fork exists when two
reads observe a pair of writes in conflicting orders (reference:
jepsen/src/jepsen/tests/long_fork.clj:1-332).

Transactions are sequences of [f, k, v] micro-ops (jepsen_tpu.txn).
Every key is written at most once, so per-key states move nil -> v and
read snapshots within a key group form a partial order by domination; the
checker verifies this order is total.

Checking routes through the transactional cycle checker
(jepsen_tpu.checker.cycle): a long fork is a dependency cycle with two
anti-dependency edges, so the pairwise-domination test reduces to
cycle detection under write-once rw-register inference. The vectorized
all-pairs comparator (find_forks) survives one release behind
checker(n, legacy=True)."""

from __future__ import annotations

import itertools
import random
import threading

import numpy as np

from .. import generator as gen
from .. import txn as mop
from ..checker import Checker
from ..history import ops as _ops


class IllegalHistory(Exception):
    """This history can't be checked — reads are malformed
    (long_fork.clj:163-175,253-258)."""

    def __init__(self, msg, **info):
        super().__init__(msg)
        self.info = {"msg": msg, **info}


_MSG_KEY_MISMATCH = (
    "These reads did not query for the same keys, and therefore cannot "
    "be compared."
)
_MSG_DISTINCT_VALUES = (
    "These two read states contain distinct values for the same key; "
    "this checker assumes only one write occurs per key."
)


def group_for(n: int, k: int) -> range:
    """The key group containing k: [k - k%n, k - k%n + n)
    (long_fork.clj:97-104)."""
    lower = k - (k % n)
    return range(lower, lower + n)


def read_txn_for(n: int, k: int) -> list:
    """A transaction reading k's whole group in shuffled order
    (long_fork.clj:106-112)."""
    ks = list(group_for(n, k))
    random.shuffle(ks)
    return [[mop.READ, k, None] for k in ks]


class LongForkGen(gen.Generator):
    """Single-key inserts, each followed (same worker) by a read of its
    group, mixed with reads of other in-flight groups
    (long_fork.clj:114-156)."""

    def __init__(self, n: int):
        self.n = n
        self._next_key = 0
        self._workers: dict = {}
        self._lock = threading.Lock()

    def op(self, test, process):
        worker = gen.process_to_thread(test, process)
        with self._lock:
            k = self._workers.get(worker)
            if k is not None:
                # Read back the group we just wrote
                self._workers[worker] = None
                return {
                    "type": "invoke",
                    "f": "read",
                    "value": read_txn_for(self.n, k),
                }
            active = [v for v in self._workers.values() if v is not None]
            if active and random.random() < 0.5:
                # Read another active group, just for grins
                return {
                    "type": "invoke",
                    "f": "read",
                    "value": read_txn_for(self.n, random.choice(active)),
                }
            k = self._next_key
            self._next_key += 1
            self._workers[worker] = k
            return {"type": "invoke", "f": "write", "value": [[mop.WRITE, k, 1]]}


def generator(n: int) -> LongForkGen:
    return LongForkGen(n)


def read_op_to_value_map(op) -> dict:
    """{key: value} for a read op (long_fork.clj:198-206)."""
    return {mop.key(m): mop.value(m) for m in op.value}


def find_forks(ops) -> list:
    """All mutually-incomparable pairs among a group's reads, via a
    vectorized all-pairs domination test (long_fork.clj:216-224)."""
    ops = list(ops)
    m = len(ops)
    if m < 2:
        return []
    maps = [read_op_to_value_map(o) for o in ops]
    keys = sorted(maps[0].keys())
    # Uniform key sets + one-write-per-key are preconditions; verify via
    # the scalar comparator's error paths when they don't hold.
    vals = np.empty((m, len(keys)), dtype=object)
    for i, vm in enumerate(maps):
        if set(vm.keys()) != set(keys):
            raise IllegalHistory(_MSG_KEY_MISMATCH, reads=[maps[0], vm])
        vals[i] = [vm[k] for k in keys]
    nil = np.equal(vals, None)
    for j, k in enumerate(keys):
        col = vals[~nil[:, j], j]
        if len(set(col.tolist())) > 1:
            rows = np.flatnonzero(~nil[:, j])[:2]
            raise IllegalHistory(
                _MSG_DISTINCT_VALUES,
                key=k,
                reads=[maps[int(rows[0])], maps[int(rows[-1])]],
            )
    # i strictly ahead of j on some key AND j strictly ahead of i on
    # another => incomparable
    ahead = (~nil[:, None, :] & nil[None, :, :]).any(axis=-1)
    fork_at = np.triu(ahead & ahead.T, k=1)
    return [
        [ops[i], ops[j]] for i, j in zip(*np.nonzero(fork_at))
    ]


def is_read_txn(txn) -> bool:
    return all(mop.is_read(m) for m in txn)


def is_write_txn(txn) -> bool:
    return len(txn) == 1 and mop.is_write(txn[0])


def is_legal_txn(txn) -> bool:
    return is_read_txn(txn) or is_write_txn(txn)


def op_read_keys(op) -> tuple:
    """The keys a read op observed, as a canonical sorted tuple
    (long_fork.clj:243-246)."""
    return tuple(sorted(mop.key(m) for m in op.value))


def groups(n: int, read_ops) -> list:
    """Partition reads by key group; each group must read exactly n keys
    (long_fork.clj:248-261)."""
    by_group: dict = {}
    for op in read_ops:
        by_group.setdefault(op_read_keys(op), []).append(op)
    out = []
    for group, ops in by_group.items():
        if len(set(group)) != n:
            raise IllegalHistory(
                f"Every read in this history should have observed exactly "
                f"{n} keys, but this read observed {len(set(group))} "
                f"instead: {group!r}",
                op=ops[0],
            )
        out.append(ops)
    return out


def ensure_no_long_forks(n: int, reads) -> dict | None:
    forks = [f for g in groups(n, reads) for f in find_forks(g)]
    if forks:
        return {"valid": False, "forks": forks}
    return None


def ensure_no_multiple_writes_to_one_key(history) -> dict | None:
    """valid=unknown if any key is written twice (long_fork.clj:273-288)."""
    seen = set()
    for op in history:
        if op.is_invoke and is_write_txn(op.value or []):
            k = mop.key(op.value[0])
            if k in seen:
                return {"valid": "unknown", "error": ["multiple-writes", k]}
            seen.add(k)
    return None


def reads(history) -> list:
    """All ok pure-read ops (long_fork.clj:290-295)."""
    return [o for o in history if o.is_ok and is_read_txn(o.value or [])]


def early_reads(read_ops) -> list:
    """Reads observing only nils — too early to signify
    (long_fork.clj:297-302)."""
    return [
        o.value
        for o in read_ops
        if all(mop.value(m) is None for m in o.value)
    ]


def late_reads(read_ops) -> list:
    """Reads observing every key written — too late to signify
    (long_fork.clj:304-309)."""
    return [
        o.value
        for o in read_ops
        if all(mop.value(m) is not None for m in o.value)
    ]


class LongForkChecker(Checker):
    """No key written twice; no pair of reads observing conflicting write
    orders (long_fork.clj:311-324).

    The default path routes through the transactional cycle checker
    (checker/cycle): every key is written once, so rw-register
    inference under the write-once order applies, and a long fork IS a
    dependency cycle — each of the two reads wr-depends on the write
    it saw and rw-precedes the write it missed, closing a cycle with
    two anti-dependencies (G2-class; any requested anomaly fails). The
    pre-cycle pairwise-domination comparator survives one release
    behind legacy=True."""

    def __init__(self, n: int, legacy: bool = False):
        self.n = n
        self.legacy = legacy

    def check(self, test, history, opts=None) -> dict:
        history = _ops(history)
        rs = reads(history)
        out = {
            "reads-count": len(rs),
            "early-read-count": len(early_reads(rs)),
            "late-read-count": len(late_reads(rs)),
        }
        try:
            verdict = (
                ensure_no_multiple_writes_to_one_key(history)
                or (ensure_no_long_forks(self.n, rs) if self.legacy
                    else self._cycle_verdict(test, history, rs, opts))
                or {"valid": True}
            )
        except IllegalHistory as e:
            verdict = {"valid": "unknown", "error": e.info}
        out.update(verdict)
        return out

    def _cycle_verdict(self, test, history, rs, opts) -> dict | None:
        from ..checker import cycle

        # structural validation first: mismatched group sizes and
        # twice-written values are uncheckable, same as the legacy path
        groups(self.n, rs)
        r = cycle.checker(version_order="write-once").check(
            test, history, opts)
        if r["valid"] is True:
            return None
        if r["valid"] is False:
            # a long fork's witness cycle alternates reads and writes;
            # the observing reads are the classic "forks" pair
            forks = [
                [o for o in w["ops"] if is_read_txn(o.value or [])]
                for ws in r["anomalies"].values() for w in ws
            ]
            return {"valid": False, "forks": forks,
                    "anomaly-types": r["anomaly-types"],
                    "anomalies": r["anomalies"]}
        return {"valid": "unknown", "error": r.get("error")}


def checker(n: int, legacy: bool = False) -> LongForkChecker:
    return LongForkChecker(n, legacy=legacy)


def workload(n: int = 2) -> dict:
    """Checker + generator bundle; n is the group size
    (long_fork.clj:326-332)."""
    return {"checker": checker(n), "generator": generator(n)}
