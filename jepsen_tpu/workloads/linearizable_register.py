"""Linearizability over many independent CAS registers: the standard
per-key register workload (reference: jepsen/src/jepsen/tests/
linearizable_register.clj:1-46).

Clients understand three functions, with independent-tuple values:

    {"type": "invoke", "f": "write", "value": (k, v)}
    {"type": "invoke", "f": "read",  "value": (k, None)}
    {"type": "invoke", "f": "cas",   "value": (k, (v, v2))}
"""

from __future__ import annotations

import itertools
import random

from .. import checker as checker_mod
from .. import generator as gen
from .. import independent, models


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def cas(test, process):
    return {
        "type": "invoke",
        "f": "cas",
        "value": (random.randrange(5), random.randrange(5)),
    }


def test(opts: dict) -> dict:
    """Partial test: generator, model, checker; you supply the client
    (linearizable_register.clj:22-46). Options:

        nodes          nodes you'll operate on (only the count matters)
        per_key_limit  max ops per key, default 128
        algorithm      linearizable-checker algorithm override
    """
    n = len(opts["nodes"])
    per_key_limit = opts.get("per_key_limit", 128)
    algorithm = opts.get("algorithm", "auto")

    def fgen(k):
        # Randomize the per-key limit so keys drift out of phase and
        # don't line up on Significant Event Boundaries
        # (linearizable_register.clj:42-46).
        return gen.limit(
            int((random.random() * 0.1 + 0.9) * per_key_limit),
            gen.reserve(n, r, gen.mix([w, cas, cas])),
        )

    return {
        "checker": independent.checker(
            checker_mod.Compose(
                {
                    "linearizable": checker_mod.linearizable(algorithm=algorithm),
                    "timeline": checker_mod.timeline_html(),
                }
            )
        ),
        "model": models.cas_register(),
        "generator": independent.concurrent_generator(
            2 * n, itertools.count(), fgen
        ),
    }
