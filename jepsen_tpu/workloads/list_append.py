"""List-append workload: transactions of appends and whole-list reads
(reference: Elle's list-append test, elle.list-append; jepsen's
append workload).

Each micro-op is [f, k, v] with f "append" (push v onto key k's list)
or "r" (read the whole list). Because reads return the complete list,
the per-key version order is recoverable exactly from the observed
prefixes — the richest inference path the cycle checker
(checker/cycle) supports, turning ww/wr/rw edges into Adya anomalies
via matrix closure on the engine ladder.

Besides the live generator, `simulate` produces a seeded serializable
history (invoke/ok pairs, no cluster needed) with optional injected
G1c / G-single anomalies on dedicated keys — the acceptance fixture
for tests, bench.py's cycle_closure lane, and replay parity.
"""

from __future__ import annotations

import itertools
import random
import threading

from .. import txn as mop
from ..checker import cycle
from ..history import Op, index as _index

DEFAULT_ANOMALIES = ("G0", "G1c", "G-single", "G2")


class ListAppendGen:
    """Random txns of 1..max_txn_len micro-ops over a rolling key
    window; append values are unique per key (a counter), which the
    inference requires."""

    def __init__(self, keys: int = 16, max_txn_len: int = 4,
                 read_ratio: float = 0.5, seed: int | None = None):
        self.keys = keys
        self.max_txn_len = max_txn_len
        self.read_ratio = read_ratio
        self._rng = random.Random(seed)
        self._counters: dict = {}
        self._lock = threading.Lock()

    def _next_value(self, k) -> int:
        c = self._counters.setdefault(k, itertools.count(1))
        return next(c)

    def op(self, test, process):
        with self._lock:
            n = self._rng.randint(1, self.max_txn_len)
            t = []
            for _ in range(n):
                k = self._rng.randrange(self.keys)
                if self._rng.random() < self.read_ratio:
                    t.append([mop.READ, k, None])
                else:
                    t.append([mop.APPEND, k, self._next_value(k)])
            return {"type": "invoke", "f": "txn", "value": t}


def generator(keys: int = 16, max_txn_len: int = 4,
              read_ratio: float = 0.5, seed: int | None = None):
    return ListAppendGen(keys, max_txn_len, read_ratio, seed)


def checker(anomalies=DEFAULT_ANOMALIES, **kw) -> cycle.CycleChecker:
    """The cycle checker parameterized for list-append histories."""
    return cycle.checker(anomalies, **kw)


def workload(keys: int = 16, anomalies=DEFAULT_ANOMALIES) -> dict:
    return {"checker": checker(anomalies), "generator": generator(keys)}


# ---------------------------------------------------------------------------
# Seeded simulation (no cluster)

def _emit(h, proc, value_in, value_out):
    h.append(Op(proc, "invoke", "txn", value_in))
    h.append(Op(proc, "ok", "txn", value_out))


def inject_g1c(h, proc, key_a, key_b) -> None:
    """A circular-information-flow pair on two fresh keys: each txn
    appends one value and reads the OTHER txn's append — mutual wr
    edges, a two-cycle in ww|wr (anomalies.py G1c)."""
    _emit(h, proc,
          [[mop.APPEND, key_a, 1], [mop.READ, key_b, None]],
          [[mop.APPEND, key_a, 1], [mop.READ, key_b, [1]]])
    _emit(h, proc,
          [[mop.APPEND, key_b, 1], [mop.READ, key_a, None]],
          [[mop.APPEND, key_b, 1], [mop.READ, key_a, [1]]])


def inject_g_single(h, proc, key_x, key_y) -> None:
    """Read skew on two fresh keys: T2 appends to both; T1 misses the
    x append (rw T1->T2) but observes the y append (wr T2->T1) —
    a cycle with exactly one rw. A trailing read makes the missed x
    version observed, which the prefix inference needs to position
    it."""
    _emit(h, proc,
          [[mop.APPEND, key_x, 1], [mop.APPEND, key_y, 1]],
          [[mop.APPEND, key_x, 1], [mop.APPEND, key_y, 1]])
    _emit(h, proc,
          [[mop.READ, key_x, None], [mop.READ, key_y, None]],
          [[mop.READ, key_x, []], [mop.READ, key_y, [1]]])
    _emit(h, proc,
          [[mop.READ, key_x, None]],
          [[mop.READ, key_x, [1]]])


def simulate(n_ops: int = 5000, seed: int = 0, keys: int = 32,
             processes: int = 5, max_txn_len: int = 4,
             read_ratio: float = 0.5,
             inject=("G1c", "G-single")) -> list:
    """A seeded list-append history of ~n_ops invoke/ok pairs executed
    serially against an in-memory store (so the base history is
    serializable and anomaly-free), plus the requested injected
    anomalies on dedicated keys disjoint from the workload's. Returns
    an indexed Op list ready for the cycle checker."""
    rng = random.Random(seed)
    store: dict = {k: [] for k in range(keys)}
    counters = {k: itertools.count(1) for k in range(keys)}
    h: list = []
    n_txns = max(1, n_ops // 2)
    inject = list(inject)
    # spread injection sites deterministically through the middle
    sites = {max(1, (i + 1) * n_txns // (len(inject) + 1)): a
             for i, a in enumerate(inject)} if inject else {}
    extra_key = itertools.count(keys)  # fresh keys for injections
    for t in range(n_txns):
        a = sites.get(t)
        if a == "G1c":
            inject_g1c(h, rng.randrange(processes),
                       next(extra_key), next(extra_key))
        elif a == "G-single":
            inject_g_single(h, rng.randrange(processes),
                            next(extra_key), next(extra_key))
        proc = rng.randrange(processes)
        value_in, value_out = [], []
        for _ in range(rng.randint(1, max_txn_len)):
            k = rng.randrange(keys)
            if rng.random() < read_ratio:
                value_in.append([mop.READ, k, None])
                value_out.append([mop.READ, k, list(store[k])])
            else:
                v = next(counters[k])
                value_in.append([mop.APPEND, k, v])
                value_out.append([mop.APPEND, k, v])
                store[k].append(v)
        _emit(h, proc, value_in, value_out)
    return _index(h)
