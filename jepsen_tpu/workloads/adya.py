"""Adya G2 anti-dependency-cycle workload: for each key, two concurrent
transactions each predicate-read both tables and insert one row; under
serializability at most one insert per key may commit (reference:
jepsen/src/jepsen/tests/adya.clj:1-89; see Adya's thesis for G2).

Clients take ops {"f": "insert", "value": (key, (a_id, b_id))} where
exactly one of a_id/b_id is set, predicate-read both tables for the key,
and insert into table a or b iff both reads came back empty."""

from __future__ import annotations

import itertools
import threading

from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..history import ops as _ops


class _IdSource:
    def __init__(self):
        self._n = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._n += 1
            return self._n


def g2_gen() -> gen.Generator:
    """Pairs of insert ops per key, ids globally unique; one txn holds
    a_id, the other b_id (adya.clj:13-61)."""
    ids = _IdSource()
    return independent.concurrent_generator(
        2,
        itertools.count(),
        lambda k: gen.seq(
            [
                lambda t, p: {
                    "type": "invoke",
                    "f": "insert",
                    "value": (None, ids.next()),
                },
                lambda t, p: {
                    "type": "invoke",
                    "f": "insert",
                    "value": (ids.next(), None),
                },
            ]
        ),
    )


class G2Checker(Checker):
    """At most one insert may succeed per key (adya.clj:63-89).

    The default path restates each ok insert as the transaction the
    client actually ran — predicate-read both tables empty, then write
    own row — and hands the lot to the cycle checker
    (jepsen_tpu.checker.cycle): two committed inserts for one key each
    read the emptiness the other destroyed, a mutual-anti-dependency
    cycle, which is exactly Adya's G2. The pre-cycle per-key counting
    survives one release behind legacy=True (and still produces the
    key/legal/illegal tallies on both paths)."""

    def __init__(self, legacy: bool = False):
        self.legacy = legacy

    def check(self, test, history, opts=None) -> dict:
        keys: dict = {}
        inserts: dict = {}
        for op in _ops(history):
            if op.f != "insert" or not independent.is_tuple(op.value):
                continue
            k = op.value.key
            if op.is_ok:
                keys[k] = keys.get(k, 0) + 1
                inserts.setdefault(k, []).append(op)
            else:
                keys.setdefault(k, 0)
        insert_count = sum(1 for c in keys.values() if c > 0)
        illegal = {k: c for k, c in sorted(keys.items()) if c > 1}
        out = {
            "key-count": len(keys),
            "legal-count": insert_count - len(illegal),
            "illegal-count": len(illegal),
            "illegal": illegal,
        }
        if self.legacy:
            out["valid"] = not illegal
            return out
        r = self._cycle_verdict(test, inserts, opts)
        if r["valid"] is False:
            out["valid"] = False
            out["anomaly-types"] = r["anomaly-types"]
            out["anomalies"] = r["anomalies"]
        elif illegal:
            # the per-key count is structural ground truth; a double
            # insert the inference couldn't attribute still fails
            out["valid"] = False
        elif r["valid"] == "unknown":
            out["valid"] = "unknown"
            out["error"] = r.get("error")
        else:
            out["valid"] = True
        return out

    def _cycle_verdict(self, test, inserts, opts) -> dict:
        from ..checker import cycle

        txn_history = []
        for k, ops in inserts.items():
            for op in ops:
                a_id, b_id = op.value.value
                table = (k, "a") if a_id is not None else (k, "b")
                txn_history.append(op.with_(value=[
                    ["r", (k, "a"), None],
                    ["r", (k, "b"), None],
                    ["w", table, a_id if a_id is not None else b_id],
                ]))
        return cycle.checker(("G2",), version_order="write-once").check(
            test, txn_history, opts)


def g2_checker(legacy: bool = False) -> G2Checker:
    return G2Checker(legacy=legacy)


def workload() -> dict:
    return {"checker": g2_checker(), "generator": g2_gen()}
