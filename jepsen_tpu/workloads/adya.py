"""Adya G2 anti-dependency-cycle workload: for each key, two concurrent
transactions each predicate-read both tables and insert one row; under
serializability at most one insert per key may commit (reference:
jepsen/src/jepsen/tests/adya.clj:1-89; see Adya's thesis for G2).

Clients take ops {"f": "insert", "value": (key, (a_id, b_id))} where
exactly one of a_id/b_id is set, predicate-read both tables for the key,
and insert into table a or b iff both reads came back empty."""

from __future__ import annotations

import itertools
import threading

from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..history import ops as _ops


class _IdSource:
    def __init__(self):
        self._n = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._n += 1
            return self._n


def g2_gen() -> gen.Generator:
    """Pairs of insert ops per key, ids globally unique; one txn holds
    a_id, the other b_id (adya.clj:13-61)."""
    ids = _IdSource()
    return independent.concurrent_generator(
        2,
        itertools.count(),
        lambda k: gen.seq(
            [
                lambda t, p: {
                    "type": "invoke",
                    "f": "insert",
                    "value": (None, ids.next()),
                },
                lambda t, p: {
                    "type": "invoke",
                    "f": "insert",
                    "value": (ids.next(), None),
                },
            ]
        ),
    )


class G2Checker(Checker):
    """At most one insert may succeed per key (adya.clj:63-89)."""

    def check(self, test, history, opts=None) -> dict:
        keys: dict = {}
        for op in _ops(history):
            if op.f != "insert" or not independent.is_tuple(op.value):
                continue
            k = op.value.key
            if op.is_ok:
                keys[k] = keys.get(k, 0) + 1
            else:
                keys.setdefault(k, 0)
        insert_count = sum(1 for c in keys.values() if c > 0)
        illegal = {k: c for k, c in sorted(keys.items()) if c > 1}
        return {
            "valid": not illegal,
            "key-count": len(keys),
            "legal-count": insert_count - len(illegal),
            "illegal-count": len(illegal),
            "illegal": illegal,
        }


def g2_checker() -> G2Checker:
    return G2Checker()


def workload() -> dict:
    return {"checker": g2_checker(), "generator": g2_gen()}
