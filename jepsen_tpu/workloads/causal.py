"""Causal-consistency register workload: a causal order of reads and
writes against per-key registers, verified by sequential replay
(reference: jepsen/src/jepsen/tests/causal.clj:1-131).

Ops carry two extra fields (in Op.extra): "position", an opaque site
position for this op, and "link", the position of the causally preceding
op (or "init" for the first op in a causal order).
"""

from __future__ import annotations

import itertools

from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..history import ops as _ops
from ..models import Inconsistent, inconsistent


class CausalRegister:
    """Register whose writes must arrive in counter order and whose ops
    must link to the last-seen position (causal.clj:33-83)."""

    def __init__(self, value=0, counter=0, last_pos=None):
        self.value = value
        self.counter = counter
        self.last_pos = last_pos

    def step(self, op):
        c = self.counter + 1
        v = op.value
        pos = op.extra.get("position")
        link = op.extra.get("link")
        if link != "init" and link != self.last_pos:
            return Inconsistent(
                f"Cannot link {link} to last-seen position {self.last_pos}"
            )
        if op.f == "write":
            if v == c:
                return CausalRegister(v, c, pos)
            return Inconsistent(
                f"expected value {c} attempting to write {v} instead"
            )
        if op.f == "read-init":
            # On a fresh register the init read must be exactly 0 —
            # the reference's (and (= 0 counter) (not= 0 v')) also
            # rejects nil (causal.clj:56-60).
            if self.counter == 0 and v != 0:
                return Inconsistent(f"expected init value 0, read {v}")
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return Inconsistent(f"can't read {v} from register {self.value}")
        if op.f == "read":
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return Inconsistent(f"can't read {v} from register {self.value}")
        return Inconsistent(f"unknown f {op.f}")

    def __str__(self) -> str:
        return repr(self.value)


def causal_register() -> CausalRegister:
    return CausalRegister()


class CausalChecker(Checker):
    """Sequentially folds the model over ok ops; any inconsistency fails
    the history (causal.clj:88-110)."""

    def __init__(self, model=None):
        self.model = model

    def check(self, test, history, opts=None) -> dict:
        s = self.model or test.get("model") or causal_register()
        for op in _ops(history):
            if not op.is_ok:
                continue
            s = s.step(op)
            if inconsistent(s):
                return {"valid": False, "error": s.msg}
        return {"valid": True, "model": str(s)}


def check(model=None) -> CausalChecker:
    return CausalChecker(model)


# Generators (causal.clj:113-116)
def r(test, process):
    return {"type": "invoke", "f": "read"}


def ri(test, process):
    return {"type": "invoke", "f": "read-init"}


def cw1(test, process):
    return {"type": "invoke", "f": "write", "value": 1}


def cw2(test, process):
    return {"type": "invoke", "f": "write", "value": 2}


def test(opts: dict) -> dict:
    """Partial test: one causal order (ri w1 r w2 r) per key, one worker
    per key, partition nemesis cycling every 10 s (causal.clj:118-131)."""
    nemesis_cycle = itertools.cycle(
        [
            gen.sleep(10),
            {"type": "info", "f": "start"},
            gen.sleep(10),
            {"type": "info", "f": "stop"},
        ]
    )
    from ..checker import Compose, cycle

    return {
        "model": causal_register(),
        # per key: the sequential causal replay, plus the cycle
        # checker under value-ordered rw-register inference (writes
        # are the counter values 1, 2, ...; reads may see the initial
        # 0) — circular causality shows up as a G1c/G-single cycle
        "checker": independent.checker(Compose({
            "causal": check(),
            "cycle": cycle.checker(version_order="value",
                                   init_values=(0,)),
        })),
        "generator": gen.time_limit(
            opts.get("time_limit", 60),
            gen.nemesis(
                gen.seq(nemesis_cycle),
                gen.stagger(
                    1,
                    independent.concurrent_generator(
                        1,
                        itertools.count(),
                        lambda k: gen.seq([ri, cw1, r, cw2, r]),
                    ),
                ),
            ),
        ),
    }
