"""Auto-reconnecting connection wrappers (reference: jepsen.reconnect,
reconnect.clj:1-129).

A Wrapper owns a connection plus open/close functions. Many threads may
use the connection concurrently (read lock); open/close/reopen take the
write lock. `with_conn()` yields the current connection and, if the body
throws, closes and reopens it (once, only if it's still the same
connection that failed) before re-raising."""

from __future__ import annotations

import logging
import random
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable

log = logging.getLogger("jepsen_tpu.reconnect")


class RWLock:
    """Write-preferring reentrant reader/writer lock (the reference's
    ReentrantReadWriteLock, reconnect.clj:14,30).

    Matches java.util.concurrent semantics: a thread may re-acquire the
    read lock it already holds (nested with_conn works), the writer may
    take the read lock (downgrade), and write acquisition is reentrant.
    Read→write *upgrade* is not supported — like the Java lock, a
    reader calling acquire_write deadlocks — so open()/close()/reopen()
    must not be called from inside a with_conn body."""

    def __init__(self):
        self._cond = threading.Condition()
        self._read_holds: dict[int, int] = {}  # thread id -> hold count
        self._writer: int | None = None  # owning thread id
        self._write_holds = 0
        self._writers_waiting = 0

    def acquire_read(self):
        me = threading.get_ident()
        with self._cond:
            if self._read_holds.get(me) or self._writer == me:
                self._read_holds[me] = self._read_holds.get(me, 0) + 1
                return
            while self._writer is not None or self._writers_waiting:
                self._cond.wait()
            self._read_holds[me] = 1

    def release_read(self):
        me = threading.get_ident()
        with self._cond:
            n = self._read_holds.get(me, 0) - 1
            if n > 0:
                self._read_holds[me] = n
            else:
                self._read_holds.pop(me, None)
                if not self._read_holds:
                    self._cond.notify_all()

    def acquire_write(self):
        me = threading.get_ident()
        with self._cond:
            if self._writer == me:
                self._write_holds += 1
                return
            self._writers_waiting += 1
            try:
                while self._writer is not None or self._read_holds:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = me
            self._write_holds = 1

    def release_write(self):
        with self._cond:
            self._write_holds -= 1
            if self._write_holds == 0:
                self._writer = None
                self._cond.notify_all()

    def release_all_reads(self) -> int:
        """Drop every read hold this thread has; returns the count so it
        can be restored with reacquire_reads. Used by with_conn's
        error path so a nested body can still trade up to the write
        lock without deadlocking on its own outer holds."""
        me = threading.get_ident()
        with self._cond:
            n = self._read_holds.pop(me, 0)
            if n and not self._read_holds:
                self._cond.notify_all()
            return n

    def reacquire_reads(self, n: int):
        if n <= 0:
            return
        me = threading.get_ident()
        with self._cond:
            if self._read_holds.get(me) or self._writer == me:
                self._read_holds[me] = self._read_holds.get(me, 0) + n
                return
            while self._writer is not None or self._writers_waiting:
                self._cond.wait()
            self._read_holds[me] = n

    @contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextmanager
    def write(self):
        self.acquire_write()
        try:
            yield
        finally:
            self.release_write()


class Wrapper:
    """Stateful reconnecting handle to a database connection
    (reconnect.clj:16-31)."""

    def __init__(
        self,
        open: Callable[[], Any],
        close: Callable[[Any], None],
        name: str | None = None,
        log_reconnects: bool = True,
        max_retries: int = 1,
        backoff_base: float = 0.05,
        backoff_cap: float = 5.0,
        seed: int | None = None,
    ):
        """max_retries is the number of open ATTEMPTS per (re)open
        (default 1 — the historical immediate-single-attempt behavior);
        between failed attempts we sleep a capped exponential backoff
        with seeded jitter, and the LAST error surfaces to the caller."""
        assert callable(open) and callable(close)
        assert max_retries >= 1
        self._open = open
        self._close = close
        self.name = name
        self.log_reconnects = log_reconnects
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self.lock = RWLock()
        self._conn: Any = None

    def _backoff(self, attempt: int) -> float:
        """Capped exponential backoff with jitter in [0.5x, 1.5x) —
        seeded so a test run's reconnect schedule replays exactly."""
        with self._rng_lock:
            jitter = 0.5 + self._rng.random()
        return min(self.backoff_cap, self.backoff_base * 2 ** attempt) * jitter

    def _open_retry(self):
        """One logical open = up to max_retries attempts with backoff;
        raises the last error when all fail. Called under the write
        lock."""
        last: Exception | None = None
        for attempt in range(self.max_retries):
            if attempt:
                delay = self._backoff(attempt - 1)
                if self.log_reconnects:
                    log.warning(
                        "Reopen %r attempt %d/%d failed; retrying in "
                        "%.2fs", self.name, attempt, self.max_retries,
                        delay)
                time.sleep(delay)
            try:
                c = self._open()
            except Exception as e:  # noqa: BLE001
                last = e
                continue
            if c is None:
                raise RuntimeError(
                    f"Reconnect wrapper {self.name!r}'s open function "
                    "returned None instead of a connection!"
                )
            return c
        assert last is not None
        raise last

    def conn(self):
        """The active connection, if any (reconnect.clj:49-52)."""
        return self._conn

    def open(self) -> "Wrapper":
        """Open a connection; no-op if already open
        (reconnect.clj:54-66)."""
        with self.lock.write():
            if self._conn is None:
                self._conn = self._open_retry()
        return self

    def close(self) -> "Wrapper":
        """Close the connection, if open (reconnect.clj:68-75)."""
        with self.lock.write():
            if self._conn is not None:
                self._close(self._conn)
                self._conn = None
        return self

    def reopen(self) -> "Wrapper":
        """Close (if open) and open a fresh connection
        (reconnect.clj:77-90)."""
        with self.lock.write():
            if self._conn is not None:
                self._close(self._conn)
                self._conn = None
            self._conn = self._open_retry()
        return self

    @contextmanager
    def with_conn(self):
        """Yield the current connection under the read lock; on any
        exception, reopen the connection (if it's still the one that
        failed) and re-raise the original error (reconnect.clj:92-129)."""
        self.lock.acquire_read()
        c = self._conn
        try:
            yield c
        except Exception:
            # Trade the read lock for the write lock to reopen. Release
            # ALL of this thread's read holds (we may be nested) so the
            # write acquisition can't deadlock on our own outer holds.
            held = self.lock.release_all_reads()
            try:
                with self.lock.write():
                    if self._conn is c:
                        if self.log_reconnects:
                            log.warning(
                                "Encountered error with conn %r; reopening",
                                self.name,
                            )
                        if self._conn is not None:
                            try:
                                self._close(self._conn)
                            finally:
                                self._conn = None
                        self._conn = self._open_retry()
            except Exception:  # noqa: BLE001
                # Log but don't mask the original transaction error
                if self.log_reconnects:
                    log.warning("Error reopening %r", self.name, exc_info=True)
            finally:
                self.lock.reacquire_reads(held)
            raise
        finally:
            self.lock.release_read()


def wrapper(open, close, name=None, log_reconnects=True, **kw) -> Wrapper:
    return Wrapper(open, close, name=name, log_reconnects=log_reconnects,
                   **kw)
