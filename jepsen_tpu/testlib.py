"""Base test maps and the in-process fake backend (reference:
jepsen.tests, tests.clj).

`atom_db`/`atom_client` run the ENTIRE engine — workers, generators,
history capture, checking — against a lock-protected in-memory register,
no cluster required (tests.clj:27-56; the trick behind the reference's
hermetic core_test.clj:18-30)."""

from __future__ import annotations

import threading

from . import checker as checker_mod
from . import client as client_mod
from . import db as db_mod
from . import generator as gen
from . import models, nemesis as nemesis_mod, net as net_mod, osenv


def noop_test() -> dict:
    """Boring test stub to build real tests on (tests.clj:12-25)."""
    return {
        "name": "noop",
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "os": osenv.noop,
        "db": db_mod.noop,
        "net": net_mod.noop,
        "client": client_mod.noop,
        "nemesis": nemesis_mod.noop,
        "generator": gen.void,
        "model": models.noop(),
        "checker": checker_mod.unbridled_optimism(),
        "ssh": {"dummy": True},
    }


class SharedAtom:
    """A compare-and-set cell guarded by a lock (the Clojure atom)."""

    def __init__(self, value=None):
        self.value = value
        self.lock = threading.Lock()


class AtomDB(db_mod.DB):
    """Wraps an atom as a database (tests.clj:27-32)."""

    def __init__(self, state: SharedAtom):
        self.state = state

    def setup(self, test, node):
        with self.state.lock:
            self.state.value = None

    def teardown(self, test, node):
        with self.state.lock:
            self.state.value = "done"


class AtomClient(client_mod.Client):
    """A linearizable-by-construction CAS register client over a shared
    atom (tests.clj:34-56)."""

    def __init__(self, state: SharedAtom):
        self.state = state

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        s = self.state
        if op.f == "write":
            with s.lock:
                s.value = op.value
            return op.with_(type="ok")
        if op.f == "cas":
            old, new = op.value
            with s.lock:
                if s.value == old:
                    s.value = new
                    return op.with_(type="ok")
            return op.with_(type="fail")
        if op.f == "read":
            with s.lock:
                v = s.value
            return op.with_(type="ok", value=v)
        raise ValueError(f"unknown op {op.f!r}")


class FlakyClient(AtomClient):
    """AtomClient that crashes (raises) with some probability AFTER
    applying the op — producing genuine :info indeterminacy for engine
    tests (the analog of core_test.clj's throwing clients)."""

    def __init__(self, state, crash_p=0.1, seed=0):
        super().__init__(state)
        import random

        self.rng = random.Random(seed)
        self.crash_p = crash_p
        self._lock = threading.Lock()

    def invoke(self, test, op):
        completion = super().invoke(test, op)
        with self._lock:
            crash = self.rng.random() < self.crash_p
        if crash:
            raise RuntimeError("simulated client crash (post-apply)")
        return completion


class FlakyEngine:
    """Deterministic fault injection for checker-engine batch calls —
    the chaos fixture the supervisor tests (tests/test_supervisor.py)
    drive the degradation ladder with.

    Wraps an engine's batch function with a seeded SCHEDULE of faults,
    one entry per call: None passes through to the wrapped engine,
    "fail" raises a transient error, "oom" raises a device-OOM-shaped
    error (the supervisor's bisection trigger), "hang" sleeps hang_s
    then proceeds (trips the watchdog when hang_s exceeds the call
    timeout). Past the schedule's end every call passes through. The
    instance records (kind, n_lanes) per call in .log and counts calls
    in .calls — a quarantined engine is asserted by .calls holding
    still."""

    def __init__(self, fn, schedule=(), hang_s: float = 1.0):
        self.fn = fn
        self.schedule = list(schedule)
        self.hang_s = hang_s
        self.calls = 0
        self.log: list = []
        self._lock = threading.Lock()

    def __call__(self, model, ess, max_steps=None, time_limit=None):
        import time as _t

        with self._lock:
            i = self.calls
            self.calls += 1
            kind = self.schedule[i] if i < len(self.schedule) else None
            self.log.append((kind, len(ess)))
        if kind == "fail":
            raise RuntimeError("injected transient engine failure")
        if kind == "oom":
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: injected out of memory")
        if kind == "hang":
            _t.sleep(self.hang_s)
        return self.fn(model, ess, max_steps=max_steps,
                       time_limit=time_limit)


def cas_test(state: SharedAtom | None = None, **overrides) -> dict:
    """The reference's basic-cas-test shape (core_test.clj:18-30): full
    engine against the atom backend, linearizable checker."""
    state = state or SharedAtom()
    base = noop_test()
    base.update(
        {
            "name": "cas-atom",
            "db": AtomDB(state),
            "client": AtomClient(state),
            "model": models.cas_register(),
            "generator": gen.clients(
                gen.time_limit(2, gen.limit(100, gen.cas))
            ),
            "checker": checker_mod.linearizable(algorithm="host"),
        }
    )
    base.update(overrides)
    return base
