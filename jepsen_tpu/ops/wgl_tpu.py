"""Wing-Gong-Lowe linearizability search as a jitted TPU kernel.

The north star (BASELINE.json): knossos.wgl re-expressed as a bitmask-DFS
with the model's state-transition function compiled into the kernel and
the memoization cache in HBM. The host algorithm (ops/wgl_host.py) is
restated in fixed-shape, branch-free form:

- the doubly-linked event list is a pair of int32 arrays (nxt/prv)
  updated functionally with scatter;
- the DFS is ONE lax.while_loop whose body executes exactly one search
  step (try-linearize / advance / backtrack), selected with jnp.where —
  no data-dependent Python control flow (XLA traces it once);
- the linearized set is a uint32[W] bitset;
- the memo cache is an open-addressed hash table storing the FULL
  (bitset, state) key — lookups compare every word, so pruning is exact
  and the verdict is bit-identical to the host search; a full table only
  loses pruning, never soundness;
- the undo stack is an explicit int32 stack (entry id, previous state).

Scale-out: `analysis_batch` vmaps the whole search over independent keys
(jepsen.independent's sharding axis, independent.clj:66-220) — every
lane advances one search step per iteration in lockstep, which is
exactly the shape TPUs like. Sharding the lane axis over a
jax.sharding.Mesh spreads keys across devices; all per-lane work is
elementwise, so no collectives are needed inside the loop.

Single-lane latency is dominated by sequential dependency (one step per
iteration), so checking ONE history on TPU is no faster than the host;
the win is checking tens-to-hundreds of keys concurrently. The
linearizable checker's "auto"/"competition" modes exploit exactly that
split.
"""

from __future__ import annotations

import time as _time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import _configure_compilation_cache, next_pow2 as _next_pow2
from ..history import Entries
from ..models import jit as mjit
from .wgl_host import (WGLResult, analysis as wgl_host_analysis,
                       recover_invalid)

# before any kernel compiles (see ops/__init__ docstring) — here, not
# at package import, so pure-host consumers never pay an eager jax
_configure_compilation_cache()

# verdict codes
RUNNING, VALID, INVALID, UNKNOWN = 0, 1, 2, 3

DEFAULT_MAX_STEPS = 2_000_000
DEFAULT_CACHE_BITS = 13  # 8192 slots per lane
N_PROBES = 8
# Search steps executed per while_loop iteration. Each unrolled step
# re-checks the (verdict, step-budget) gate, so semantics — verdicts,
# step counts, max_steps cutoffs — are bit-identical at any unroll;
# finished lanes just burn gated no-op steps at the tail. Measured on
# the v5e: ~3x on per-key-sized lanes (amortizes per-iteration
# dispatch), nil on stress-sized lanes (per-step array work
# dominates); compile time scales with the body, so 8 is the sweet
# spot.
DEFAULT_UNROLL = 8


def encode_entries(es: Entries, jm, n_pad: int) -> dict:
    """Pack host Entries into fixed-shape int32 arrays for one kernel
    lane. Event node ids: 0 is the head sentinel; event at position p is
    node p+1. Padded entries simply never appear in the linked list.
    Value encoding is delegated to the kernel model: scalar models use
    the global int32 codec, the queue model a per-lane value->slot map
    (models/jit.py)."""
    n = len(es)
    assert n <= n_pad
    m = 2 * n_pad + 1
    f = np.zeros(n_pad, np.int32)
    v1 = np.full(n_pad, mjit.NIL32, np.int32)
    v2 = np.full(n_pad, mjit.NIL32, np.int32)
    # payload encoding is the only per-op host work left — and for
    # scalar models it's memoized across lanes (jm.encode_lane)
    if n > 0:
        f[:n], v1[:n], v2[:n] = jm.encode_lane(es)
    crashed = np.zeros(n_pad, bool)
    call_node = np.zeros(n_pad, np.int32)
    ret_node = np.zeros(n_pad, np.int32)
    node_entry = np.zeros(m, np.int32)
    node_is_call = np.zeros(m, bool)
    if n > 0:
        crashed[:n] = es.crashed
        cp = np.asarray(es.call_pos, np.int32) + 1
        rp = np.asarray(es.ret_pos, np.int32) + 1
        call_node[:n] = cp
        ret_node[:n] = rp
        # cp/rp must be globally unique node positions: numpy fancy-index
        # writes have undefined order on duplicates, so a collision would
        # silently corrupt node_entry (history.Entries guarantees distinct
        # call/ret positions; this guards the invariant).
        both = np.concatenate([cp, rp])
        assert len(np.unique(both)) == len(both), \
            "duplicate call/ret node positions in Entries"
        idx = np.arange(n, dtype=np.int32)
        node_entry[cp] = idx
        node_entry[rp] = idx
        node_is_call[cp] = True
    # initial linked list: nodes 1..2n in order, tail -> 0
    nxt = np.zeros(m, np.int32)
    prv = np.zeros(m, np.int32)
    if n > 0:
        nxt[: 2 * n] = np.arange(1, 2 * n + 1, dtype=np.int32)
        nxt[2 * n] = 0
        prv[1 : 2 * n + 1] = np.arange(0, 2 * n, dtype=np.int32)
    return {
        "f": f,
        "v1": v1,
        "v2": v2,
        "crashed": crashed,
        "call_node": call_node,
        "ret_node": ret_node,
        "node_entry": node_entry,
        "node_is_call": node_is_call,
        "nxt0": nxt,
        "prv0": prv,
        "n": np.int32(n),
        "n_completed": np.int32(es.n_completed),
    }


def _zobrist_table(n_pad: int) -> np.ndarray:
    """One random uint32 per entry (splitmix-style, deterministic).
    The bitset's bucket hash is maintained INCREMENTALLY: XOR the
    entry's constant in when it linearizes, out when it backtracks —
    O(1) per step instead of an O(n_words) fold, which dominated the
    loop body for long histories. The exact full-key compare is what
    guarantees soundness; this hash only picks buckets."""
    x = np.arange(1, n_pad + 1, dtype=np.uint64) * np.uint64(
        0x9E3779B97F4A7C15)
    x = (x ^ (x >> 30)) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * np.uint64(0x94D049BB133111EB)
    return ((x ^ (x >> 31)) & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def _mix_hash(h_lin: jnp.ndarray, state: jnp.ndarray,
              state_in_key: bool) -> jnp.ndarray:
    """Combine the incremental bitset hash with a fold of the (small)
    state vector and avalanche into a bucket hash."""
    h = h_lin
    if state_in_key:
        for w in range(state.shape[0]):
            h = (h ^ state[w].astype(jnp.uint32)) * jnp.uint32(16777619)
    h = (h ^ (h >> 15)) * jnp.uint32(0x85EBCA6B)
    return h ^ (h >> 13)


def _search_one(ent: dict, jm, n_state: int, n_words: int, cache_bits: int,
                unroll: int = DEFAULT_UNROLL,
                dense: bool = False):
    """The complete DFS for one lane. All shapes static.

    Model state is an int32[n_state] vector (width 1 for the scalar
    models). Two model-declared structural facts shrink the kernel:
    state_in_key=False drops the state words from the memo key (sound
    when state is a function of the linearized bitset, as for the
    unordered queue), and has_unstep=True replaces the per-depth state
    snapshot stack with an exact inverse transition on backtrack.

    ONE step core serves two array strategies; the strategies differ
    only in how arrays are laid out, read, and written — every search
    decision in between is shared code, so the forms cannot drift.
    Verdicts AND step counts are bit-identical (the parity tests
    assert both against the host search):

    - dense=False (scatter): nxt/prv/stack/cache as separate arrays,
      reads are gather ops, writes are targeted conditional scalar
      scatters. Right for stress-sized lanes (n_pad in the tens of
      thousands) where a full-array pass per step would be the
      bandwidth bill, and for small lane counts where every step is
      launch-overhead-bound anyway.
    - dense=True: packed tables (nxt/prv in one np2[m, 2]; stack row =
      entry id + state snapshot; cache row = used flag + key), reads
      are one-hot masked reductions, writes are fused full-array
      selects (iota == pos). A gather/scatter HLO inside a while body
      is its own kernel launch per iteration on this backend (~tens
      of us), so at per-key lane sizes this form collapses the step
      to a handful of fused kernels and runs 3-5x faster once enough
      lanes amortize the array passes. Only the memo-cache probe
      stays a real gather (a one-hot pass over the whole cache per
      step would swamp the win).

    Both forms read the round-A linked-list writes back via scalar
    fixups, so the intermediate list state never materializes."""
    n_pad = ent["f"].shape[0]
    m = 2 * n_pad + 1
    cache_size = 1 << cache_bits
    mask = jnp.uint32(cache_size - 1)
    key_width = n_words + (n_state if jm.state_in_key else 0)
    # runtime input, not a compile-time constant: every step budget
    # shares one compiled kernel per shape
    max_steps = ent["max_steps"]

    iota_m = lax.iota(jnp.int32, m)
    iota_w = lax.iota(jnp.int32, n_words)
    iota_n = lax.iota(jnp.int32, n_pad)
    iota_c = lax.iota(jnp.int32, cache_size)

    ztab_i32 = jnp.asarray(_zobrist_table(n_pad).view(np.int32))
    ent_tab = jnp.stack(
        [ent["f"].astype(jnp.int32),
         ent["v1"].astype(jnp.int32),
         ent["v2"].astype(jnp.int32),
         ent["crashed"].astype(jnp.int32),
         ent["call_node"].astype(jnp.int32),
         ent["ret_node"].astype(jnp.int32),
         ztab_i32],
        axis=-1)                                        # [n_pad, 7]
    node_tab = jnp.stack(
        [ent["node_entry"].astype(jnp.int32),
         ent["node_is_call"].astype(jnp.int32)],
        axis=-1)                                        # [m, 2]
    n_completed = ent["n_completed"]

    init = dict(
        node=ent["nxt0"][0].astype(jnp.int32),
        state=jnp.asarray(jm.init_vec(n_state), jnp.int32),
        linearized=jnp.zeros(n_words, jnp.uint32),
        h_lin=jnp.uint32(2166136261),
        depth=jnp.int32(0),
        completed_done=jnp.int32(0),
        steps=jnp.int32(0),
        verdict=jnp.where(
            n_completed == 0, jnp.int32(VALID), jnp.int32(RUNNING)
        ),
    )
    nxt0 = ent["nxt0"].astype(jnp.int32)
    prv0 = ent["prv0"].astype(jnp.int32)
    if dense:
        stack_width = 1 + (0 if jm.has_unstep else n_state)
        init["np2"] = jnp.stack([nxt0, prv0], axis=-1)
        init["stack"] = jnp.zeros((n_pad, stack_width), jnp.int32)
        # col 0: used flag; cols 1..: the exact (bitset, state) key
        init["cache"] = jnp.zeros((cache_size, 1 + key_width), jnp.int32)
    else:
        init["nxt"] = nxt0
        init["prv"] = prv0
        init["stack_e"] = jnp.zeros(n_pad, jnp.int32)
        if not jm.has_unstep:
            init["stack_s"] = jnp.zeros((n_pad, n_state), jnp.int32)
        init["cache_keys"] = jnp.zeros((cache_size, key_width), jnp.int32)
        init["cache_used"] = jnp.zeros(cache_size, bool)

    def cond(st):
        return (st["verdict"] == RUNNING) & (st["steps"] < max_steps)

    def oh_read(table, idx):
        """table[idx] as a one-hot masked reduction — fuses into the
        surrounding elementwise kernels where a gather would be its
        own per-iteration launch. Out-of-range idx yields zeros (a
        gather would clamp/wrap to garbage instead); every consumer
        of a possibly-out-of-range read is gated, so the forms still
        decide identically."""
        oh = lax.iota(jnp.int32, table.shape[0]) == idx
        return jnp.sum(jnp.where(oh[:, None], table, 0), axis=0)

    # ---- the array strategy: layout + read/write primitives are the
    # ONLY form-divergent code ----
    if dense:
        def read_np(st, i):
            r = oh_read(st["np2"], i)
            return r[0], r[1]

        def read_stack_top(st, depth):
            srow = oh_read(st["stack"], depth - 1)
            return srow[0], srow[1:]

        def probe_cache(st, probe_idx):
            crows = st["cache"][probe_idx]               # [P, 1+kw]
            return crows[:, 0] != 0, crows[:, 1:]

        def list_round(st, out, do_lift, do_back, cn, rn, cn2, rn2, node):
            """Linked-list update, dense: reads are one-hot, the
            round-A intermediate is read back via scalar fixups (never
            materialized), the writes one fused B-over-A select per
            column. Returns the post-update nxt values node selection
            needs."""
            np2 = st["np2"]
            zero = jnp.int32(0)
            nxt_cn, prv_cn = read_np(st, cn)
            nxt_rn, prv_rn = read_np(st, rn)
            nxt_rn2, prv_rn2 = read_np(st, rn2)
            nxt_cn2, prv_cn2 = read_np(st, cn2)
            nxt_0, prv_0 = np2[0, 0], np2[0, 1]
            nxt_node = read_np(st, node)[0]

            posA_n = jnp.where(do_lift, prv_cn,
                               jnp.where(do_back, prv_rn2, zero))
            valA_n = jnp.where(do_lift, nxt_cn,
                               jnp.where(do_back, rn2, nxt_0))
            posA_p = jnp.where(do_lift, nxt_cn,
                               jnp.where(do_back, nxt_rn2, zero))
            valA_p = jnp.where(do_lift, prv_cn,
                               jnp.where(do_back, rn2, prv_0))
            rd_n1 = lambda i, raw: jnp.where(i == posA_n, valA_n, raw)  # noqa: E731,E501
            rd_p1 = lambda i, raw: jnp.where(i == posA_p, valA_p, raw)  # noqa: E731,E501
            posB_n = jnp.where(do_lift, rd_p1(rn, prv_rn),
                               jnp.where(do_back, rd_p1(cn2, prv_cn2),
                                         zero))
            valB_n = jnp.where(do_lift, rd_n1(rn, nxt_rn),
                               jnp.where(do_back, cn2, rd_n1(zero, nxt_0)))
            posB_p = jnp.where(do_lift, rd_n1(rn, nxt_rn),
                               jnp.where(do_back, rd_n1(cn2, nxt_cn2),
                                         zero))
            valB_p = jnp.where(do_lift, rd_p1(rn, prv_rn),
                               jnp.where(do_back, cn2, rd_p1(zero, prv_0)))

            col_n = jnp.where(iota_m == posB_n, valB_n,
                              jnp.where(iota_m == posA_n, valA_n,
                                        np2[:, 0]))
            col_p = jnp.where(iota_m == posB_p, valB_p,
                              jnp.where(iota_m == posA_p, valA_p,
                                        np2[:, 1]))
            out["np2"] = jnp.stack([col_n, col_p], axis=-1)
            rd_nout = lambda i, raw: jnp.where(  # noqa: E731
                i == posB_n, valB_n, rd_n1(i, raw))
            return (rd_nout(zero, nxt_0), rd_nout(node, nxt_node),
                    rd_nout(cn2, nxt_cn2))

        def write_cache_stack(st, out, w):
            at_ins = (iota_c == w["ins"]) & w["do_lift"]
            ins_row = jnp.concatenate(
                [jnp.ones(1, jnp.int32), w["key"]])
            out["cache"] = jnp.where(
                at_ins[:, None], ins_row[None, :], st["cache"])
            srow_parts = [w["e"][None]]
            if not jm.has_unstep:
                srow_parts.append(w["state"])
            srow_new = jnp.concatenate(srow_parts)
            out["stack"] = jnp.where(
                ((iota_n == w["depth"]) & w["do_lift"])[:, None],
                srow_new[None, :], st["stack"])
    else:
        def read_stack_top(st, depth):
            e2 = st["stack_e"][depth - 1]
            snap = None if jm.has_unstep else st["stack_s"][depth - 1]
            return e2, snap

        def probe_cache(st, probe_idx):
            return (st["cache_used"][probe_idx],
                    st["cache_keys"][probe_idx])

        def list_round(st, out, do_lift, do_back, cn, rn, cn2, rn2, node):
            """Linked-list update, scatter: two rounds of conditional
            scalar scatters with the round-A intermediate materialized
            and gathered from — bounded expression depth keeps XLA
            compile time sane under unroll (the fixup form's select
            chains compound across unrolled steps)."""
            nxt, prv = st["nxt"], st["prv"]
            zero = jnp.int32(0)
            posA_n = jnp.where(do_lift, prv[cn],
                               jnp.where(do_back, prv[rn2], zero))
            valA_n = jnp.where(do_lift, nxt[cn],
                               jnp.where(do_back, rn2, nxt[0]))
            posA_p = jnp.where(do_lift, nxt[cn],
                               jnp.where(do_back, nxt[rn2], zero))
            valA_p = jnp.where(do_lift, prv[cn],
                               jnp.where(do_back, rn2, prv[0]))
            nxt1 = nxt.at[posA_n].set(valA_n)
            prv1 = prv.at[posA_p].set(valA_p)

            posB_n = jnp.where(do_lift, prv1[rn],
                               jnp.where(do_back, prv1[cn2], zero))
            valB_n = jnp.where(do_lift, nxt1[rn],
                               jnp.where(do_back, cn2, nxt1[0]))
            posB_p = jnp.where(do_lift, nxt1[rn],
                               jnp.where(do_back, nxt1[cn2], zero))
            valB_p = jnp.where(do_lift, prv1[rn],
                               jnp.where(do_back, cn2, prv1[0]))
            nxt_out = nxt1.at[posB_n].set(valB_n)
            out["nxt"] = nxt_out
            out["prv"] = prv1.at[posB_p].set(valB_p)
            return nxt_out[0], nxt_out[node], nxt_out[cn2]

        def write_cache_stack(st, out, w):
            out["cache_keys"] = st["cache_keys"].at[w["ins"]].set(
                jnp.where(w["do_lift"], w["key"],
                          st["cache_keys"][w["ins"]]))
            out["cache_used"] = st["cache_used"].at[w["ins"]].set(
                st["cache_used"][w["ins"]] | w["do_lift"])
            out["stack_e"] = st["stack_e"].at[w["depth"]].set(
                jnp.where(w["do_lift"], w["e"],
                          st["stack_e"][w["depth"]]))
            if not jm.has_unstep:
                out["stack_s"] = st["stack_s"].at[w["depth"]].set(
                    jnp.where(w["do_lift"], w["state"],
                              st["stack_s"][w["depth"]]))

    rd = oh_read if dense else (lambda table, idx: table[idx])

    def step(st):
        # gate: a finished lane (or one past its budget) must pass
        # through unrolled steps untouched — every write below is
        # conditioned on one of do_lift/advance/do_back, all of which
        # require `active`
        active = (st["verdict"] == RUNNING) & (st["steps"] < max_steps)

        node = st["node"]
        state = st["state"]
        lin = st["linearized"]
        depth = st["depth"]
        zero = jnp.int32(0)

        nt = rd(node_tab, node)
        e = nt[0]
        is_call = (node != 0) & (nt[1] != 0)

        e2, snap = read_stack_top(st, depth)

        row_e = rd(ent_tab, e)
        row_e2 = rd(ent_tab, e2)
        f_e, v1_e, v2_e = row_e[0], row_e[1], row_e[2]
        crashed_e = row_e[3] != 0
        cn, rn = row_e[4], row_e[5]
        z_e = lax.bitcast_convert_type(row_e[6], jnp.uint32)
        f_e2, v1_e2, v2_e2 = row_e2[0], row_e2[1], row_e2[2]
        crashed_e2 = row_e2[3] != 0
        cn2, rn2 = row_e2[4], row_e2[5]
        z_e2 = lax.bitcast_convert_type(row_e2[6], jnp.uint32)

        new_state, ok = jm.vec_step(state, f_e, v1_e, v2_e)
        new_state = new_state.astype(jnp.int32)
        can_lin = active & is_call & ok

        word = e // 32
        bit = (jnp.uint32(1) << (e % 32).astype(jnp.uint32))
        new_lin = lin | jnp.where(iota_w == word, bit, jnp.uint32(0))
        new_h = st["h_lin"] ^ z_e  # incremental bitset hash

        # ---- cache probe (exact full-key compare) ----
        # canonicalized state: memo keys encode LOGICAL state (e.g.
        # the fifo ring buffer's live window, not its offsets)
        key_state = jm.vec_canon(new_state) if jm.state_in_key \
            else new_state
        key_parts = [new_lin.astype(jnp.int32)]
        if jm.state_in_key:
            key_parts.append(key_state)
        key = jnp.concatenate(key_parts)
        h = _mix_hash(new_h, key_state, jm.state_in_key)
        probe_idx = (h[None] + jnp.arange(N_PROBES, dtype=jnp.uint32)) & mask
        probe_idx = probe_idx.astype(jnp.int32)
        slot_used, slot_keys = probe_cache(st, probe_idx)
        matches = slot_used & jnp.all(slot_keys == key[None, :], axis=1)
        found = jnp.any(matches)
        free = ~slot_used
        has_free = jnp.any(free)
        first_free = jnp.argmax(free)
        # insert slot: first free probe, else overwrite last probe
        # (only loses pruning, never soundness)
        ins = jnp.where(has_free, probe_idx[first_free], probe_idx[-1])

        do_lift = can_lin & ~found

        lift_completed = st["completed_done"] + jnp.where(
            crashed_e, 0, 1).astype(jnp.int32)

        # ---- branch: backtrack (hit a return node / END) ----
        can_pop = depth > 0
        if jm.has_unstep:
            # exact inverse of the popped (applied) transition — no
            # snapshot stack needed
            pop_state = jm.vec_unstep(
                state, f_e2, v1_e2, v2_e2).astype(jnp.int32)
        else:
            pop_state = snap
        word2 = e2 // 32
        bit2 = (jnp.uint32(1) << (e2 % 32).astype(jnp.uint32))
        pop_lin = lin & ~jnp.where(iota_w == word2, bit2, jnp.uint32(0))
        pop_completed = st["completed_done"] - jnp.where(
            crashed_e2, 0, 1).astype(jnp.int32)

        advance = active & is_call & ~do_lift  # seen or inconsistent
        backtrack = active & ~is_call
        do_back = backtrack & can_pop

        out = dict(
            steps=st["steps"] + active.astype(jnp.int32),
        )

        # ---- linked list (strategy): lift unlinks cn then rn,
        # backtrack relinks rn2 then cn2, with identity writes at the
        # sentinel when neither branch fires; returns the post-update
        # nxt reads the node selection needs
        new_nxt_0, new_nxt_node, new_nxt_cn2 = list_round(
            st, out, do_lift, do_back, cn, rn, cn2, rn2, node)

        write_cache_stack(st, out, dict(
            ins=ins, key=key, do_lift=do_lift, e=e, state=state,
            depth=depth,
        ))

        # ---- select scalars ----
        sel = lambda on_lift, on_adv, on_back: jnp.where(  # noqa: E731
            do_lift, on_lift, jnp.where(advance, on_adv, on_back)
        )

        node_out = sel(
            new_nxt_0,
            new_nxt_node,
            jnp.where(do_back, new_nxt_cn2, node),
        )
        state_out = sel(new_state, state, jnp.where(do_back, pop_state, state))
        lin_out = jnp.where(
            do_lift,
            new_lin,
            jnp.where(do_back, pop_lin, lin),
        )
        h_out = sel(new_h, st["h_lin"],
                    jnp.where(do_back, st["h_lin"] ^ z_e2, st["h_lin"]))
        depth_out = sel(depth + 1, depth, jnp.where(do_back, depth - 1, depth))
        completed_out = sel(
            lift_completed,
            st["completed_done"],
            jnp.where(do_back, pop_completed, st["completed_done"]),
        )

        verdict = jnp.where(
            do_lift & (lift_completed == n_completed),
            jnp.int32(VALID),
            jnp.where(
                backtrack & ~can_pop, jnp.int32(INVALID), st["verdict"]
            ),
        )

        out.update(
            node=node_out,
            state=state_out,
            linearized=lin_out,
            h_lin=h_out,
            depth=depth_out,
            completed_done=completed_out,
            verdict=verdict,
        )
        return out

    def body(st):
        for _ in range(unroll):
            st = step(st)
        return st

    out = lax.while_loop(cond, body, init)
    final_verdict = jnp.where(
        out["verdict"] == RUNNING, jnp.int32(UNKNOWN), out["verdict"]
    )
    return final_verdict, out["steps"], out["depth"]


# Where the dense (scatter-free) step form wins, measured on the v5e:
# below ~128 lanes every step is launch-overhead-bound either way and
# the dense full-array passes only add cost; at >=128 lanes the scatter
# form's per-lane buffer passes dominate and dense runs 3-5x faster —
# until n_pad grows past ~512, where the dense passes (the cache write
# in particular) become the bandwidth bill.
DENSE_MIN_LANES = 128
DENSE_MAX_PAD = 512


def _resolve_unroll(unroll: int | None, n_pad: int) -> int:
    """None -> the measured sweet spot: DEFAULT_UNROLL on per-key
    lanes, 1 on stress-sized lanes where unrolling buys nothing but
    compile time. unroll < 1 would make the while body the identity
    and spin forever, so it is rejected here."""
    if unroll is None:
        return 1 if n_pad > DENSE_MAX_PAD else DEFAULT_UNROLL
    if unroll < 1:
        raise ValueError(f"unroll must be >= 1, got {unroll}")
    return unroll


def build_kernel(jm, n_pad: int, n_state: int = 1,
                 cache_bits: int = DEFAULT_CACHE_BITS,
                 unroll: int | None = None,
                 dense: bool | None = None):
    """A jitted batch kernel for histories padded to n_pad entries with
    int32[n_state] model state: dict of stacked arrays (including a
    per-lane "max_steps" budget) -> (verdicts, steps, depths), vmapped
    over the leading lane axis."""
    n_words = max(1, (n_pad + 31) // 32)
    unroll = _resolve_unroll(unroll, n_pad)
    # lane-count-aware dense auto lives in analysis_batch; a direct
    # build picks the always-safe scatter form
    dense = bool(dense)

    def one(ent):
        return _search_one(ent, jm, n_state, n_words, cache_bits,
                           unroll, dense)

    return jax.jit(jax.vmap(one))


_kernel_cache: dict = {}


def _kernel_for(jm, n_pad: int, n_state: int, cache_bits: int,
                unroll: int | None = None,
                dense: bool | None = None):
    # normalize before keying so None/False (and None/default unroll)
    # don't compile the same kernel twice; the step budget is a
    # runtime input and never keys a compile
    unroll = _resolve_unroll(unroll, n_pad)
    dense = bool(dense)
    key = (jm.name, n_pad, n_state, cache_bits, unroll, dense)
    if key not in _kernel_cache:
        _kernel_cache[key] = build_kernel(
            jm, n_pad, n_state, cache_bits, unroll, dense
        )
    return _kernel_cache[key]


def _pad_size(n: int) -> int:
    """Bucket entry counts to limit kernel recompiles (variable-length
    subhistories -> a few static shapes; SURVEY.md SS7.4). The rule —
    pow2, floor 32 — is the package-wide one (ops.pad_size), shared
    with the closure engines' adjacency buckets."""
    from . import pad_size

    return pad_size(n)


def _stack(ents: list[dict]) -> dict:
    return {
        k: jnp.asarray(np.stack([e[k] for e in ents]))
        for k in ents[0]
    }


def analysis_batch(
    model,
    entries_list: list[Entries],
    cache_bits: int = DEFAULT_CACHE_BITS,
    max_steps: int = DEFAULT_MAX_STEPS,
    devices=None,
    unroll: int | None = None,
    dense: bool | None = None,
) -> list[WGLResult]:
    """Check many independent histories in one vmapped kernel launch.
    With `devices` (or more than one addressable device and enough
    lanes), lanes are sharded across a 1-D mesh."""
    jm = mjit.for_model(model)
    if jm is None:
        raise ValueError(f"model {model!r} has no int32 kernel encoding")
    if not entries_list:
        return []
    n_pad = _pad_size(max(len(es) for es in entries_list))
    # state width: max over lanes, bucketed like n_pad to bound
    # recompiles (lanes narrower than the bucket just never touch the
    # padding slots — their codecs only emit indices < their own width)
    n_state = max(jm.lane_width(es) for es in entries_list)
    n_state = 1 if n_state <= 1 else _next_pow2(n_state)
    ents = [encode_entries(es, jm, n_pad) for es in entries_list]
    n_lanes = len(ents)
    if dense is None:
        dense = n_lanes >= DENSE_MIN_LANES and n_pad <= DENSE_MAX_PAD
    for e in ents:
        e["max_steps"] = np.int32(max_steps)
    batch = _stack(ents)

    devices = devices if devices is not None else jax.devices()
    n_dev = len(devices)
    # row j of the (possibly permuted, padded) batch -> original lane
    # index, or -1 for a padding row
    row_to_lane = list(range(n_lanes))
    if n_dev > 1 and n_lanes >= n_dev:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        # Cost-aware lane scheduling: the sharded axis splits into
        # CONTIGUOUS per-device chunks, and a device's wall clock is
        # bounded by its deepest lane — so deal lanes LONGEST-FIRST
        # round-robin across chunks (entry count is the cheap,
        # monotone proxy for search depth) instead of shipping them in
        # arrival order, where a run of deep lanes lands on one
        # device and serializes the batch. Chunks pad to equal length
        # with EMPTY lanes (n_completed == 0 -> VALID at init, no
        # steps), never with copies of a real lane (duplicate work).
        order = sorted(range(n_lanes),
                       key=lambda i: -len(entries_list[i]))
        chunks: list[list[int]] = [[] for _ in range(n_dev)]
        for j, i in enumerate(order):
            chunks[j % n_dev].append(i)
        per = max(len(c) for c in chunks)
        empty = {k: np.zeros_like(ents[0][k]) for k in ents[0]}
        rows = []
        row_to_lane = []
        for c in chunks:
            for i in c:
                rows.append(ents[i])
                row_to_lane.append(i)
            for _ in range(per - len(c)):
                rows.append(empty)
                row_to_lane.append(-1)
        batch = _stack(rows)
        mesh = Mesh(np.array(devices), ("keys",))
        sharding = NamedSharding(mesh, P("keys"))
        batch = {k: jax.device_put(v, sharding) for k, v in batch.items()}

    kernel = _kernel_for(jm, n_pad, n_state, cache_bits, unroll, dense)
    verdicts_dev, steps_dev, _depths = kernel(batch)
    # deferred gather (same discipline as wgl_pallas_vec's launch
    # pipeline): start BOTH device->host copies before materializing
    # either, instead of block_until_ready-ing the whole tuple and
    # fetching serially — np.asarray below is the completion sync
    for a in (verdicts_dev, steps_dev):
        try:
            a.copy_to_host_async()
        except (AttributeError, NotImplementedError):
            pass
    verdicts = np.asarray(verdicts_dev)
    steps = np.asarray(steps_dev)

    out: list = [None] * n_lanes
    for row, i in enumerate(row_to_lane):
        if i < 0:
            continue
        v = int(verdicts[row])
        valid = {VALID: True, INVALID: False, UNKNOWN: "unknown"}[v]
        r = WGLResult(valid=valid, steps=int(steps[row]))
        if valid is False:
            # Recover counterexample details host-side (only failed
            # keys pay this cost; verdicts agree by construction),
            # native engine preferred (wgl_host.recover_invalid).
            r = recover_invalid(model, entries_list[i])
        out[i] = r
    return out


# Conservative lower bound on kernel search steps per second, used to
# translate a wall-clock budget into a step budget. Underestimating only
# makes the kernel give up (unknown) EARLIER than the wall budget —
# never later by more than one kernel launch.
STEPS_PER_SEC_ESTIMATE = 50_000


def analysis(
    model,
    history,
    time_limit: float | None = None,
    max_steps: int = DEFAULT_MAX_STEPS,
    cache_bits: int = DEFAULT_CACHE_BITS,
) -> WGLResult:
    """Single-history TPU check (the jepsen.checker/linearizable
    backend). A time_limit is translated into a step budget using a
    conservative steps/sec estimate (a while_loop cannot consult the
    wall clock mid-flight on device)."""
    from ..history import entries as make_entries

    es = history if isinstance(history, Entries) else make_entries(history)
    if es.n_completed == 0:
        return WGLResult(valid=True)
    if time_limit is not None:
        max_steps = min(
            max_steps, max(1000, int(time_limit * STEPS_PER_SEC_ESTIMATE))
        )
    (r,) = analysis_batch(
        model, [es], cache_bits=cache_bits, max_steps=max_steps
    )
    return r


def probe() -> bool:
    """Compile-and-run one minimal lane through the vmapped kernel
    (trace, XLA compile, launch, fetch). Run in a subprocess by the
    supervisor's first-compile probe (checker/supervisor.py) so a
    FATAL compile abort is contained."""
    from ..history import Op, entries as make_entries
    from ..models import CASRegister

    h = [Op(0, "invoke", "write", 1, time=0, index=0),
         Op(0, "ok", "write", 1, time=1, index=1)]
    (r,) = analysis_batch(CASRegister(None), [make_entries(h)],
                          max_steps=10_000)
    return r.valid is True


def probe_mesh() -> bool:
    """Compile-and-run one uneven lane batch dealt longest-first over
    every addressable device (the wgl_mesh rung's launch shape): an
    odd lane count exercises the empty-lane chunk padding too."""
    from ..history import Op, entries as make_entries
    from ..models import CASRegister

    devices = jax.devices()
    ess = []
    for lane in range(2 * len(devices) + 1):
        h = []
        for i in range(1 + lane % 3):
            h.append(Op(0, "invoke", "write", i, time=2 * i,
                        index=2 * i))
            h.append(Op(0, "ok", "write", i, time=2 * i + 1,
                        index=2 * i + 1))
        ess.append(make_entries(h))
    rs = analysis_batch(CASRegister(None), ess, max_steps=10_000,
                        devices=devices)
    return all(r.valid is True for r in rs)
