"""Transitive closure by boolean repeated squaring on device.

The cycle checker's device rung: reachability over a dependency
adjacency matrix computed as R <- R | (R @ R > 0) until fixpoint —
log2(n) rounds of dense matmul, which lands on the MXU, instead of the
host's O(n*(n+e)) pointer-chasing DFS (ops/closure_host.py). The
resident loop state is a *packed* uint32 bitmat (32 columns per word):
each round unpacks to a 0/1 float32 matrix for the matmul, repacks,
and compares packed words for the fixpoint early-exit, so the
while-loop carry and the equality test touch n*n/32 words, not n*n
lanes.

Matrices are padded to a power of two (min 32) so recompiles bucket by
size the way the search kernels bucket by history length, and
`reach_batch` stacks same-pad-size matrices into one batched launch.
Padding is all-zero rows/columns, which cannot create or destroy
paths, so slicing the result back out is exact.

Closures here are irreflexive-path closures, matching the host engine:
out[i, j] iff a path i -> ... -> j with >= 1 edge exists, so the
diagonal marks nodes on genuine cycles.

Two shape-special paths share the same fixpoint loop:

- buckets that fit ONE uint32 word of columns (n <= 32) square with
  pure bitwise ops — row i OR-folds the rows its word selects — and
  never round-trip through float32 at all;
- with `devices` (the supervisor's `closure_mesh` rung), the packed
  bitmat is **block-row sharded** over a 1-D mesh via shard_map: each
  device keeps its row block as the while-loop carry, `lax.all_gather`
  reconstructs the full packed matrix once per round (the column view
  each row block squares against), and the fixpoint test is a
  `psum`-reduced equality — so the resident state per device is
  n*n/32/D words and graphs too big for one chip's HBM close at all,
  while same-bucket batches split their matmul work D ways. The
  transient all-gathered matrix is the memory price of each round
  (docs/tutorial/11-mesh.md).
"""

from __future__ import annotations

from functools import partial, reduce

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import MIN_PAD, _configure_compilation_cache, pad_size as _pad_size

# before any kernel compiles (see ops/__init__ docstring)
_configure_compilation_cache()


def _pack(m):
    """[..., r, c] 0/1 -> [..., r, c//32] uint32 (bit b of word w is
    column w*32+b). Rows and columns are independent so the mesh
    path's row-padded (non-square) blocks pack the same way."""
    *lead, r, c = m.shape
    words = m.reshape(*lead, r, c // 32, 32).astype(jnp.uint32)
    return (words << jnp.arange(32, dtype=jnp.uint32)).sum(
        axis=-1, dtype=jnp.uint32)


def _unpack(words, n: int):
    """[..., n, n//32] uint32 -> [..., n, n] float32 0/1."""
    bits = (words[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    return bits.reshape(*words.shape[:-1], n).astype(jnp.float32)


@partial(jax.jit, static_argnames=("n", "rounds"))
def _closure_packed(words0, n: int, rounds: int):
    """Fixpoint of R <- R | (R @ R > 0) on a packed [b, n, n//32]
    bitmat batch. `rounds` bounds the loop (ceil(log2(n)) squarings
    reach any path; +1 proves the fixpoint), early-exiting as soon as
    one squaring changes nothing."""

    def cond(carry):
        t, _, done = carry
        return jnp.logical_and(t < rounds, jnp.logical_not(done))

    def body(carry):
        t, words, _ = carry
        m = _unpack(words, n)
        # 0/1 float32 matmul counts paths of length 2 through R; exact
        # for n <= 2^24 so thresholding at >0 is the boolean product
        prod = jnp.matmul(m, m, preferred_element_type=jnp.float32)
        nxt = _pack(jnp.logical_or(m > 0, prod > 0))
        done = jnp.all(nxt == words)
        return t + 1, nxt, done

    _, words, _ = lax.while_loop(
        cond, body, (jnp.int32(0), words0, jnp.array(False)))
    return words


@partial(jax.jit, static_argnames=("rounds",))
def _closure_packed_word(words0, rounds: int):
    """The one-word bucket (n <= 32): each row is a single uint32, so
    the boolean square is 32 conditional OR-folds — prod[i] = OR over
    set bits k of row i of word[k] — with no float32 round-trip.
    `words0` is [b, 32] uint32; semantics match _closure_packed bit
    for bit (OR of ANDs == thresholded counting matmul)."""

    def cond(carry):
        t, _, done = carry
        return jnp.logical_and(t < rounds, jnp.logical_not(done))

    def body(carry):
        t, words, _ = carry
        # bit k of words[b, i] selects row k into row i's OR-fold
        sel = [(words >> jnp.uint32(k)) & 1 for k in range(32)]
        prod = reduce(
            jnp.bitwise_or,
            [jnp.where(sel[k].astype(bool), words[:, k][:, None],
                       jnp.uint32(0)) for k in range(32)])
        nxt = words | prod
        done = jnp.all(nxt == words)
        return t + 1, nxt, done

    _, words, _ = lax.while_loop(
        cond, body, (jnp.int32(0), words0, jnp.array(False)))
    return words


def _closure_block(batch: np.ndarray) -> np.ndarray:
    """One device launch: [b, p, p] bool (p a pad size) -> closure."""
    b, p, _ = batch.shape
    # ceil(log2(p)) squarings cover every simple path; one more round
    # observes the fixpoint and exits
    rounds = max(1, p.bit_length())
    if p == MIN_PAD:
        words0 = _pack(jnp.asarray(batch, dtype=jnp.float32))[..., 0]
        words = _closure_packed_word(words0, rounds)
        return np.asarray(_unpack(words[..., None], p) > 0)
    words0 = _pack(jnp.asarray(batch, dtype=jnp.float32))
    words = _closure_packed(words0, p, rounds)
    return np.asarray(_unpack(words, p) > 0)


# ---------------------------------------------------------------------------
# Mesh path: block-row-sharded squaring over a 1-D device mesh.

def _shard_map_fn():
    # jax.shard_map only exists on newer jax; the experimental module
    # spans every version this repo supports (wgl_pallas_vec idiom)
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    return sm


_mesh_kernel_cache: dict = {}


def _mesh_kernel(mesh, p: int, rounds: int):
    """The shard-mapped fixpoint loop for one mesh + pad bucket. The
    carried state is each device's row block of the packed bitmat
    ([b, rows/D, p/32] uint32); every round all-gathers the blocks
    into the full column view, squares the local rows against it, and
    psum-reduces the per-device "anything changed?" bit so every
    device exits the while_loop on the same round."""
    from jax.sharding import PartitionSpec as P

    key = (tuple(d.id for d in mesh.devices.flat), p, rounds)
    if key in _mesh_kernel_cache:
        return _mesh_kernel_cache[key]

    def sharded(words0):
        def cond(carry):
            t, _, done = carry
            return jnp.logical_and(t < rounds, jnp.logical_not(done))

        def body(carry):
            t, local, _ = carry
            # [b, rows, p/32]: every device's row blocks, in mesh
            # order — rows beyond p are all-zero mesh padding
            full = lax.all_gather(local, "rows", axis=1, tiled=True)
            m_local = _unpack(local, p)
            m_full = _unpack(full, p)[:, :p, :]
            prod = jnp.matmul(m_local, m_full,
                              preferred_element_type=jnp.float32)
            nxt = _pack(jnp.logical_or(m_local > 0, prod > 0))
            changed = jnp.any(nxt != local).astype(jnp.int32)
            done = lax.psum(changed, "rows") == 0
            return t + 1, nxt, done

        _, words, _ = lax.while_loop(
            cond, body, (jnp.int32(0), words0, jnp.array(False)))
        return words

    sm = _shard_map_fn()
    kw = dict(mesh=mesh, in_specs=P(None, "rows", None),
              out_specs=P(None, "rows", None))
    # the psum-ed `done` is replicated by construction; replication
    # checking off (the kwarg was renamed check_rep -> check_vma)
    try:
        f = sm(sharded, check_vma=False, **kw)
    except TypeError:
        f = sm(sharded, check_rep=False, **kw)
    _mesh_kernel_cache[key] = jax.jit(f)
    return _mesh_kernel_cache[key]


def _closure_block_mesh(batch: np.ndarray, devices) -> np.ndarray:
    """One mesh launch: [b, p, p] bool -> closure, rows dealt in
    contiguous blocks across `devices`. Rows pad with zeros to a
    multiple of the mesh size (zero rows neither create nor destroy
    paths — the same argument as the pow2 pad), so uneven block
    counts (p not divisible by D) are exact.

    The batch axis buckets to a power of two too: the kernel cache is
    keyed (mesh, p, rounds) but jit still retraces per input shape,
    and sharded compiles are an order of magnitude pricier than
    single-device ones — without the bucket, every distinct
    component-batch size a classify run produces pays a fresh mesh
    compile. All-zero pad matrices close to zero and slice back off.
    """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    b, p, _ = batch.shape
    bb = 1 << max(0, b - 1).bit_length()
    if bb != b:
        batch = np.concatenate(
            [batch, np.zeros((bb - b, p, p), dtype=bool)])
    d = len(devices)
    rows = ((p + d - 1) // d) * d
    if rows != p:
        padded = np.zeros((b, rows, p), dtype=bool)
        padded[:, :p, :] = batch
        batch = padded
    rounds = max(1, p.bit_length())
    words0 = _pack(jnp.asarray(batch, dtype=jnp.float32))
    mesh = Mesh(np.array(devices), ("rows",))
    sharding = NamedSharding(mesh, P(None, "rows", None))
    words0 = jax.device_put(words0, sharding)
    words = _mesh_kernel(mesh, p, rounds)(words0)
    try:  # deferred gather (wgl_tpu idiom); np.asarray is the sync
        words.copy_to_host_async()
    except (AttributeError, NotImplementedError):
        pass
    return np.asarray(_unpack(words, p) > 0)[:b, :p, :]


def reach(adj: np.ndarray) -> np.ndarray:
    """Irreflexive-path closure of one dense boolean adjacency matrix
    (device repeated squaring). Same contract as closure_host.reach."""
    return reach_batch([adj])[0]


def reach_batch(adjs, max_steps=None, time_limit=None,
                devices=None) -> list:
    """Closure of each adjacency matrix in `adjs`, aligned with the
    input. Matrices are bucketed by padded size and each bucket runs
    as ONE batched device launch — single-device by default, or
    block-row sharded over `devices` (>= 2: the supervisor's
    `closure_mesh` rung). Signature matches the supervisor
    engine-runner convention (checker/supervisor.py); budgets are
    accepted for uniformity — the squaring loop terminates in
    <= log2(n)+1 rounds regardless.
    """
    adjs = [np.asarray(a, dtype=bool) for a in adjs]
    for a in adjs:
        if a.ndim != 2 or a.shape[0] != a.shape[1]:
            raise ValueError(f"adjacency must be square, got {a.shape}")
    mesh_devs = list(devices) if devices is not None else None
    if mesh_devs is not None and len(mesh_devs) < 2:
        mesh_devs = None  # a 1-device "mesh" IS the single-device path
    out: list = [None] * len(adjs)
    buckets: dict = {}
    for i, a in enumerate(adjs):
        if a.shape[0] == 0:
            out[i] = np.zeros((0, 0), dtype=bool)
            continue
        buckets.setdefault(_pad_size(a.shape[0]), []).append(i)
    for p, idxs in sorted(buckets.items()):
        batch = np.zeros((len(idxs), p, p), dtype=bool)
        for j, i in enumerate(idxs):
            n = adjs[i].shape[0]
            batch[j, :n, :n] = adjs[i]
        if mesh_devs is not None:
            closed = _closure_block_mesh(batch, mesh_devs)
        else:
            closed = _closure_block(batch)
        for j, i in enumerate(idxs):
            n = adjs[i].shape[0]
            out[i] = closed[j, :n, :n]
    return out


def reach_batch_mesh(adjs, max_steps=None, time_limit=None) -> list:
    """reach_batch over every addressable device — the supervisor's
    `closure_mesh` engine runner (checker/supervisor.py registers it
    above closure_tpu in CLOSURE_LADDER)."""
    return reach_batch(adjs, max_steps=max_steps, time_limit=time_limit,
                       devices=jax.devices())


def probe() -> bool:
    """Minimal compile-and-run: a 2-cycle inside one pad bucket. Used
    by the supervisor's first-compile subprocess probe."""
    a = np.zeros((3, 3), dtype=bool)
    a[0, 1] = a[1, 0] = True
    r = reach(a)
    return bool(r[0, 0] and r[0, 1] and not r[2, 2])


def probe_mesh() -> bool:
    """Compile-and-run the sharded squaring over every addressable
    device: a ring big enough to land in a > one-word bucket, parity
    checked against the single-device path."""
    n = 2 * MIN_PAD + 5  # pads past the word bucket; uneven vs D too
    a = np.zeros((n, n), dtype=bool)
    for i in range(n):
        a[i, (i + 1) % n] = True
    (r,) = reach_batch([a], devices=jax.devices())
    (s,) = reach_batch([a])
    return bool(np.array_equal(r, s) and r[0, 0])
