"""Transitive closure by boolean repeated squaring on device.

The cycle checker's device rung: reachability over a dependency
adjacency matrix computed as R <- R | (R @ R > 0) until fixpoint —
log2(n) rounds of dense matmul, which lands on the MXU, instead of the
host's O(n*(n+e)) pointer-chasing DFS (ops/closure_host.py). The
resident loop state is a *packed* uint32 bitmat (32 columns per word):
each round unpacks to a 0/1 float32 matrix for the matmul, repacks,
and compares packed words for the fixpoint early-exit, so the
while-loop carry and the equality test touch n*n/32 words, not n*n
lanes.

Matrices are padded to a power of two (min 32) so recompiles bucket by
size the way the search kernels bucket by history length, and
`reach_batch` stacks same-pad-size matrices into one batched launch.
Padding is all-zero rows/columns, which cannot create or destroy
paths, so slicing the result back out is exact.

Closures here are irreflexive-path closures, matching the host engine:
out[i, j] iff a path i -> ... -> j with >= 1 edge exists, so the
diagonal marks nodes on genuine cycles.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import _configure_compilation_cache

# before any kernel compiles (see ops/__init__ docstring)
_configure_compilation_cache()

MIN_PAD = 32  # one uint32 word of columns; also the smallest bucket


def _pad_size(n: int) -> int:
    p = MIN_PAD
    while p < n:
        p *= 2
    return p


def _pack(m):
    """[..., n, n] 0/1 -> [..., n, n//32] uint32 (bit b of word w is
    column w*32+b)."""
    *lead, n, _ = m.shape
    words = m.reshape(*lead, n, n // 32, 32).astype(jnp.uint32)
    return (words << jnp.arange(32, dtype=jnp.uint32)).sum(
        axis=-1, dtype=jnp.uint32)


def _unpack(words, n: int):
    """[..., n, n//32] uint32 -> [..., n, n] float32 0/1."""
    bits = (words[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    return bits.reshape(*words.shape[:-1], n).astype(jnp.float32)


@partial(jax.jit, static_argnames=("n", "rounds"))
def _closure_packed(words0, n: int, rounds: int):
    """Fixpoint of R <- R | (R @ R > 0) on a packed [b, n, n//32]
    bitmat batch. `rounds` bounds the loop (ceil(log2(n)) squarings
    reach any path; +1 proves the fixpoint), early-exiting as soon as
    one squaring changes nothing."""

    def cond(carry):
        t, _, done = carry
        return jnp.logical_and(t < rounds, jnp.logical_not(done))

    def body(carry):
        t, words, _ = carry
        m = _unpack(words, n)
        # 0/1 float32 matmul counts paths of length 2 through R; exact
        # for n <= 2^24 so thresholding at >0 is the boolean product
        prod = jnp.matmul(m, m, preferred_element_type=jnp.float32)
        nxt = _pack(jnp.logical_or(m > 0, prod > 0))
        done = jnp.all(nxt == words)
        return t + 1, nxt, done

    _, words, _ = lax.while_loop(
        cond, body, (jnp.int32(0), words0, jnp.array(False)))
    return words


def _closure_block(batch: np.ndarray) -> np.ndarray:
    """One device launch: [b, p, p] bool (p a pad size) -> closure."""
    b, p, _ = batch.shape
    words0 = _pack(jnp.asarray(batch, dtype=jnp.float32))
    # ceil(log2(p)) squarings cover every simple path; one more round
    # observes the fixpoint and exits
    rounds = max(1, p.bit_length())
    words = _closure_packed(words0, p, rounds)
    return np.asarray(_unpack(words, p) > 0)


def reach(adj: np.ndarray) -> np.ndarray:
    """Irreflexive-path closure of one dense boolean adjacency matrix
    (device repeated squaring). Same contract as closure_host.reach."""
    return reach_batch([adj])[0]


def reach_batch(adjs, max_steps=None, time_limit=None) -> list:
    """Closure of each adjacency matrix in `adjs`, aligned with the
    input. Matrices are bucketed by padded size and each bucket runs
    as ONE batched device launch. Signature matches the supervisor
    engine-runner convention (checker/supervisor.py); budgets are
    accepted for uniformity — the squaring loop terminates in
    <= log2(n)+1 rounds regardless.
    """
    adjs = [np.asarray(a, dtype=bool) for a in adjs]
    for a in adjs:
        if a.ndim != 2 or a.shape[0] != a.shape[1]:
            raise ValueError(f"adjacency must be square, got {a.shape}")
    out: list = [None] * len(adjs)
    buckets: dict = {}
    for i, a in enumerate(adjs):
        if a.shape[0] == 0:
            out[i] = np.zeros((0, 0), dtype=bool)
            continue
        buckets.setdefault(_pad_size(a.shape[0]), []).append(i)
    for p, idxs in sorted(buckets.items()):
        batch = np.zeros((len(idxs), p, p), dtype=bool)
        for j, i in enumerate(idxs):
            n = adjs[i].shape[0]
            batch[j, :n, :n] = adjs[i]
        closed = _closure_block(batch)
        for j, i in enumerate(idxs):
            n = adjs[i].shape[0]
            out[i] = closed[j, :n, :n]
    return out


def probe() -> bool:
    """Minimal compile-and-run: a 2-cycle inside one pad bucket. Used
    by the supervisor's first-compile subprocess probe."""
    a = np.zeros((3, 3), dtype=bool)
    a[0, 1] = a[1, 0] = True
    r = reach(a)
    return bool(r[0, 0] and r[0, 1] and not r[2, 2])
