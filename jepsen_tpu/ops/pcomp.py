"""P-compositional decomposition of unordered-queue histories.

"Faster linearizability checking via P-compositionality" (Horn &
Kroening, PAPERS.md) observes that when an object is a PRODUCT of
independent components and every operation touches exactly one
component, Herlihy-Wing locality applies componentwise: a history is
linearizable iff each component's projection is. The unordered queue
(knossos.model/unordered-queue; models/__init__.py:134-149) is exactly
such a product — its state is a multiset, i.e. one counter per value,
and enqueue(v)/dequeue(v) read and write only v's counter — so a
queue history decomposes BY VALUE into micro-histories of a handful
of ops each. That turns the search knossos finds hardest (BASELINE
config 4: 10k-op queue histories under a partition nemesis, where
interleaving count explodes) into thousands of trivial lanes that the
batched engines clear in one pass.

Soundness notes, matching the reference's semantics exactly:
- A crashed (:info) dequeue records no value. Knossos's model steps
  (dequeue, nil) to Inconsistent, so such an entry can never
  linearize; since crashed entries are optional, it is semantically
  absent from every linearization and DROPS from the decomposition.
- A crashed enqueue carries its invoke value and projects normally
  (it may or may not have landed — exactly what the sub-lane search
  decides).
- An OK entry with an op the model doesn't know (or an ok dequeue of
  a never-enqueued value) makes its own lane invalid, which is the
  whole history's verdict — same as the undecomposed search.
- Real-time order is preserved: a projection keeps the RELATIVE order
  of its call/ret positions, and precedence between two entries is a
  positional comparison, so re-ranking cannot create or destroy a
  happens-before edge within a lane. FIFO queues do NOT decompose
  (order couples values); they stay on the full search.
"""

from __future__ import annotations

import numpy as np

from ..history import Entries
from ..models import UnorderedQueue


def eligible(model) -> bool:
    return isinstance(model, UnorderedQueue) and not model.pending


def _subset(es: Entries, idx: list) -> Entries:
    """Sub-Entries over `idx`, positions re-ranked order-preservingly."""
    sel = np.asarray(idx, np.int64)
    pos = np.concatenate([es.call_pos[sel], es.ret_pos[sel]])
    order = np.argsort(pos, kind="stable")
    rank = np.empty(len(pos), np.int64)
    rank[order] = np.arange(len(pos))
    m = len(idx)
    return Entries(
        f=[es.f[i] for i in idx],
        value_in=[es.value_in[i] for i in idx],
        value_out=[es.value_out[i] for i in idx],
        crashed=es.crashed[sel],
        call_pos=rank[:m],
        ret_pos=rank[m:],
        invokes=[es.invokes[i] for i in idx],
    )


def split(es: Entries) -> list | None:
    """Per-value sub-Entries, or None when the history isn't cleanly
    decomposable (an unhashable payload — dict-keyed grouping must use
    the same ==/hash equivalence the model's multiset does)."""
    groups: dict = {}
    try:
        for i, (f, v, crashed) in enumerate(
                zip(es.f, es.value_out, es.crashed)):
            if f == "dequeue" and crashed and v is None:
                continue  # can never linearize; optional -> absent
            groups.setdefault(v, []).append(i)
    except TypeError:  # unhashable payload
        return None
    return [_subset(es, idx) for idx in groups.values()]
