"""P-compositional decomposition of histories over product models.

"Faster linearizability checking via P-compositionality" (Horn &
Kroening, PAPERS.md) observes that when an object is a PRODUCT of
independent components and every operation touches exactly one
component, Herlihy-Wing locality applies componentwise: a history is
linearizable iff each component's projection is. Which models decompose
— and for which histories — is the model's own structural knowledge,
so the split is driven by the Model.components hook
(models/__init__.py) rather than type cases here (VERDICT r4 item 6):

- UnorderedQueue decomposes BY VALUE (its multiset state is one
  counter per value; enqueue(v)/dequeue(v) touch only v's counter) —
  the search knossos finds hardest (BASELINE config 4: 10k-op queue
  histories, where interleaving count explodes) becomes thousands of
  trivial lanes the batched engines clear in one pass.
- MultiRegister decomposes BY KEY when every txn carries exactly one
  micro-op, each projected lane REWRITING to plain register ops so it
  gets the kernel encoding and rides the batched TPU path.

Decomposition multiplies lane counts (one 10k-op queue history can
become thousands of micro-lanes), which is exactly the shape the
batched router prices: checker/linearizable groups the flattened lanes
per sub-model and routes each group through the measured-crossover
policy (checker/calibrate.py) — groups at or past the calibrated lane
count go straight to the pallas dispatch pipeline, the rest through
native triage. The decomposition itself stays engine-agnostic.

Soundness notes, matching the reference's semantics exactly:
- A crashed op that recorded no payload steps to Inconsistent in the
  model (knossos steps (dequeue, nil) to Inconsistent), so it can
  never linearize; since crashed entries are optional, it is
  semantically absent from every linearization and DROPS from the
  decomposition (each hook documents its own cases).
- An OK entry with an op the model doesn't know makes its own lane
  invalid, which is the whole history's verdict — same as the
  undecomposed search.
- Real-time order is preserved: a projection keeps the RELATIVE order
  of its call/ret positions, and precedence between two entries is a
  positional comparison, so re-ranking cannot create or destroy a
  happens-before edge within a lane. FIFO queues do NOT decompose
  (order couples values); they stay on the full search.
"""

from __future__ import annotations

import numpy as np

from ..history import Entries
from ..models import Model


def eligible(model) -> bool:
    """Does this model type declare a decomposition at all? (The
    per-history answer is split() returning non-None.)"""
    return type(model).components is not Model.components


def _subset(es: Entries, idx: list, rewrite=None) -> Entries:
    """Sub-Entries over `idx`, positions re-ranked order-preservingly;
    `rewrite` optionally maps each projected entry's (f, value) — the
    ORIGINAL invoke Ops are kept for counterexample reporting."""
    sel = np.asarray(idx, np.int64)
    pos = np.concatenate([es.call_pos[sel], es.ret_pos[sel]])
    order = np.argsort(pos, kind="stable")
    rank = np.empty(len(pos), np.int64)
    rank[order] = np.arange(len(pos))
    m = len(idx)
    f = [es.f[i] for i in idx]
    value_in = [es.value_in[i] for i in idx]
    value_out = [es.value_out[i] for i in idx]
    if rewrite is not None:
        f_in = [rewrite(fi, vi) for fi, vi in zip(f, value_in)]
        f_out = [rewrite(fi, vo) for fi, vo in zip(f, value_out)]
        f = [t[0] for t in f_out]
        value_in = [t[1] for t in f_in]
        value_out = [t[1] for t in f_out]
    return Entries(
        f=f,
        value_in=value_in,
        value_out=value_out,
        crashed=es.crashed[sel],
        call_pos=rank[:m],
        ret_pos=rank[m:],
        invokes=[es.invokes[i] for i in idx],
    )


def split(model, es: Entries) -> list | None:
    """[(sub_model, sub_Entries)] per component, or None when this
    history doesn't decompose (no hook, coupling ops, unhashable
    payloads — the hook decides; the caller falls back to the full
    search)."""
    comps = model.components(es)
    if comps is None:
        return None
    return [(m, _subset(es, idx, rewrite)) for m, idx, rewrite in comps]


def group_lanes(comp_lanes) -> dict:
    """{sub_model: [indices]} over a flat list of (sub_model, Entries)
    lanes. The batch engines take ONE model per call, so every consumer
    of flattened decompositions — Linearizable._component_results for
    one check, the resident daemon's cross-run packer for many — buckets
    lanes per distinct sub-model before dispatch. Queue components
    share one UnorderedQueue; a multi-register split yields one
    Register per distinct initial value (usually just one). Insertion
    order is preserved so dispatch order is deterministic."""
    groups: dict = {}
    for i, (m, _es) in enumerate(comp_lanes):
        groups.setdefault(m, []).append(i)
    return groups
