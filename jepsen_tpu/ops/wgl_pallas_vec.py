"""The WGL search as ONE Pallas (Mosaic) kernel per 128-lane block,
with the lanes VECTORIZED across the TPU's lane dimension.

ops/wgl_tpu.py runs the DFS as a lax.while_loop of XLA ops: every
gather/scatter in the body is its own kernel launch per iteration
(~tens of us on this backend), so whole-batch throughput tops out
around a few hundred thousand steps/s however many lanes are vmapped.
ops/wgl_pallas.py moved the loop inside one Mosaic kernel but ran one
lane per sequential grid program, leaving the scalar unit
pointer-chasing (~86 us/step). This module keeps the whole search
inside one kernel AND runs 128 lanes per program in lockstep on the
vector unit:

- every per-lane scalar (node, state, depth, ...) is a (1, 128) row;
- every table (per-entry facts, node maps, the nxt/prv linked list,
  the undo stack) is an (R, 128) VMEM block, one column per lane;
- every data-dependent read is a ONE-HOT masked reduction over the
  sublane axis and every write a predicated full-array select — there
  is no dynamic indexing at all, which sidesteps Mosaic's
  no-dynamic-lane-indexing and scalar-store constraints entirely and
  keeps every op on the VPU;
- the memo cache is exact full-key compare against ALL slots (insert
  slot from a carried Zobrist fold, computed inline — no table).
  Pruning differs from the host's unbounded memo — step counts may
  differ, and DEEP refutation searches re-explore what native's
  unbounded memo prunes (~6-7x the steps on exhaustive 256-op deep
  batches; bounded VMEM cannot replicate an unbounded memo, and the
  O(slots) lookup makes bigger caches a net loss — see the insert
  comment for the measured sweep) — but any exact-compare cache is
  sound, so VERDICTS are bit-identical to the host search (asserted
  by the parity tests).
- INVALID lanes carry their counterexample out of the kernel (deepest
  prefix + stuck entry, wgl_search.cpp:329-341 semantics): the host
  formats it instead of re-searching.
- the tunnel's measured bandwidth is only ~4MB/s (raw) to ~9MB/s
  (compressible), with a fixed dispatch+fetch round trip (~110ms), so
  BYTES are the first-order end-to-end term. Everything crosses as ONE
  bit-packed int32 buffer each way: inputs are just the per-entry facts
  (f/crashed/call/ret in one row, both values 16-bit-packed into a
  second when they fit), the node->entry map and the initial linked
  list are DERIVED IN-KERNEL from those rows, and the result fetch is
  a 5-row verdict block — the n_pad-row counterexample stack stays on
  device (int16) and is fetched only when a lane actually refuted.

Blocks of 128 lanes run as sequential grid programs; within a block,
lanes that finish idle (gated) until the block's while loop drains.
A capped first pass resolves easy lanes cheaply and survivors repack
densely (two-pass scheduling) so one deep lane can't hold 32 blocks
at the full budget.

Scope: scalar kernel models (cas-register / register / mutex — one
int32 state, state_in_key), the unordered queue (count-vector state
laid out as extra sublane rows per lane column; memo key is the
bitset alone, backtracking is the exact inverse step), AND the fifo
queue (ring rows per lane column with absolute cursors; dequeue
zeroes its slot so the raw ring is canonical and rides the memo key
directly — no per-lane roll needed), for histories up to MAX_PAD
entries. Fifo lanes wider than FIFO_MAX_RING enqueues and larger
pads route to ops/wgl_tpu.py.

On non-TPU backends the kernel runs in pallas interpret mode (the CPU
test suite uses this for parity); on TPU it compiles via Mosaic.
"""

from __future__ import annotations

import logging
import threading

import numpy as np

import jax
import jax.numpy as jnp

from ..history import Entries, entries as make_entries
from ..models import jit as mjit
from .wgl_host import WGLResult
from .wgl_tpu import (RUNNING, VALID, INVALID, UNKNOWN,
                      DEFAULT_MAX_STEPS, _next_pow2, _pad_size)

log = logging.getLogger("jepsen_tpu.ops.wgl_pallas_vec")

LANES = 128                  # lanes per grid program (one vreg row)
CACHE_SLOTS = 128            # exact-key cache rows (compared in full)
MAX_PAD = 1024               # bitset words stay a small sublane block
FIFO_MAX_RING = 64           # fifo ring rows ride the memo key, so the
#                              cache footprint scales with ring size —
#                              wider-queue lanes route to the XLA path
CACHE_VMEM_BUDGET = 2 << 20  # bytes of VMEM the memo cache may claim
#                              (fifo keys are wide; slots shrink to fit)
PASS1_CAP = 512              # first-pass step budget (two-pass sched)
CHUNK_BLOCKS = 64            # blocks per pipelined launch chunk: wider
#                              single buffers pack superlinearly slower
#                              (scattered column writes thrash cache)
#                              and serialize pack behind the kernel;
#                              64-block chunks overlap the two (r5:
#                              16k deep lanes 2.0s -> 0.8s end-to-end)
NIL16 = 32767                # NIL32's image in the 16-bit value packing


def _m_pad(n_pad: int) -> int:
    """Node-array rows (2*n_pad+1) padded to the sublane tile."""
    return ((2 * n_pad + 1 + 7) // 8) * 8


def _nw(n_pad: int) -> int:
    return max(1, (n_pad + 31) // 32)


def _nw_pad(n_pad: int) -> int:
    return ((_nw(n_pad) + 7) // 8) * 8


def eligible(jm, n_pad: int) -> bool:
    """Scalar one-word models, plus both queue families (vector state
    as extra sublane rows per lane column; backtracking is an exact
    inverse step, so no state snapshot stack). The unordered queue's
    memo key is the bitset alone; the fifo queue's appends its ring
    rows — instead of the per-lane dynamic roll a canonicalized ring
    would need (no cheap lane-vectorized form), dequeue ZEROES its
    slot and cursors are bitset-determined, so the raw ring rows ARE
    canonical. Fifo lanes additionally need a bounded ring
    (FIFO_MAX_RING) — checked per batch by `batch_eligible` /
    analysis_batch, since it depends on the lanes' enqueue counts."""
    if n_pad > MAX_PAD:
        return False
    if isinstance(jm, mjit.JitModel) and jm.state_in_key:
        return True
    return getattr(jm, "name", "") in ("unordered-queue", "fifo-queue")


def batch_eligible(jm, entries_list) -> bool:
    """Full routing probe for a concrete batch: model/pad eligibility
    plus per-lane payload encodability plus the fifo ring bound."""
    if not entries_list:
        return False
    n_pad = _pad_size(max(len(es) for es in entries_list))
    if not eligible(jm, n_pad):
        return False
    if not all(jm.lane_eligible(es) for es in entries_list):
        return False
    if getattr(jm, "name", "") == "fifo-queue":
        return _state_pad(jm, entries_list) - 8 <= FIFO_MAX_RING
    return True


def _state_pad(jm, entries_list) -> int:
    """Padded state rows for a batch: 1 for scalar models; the max
    lane width padded to a power of two (>=8, the sublane tile) for
    the unordered queue; ring capacity (pow2-bucketed max enqueue
    count) + 8 cursor rows for the fifo queue — bucketed so re-batches
    reuse kernels."""
    if isinstance(jm, mjit.JitModel):
        return 1
    w = max((jm.lane_width(es) for es in entries_list), default=1)
    if getattr(jm, "name", "") == "fifo-queue":
        # lane_width counts n_enq + 2 cursor slots; the kernel keeps
        # cursors in their own 8-row block past the ring
        return max(8, _next_pow2(max(1, w - 2))) + 8
    return max(8, _next_pow2(w))


def _make_kernel(jm, n_pad: int, n_state: int,
                 cache_slots: int = CACHE_SLOTS):
    from jax.experimental import pallas as pl  # noqa: F401

    m_pad = _m_pad(n_pad)
    nw = _nw(n_pad)
    nw_pad = _nw_pad(n_pad)
    # plain Python ints — jnp values created outside the kernel would
    # be captured tracers, which pallas rejects
    scalar = isinstance(jm, mjit.JitModel)
    fifo = getattr(jm, "name", "") == "fifo-queue"
    uq = not scalar and not fifo             # unordered queue family
    init_state_c = int(jm.init_state) if scalar else 0
    # fifo ring capacity: state rows are [0, S) ring slots (0 = empty,
    # value id + 1 otherwise), row S the head cursor, row S+1 the tail
    # cursor (absolute counts — S >= the lane's total enqueues, sized
    # by _state_pad, so cursors never wrap and overflow is impossible)
    S = n_state - 8 if fifo else 0
    # memo keys: the unordered queue's multiset is a function of WHICH
    # ops linearized, so its key is the bitset alone; scalar keys
    # append the one state word; the fifo queue's ORDER depends on the
    # path, so its key appends the ring rows — and because dequeue
    # ZEROES its slot (with the inverse step restoring it), the raw
    # ring is already canonical: for a fixed bitset the k-th linearized
    # enqueue writes slot k and head/tail are bitset-determined, so
    # equal (bitset, ring) <=> equal logical queue. Head/tail rows stay
    # OUT of the key (derivable), stale slots never exist.
    key_words = (nw + S) if fifo else (nw if uq else nw + 1)
    cache_mask_c = cache_slots - 1

    def kernel(f_ref, v1_ref, v2_ref, crashed_ref, call_ref, ret_ref,
               nn_ref, ncomp_ref, msteps_ref,
               verdict_ref, steps_ref, depth_ref,
               bestd_ref, stuck_ref, beststack_ref,
               nxt, prv, stack_e, stack_s, cache, cache_used):
        i32 = jnp.int32
        m_iota = jax.lax.broadcasted_iota(i32, (m_pad, LANES), 0)
        n_iota = jax.lax.broadcasted_iota(i32, (n_pad, LANES), 0)
        w_iota = jax.lax.broadcasted_iota(i32, (nw_pad, LANES), 0)
        c_iota = jax.lax.broadcasted_iota(i32, (cache_slots, LANES), 0)

        # --- per-program init (scratch persists across programs; a
        # stale cache entry from another block would wrongly match).
        # The initial linked list (node i -> i+1 over the 2n live
        # nodes) is derived here from the lane length: the launcher's
        # prologue used to materialize it as two (m_pad, width) arrays
        # fed through BlockSpecs — never tunnel traffic, but a VMEM
        # copy per program that two selects replace. ---
        two_n = 2 * nn_ref[...]                          # [1, L]
        nxt[...] = jnp.where(m_iota < two_n, m_iota + 1, 0)
        prv[...] = jnp.where((m_iota >= 1) & (m_iota <= two_n),
                             m_iota - 1, 0)
        cache[...] = jnp.zeros((cache_slots, key_words * LANES), i32)
        cache_used[...] = jnp.zeros((cache_slots, LANES), i32)
        beststack_ref[...] = jnp.zeros((n_pad, LANES), i32)

        n_completed = ncomp_ref[...]                     # [1, L]
        # step budget is a runtime INPUT, not a compile-time constant:
        # one compiled kernel serves every cap (the two-pass scheduler
        # below re-runs survivors with a bigger budget)
        max_steps = msteps_ref[...]                      # [1, L]

        def onehot(rows, idx):
            """The [rows, L] one-hot mask for a per-lane index. Built
            ONCE per distinct index and shared by every read of that
            index — mask construction was ~half the read cost."""
            iota = {m_pad: m_iota, n_pad: n_iota}[rows]
            return iota == idx                           # [rows, L]

        def pick(mask, ref):
            """ref[idx] per lane as a masked reduction over a shared
            one-hot mask. Out-of-range idx (e.g. depth-1 at depth 0)
            yields zeros; every consumer of such a read is gated."""
            return jnp.sum(jnp.where(mask, ref[...], 0),
                           axis=0, keepdims=True)        # [1, L]

        def zmix(x):
            """splitmix-style diffusion of an entry id -> its Zobrist
            constant, computed inline on [1, L] rows — same retention
            quality as the old per-entry random table without the
            (n_pad, L) table reads per step."""
            x = (x + i32(-1640531527)) * i32(-1640531535)
            x = (x ^ (x >> 15)) * i32(-2048144789)
            return x ^ (x >> 13)

        if uq or fifo:
            s_iota = jax.lax.broadcasted_iota(i32, (n_state, LANES), 0)

        init = (
            jnp.where(two_n > 0, i32(1), i32(0)),        # node
            # scalar models: one state word; unordered queue: count
            # vector over the lane's value slots, one sublane row each;
            # fifo queue: ring rows + head/tail cursor rows, all zero
            (jnp.full((1, LANES), init_state_c, i32) if scalar
             else jnp.zeros((n_state, LANES), i32)),
            jnp.zeros((nw_pad, LANES), i32),             # lin bitset
            jnp.zeros((1, LANES), i32),                  # h: zobrist fold
            jnp.zeros((1, LANES), i32),                  # depth
            jnp.zeros((1, LANES), i32),                  # completed
            jnp.zeros((1, LANES), i32),                  # steps
            jnp.where(n_completed == 0, i32(VALID), i32(RUNNING)),
            jnp.full((1, LANES), -1, i32),               # best depth
            jnp.full((1, LANES), -1, i32),               # stuck entry
        )

        def cond(st):
            return jnp.any((st[7] == RUNNING) & (st[6] < max_steps))

        def body(st):
            (node, state, lin, h_lin, depth, completed, steps, verdict,
             bestd, stuck) = st
            active = (verdict == RUNNING) & (steps < max_steps)
            zero = jnp.zeros((1, LANES), i32)

            # node -> entry WITHOUT a materialized inverse map: the
            # entry at `node` is the unique e with call[e] == node or
            # ret[e] == node (encode guarantees positions are a
            # permutation — _pack asserts it), found by a masked
            # reduction over the (n_pad, L) call/ret rows — CHEAPER
            # than the old (m_pad, L) map pick, and the map no longer
            # crosses the tunnel at all. node == 0 (head sentinel) and
            # padded entries (call/ret aimed at the unreachable trash
            # row m_pad-1) match nothing -> e = 0, gated by is_call.
            mask_node = onehot(m_pad, node)
            mcall = call_ref[...] == node                # [n_pad, L]
            e = jnp.sum(
                jnp.where(mcall | (ret_ref[...] == node), n_iota, 0),
                axis=0, keepdims=True)                   # [1, L]
            is_call = (node != 0) & (jnp.max(
                mcall.astype(i32), axis=0, keepdims=True) != 0)

            mask_d = onehot(n_pad, depth - 1)
            e2 = pick(mask_d, stack_e)

            mask_e = onehot(n_pad, e)
            f_e = pick(mask_e, f_ref)
            v1_e = pick(mask_e, v1_ref)
            v2_e = pick(mask_e, v2_ref)
            crashed_e = pick(mask_e, crashed_ref)
            cn = pick(mask_e, call_ref)
            rn = pick(mask_e, ret_ref)
            mask_e2 = onehot(n_pad, e2)
            crashed_e2 = pick(mask_e2, crashed_ref)
            cn2 = pick(mask_e2, call_ref)
            rn2 = pick(mask_e2, ret_ref)

            if uq:
                # unordered queue inline (QueueJitModel.vec_step
                # semantics without dynamic indexing): v1 is the
                # lane's value slot; enqueue always ok, dequeue ok iff
                # the slot count is positive. NIL32/-1 f-codes make
                # mask_slot all-false and ok False.
                is_enq = f_e == 0
                is_deq = f_e == 1
                mask_slot = s_iota == v1_e               # [S, L]
                cnt = jnp.sum(jnp.where(mask_slot, state, 0),
                              axis=0, keepdims=True)
                ok = is_enq | (is_deq & (cnt > 0))
                new_state = state + jnp.where(
                    mask_slot, jnp.where(is_enq, 1, -1), 0)
            elif fifo:
                # fifo queue inline (FifoQueueJitModel semantics as a
                # ring with absolute cursors): enqueue writes value+1
                # at slot `tail`; dequeue is ok iff the queue is
                # nonempty AND the head slot holds its value, then
                # ZEROES the slot (keeping the ring canonical for the
                # memo key) and advances head. NIL32/-1 f-codes make
                # both branches false.
                is_enq = f_e == 0
                is_deq = f_e == 1
                head = state[S:S + 1, :]                 # [1, L]
                tail = state[S + 1:S + 2, :]
                mask_head = s_iota == head               # [n_state, L]
                mask_tail = s_iota == tail
                front = jnp.sum(jnp.where(mask_head, state, 0),
                                axis=0, keepdims=True)
                enq_ok = is_enq & (tail < S)
                deq_ok = is_deq & (head < tail) & (front == v1_e + 1)
                ok = enq_ok | deq_ok
                new_state = jnp.where(
                    mask_tail & enq_ok, v1_e + 1,
                    jnp.where(mask_head & deq_ok, 0,
                              jnp.where(s_iota == S, head + deq_ok,
                                        jnp.where(s_iota == S + 1,
                                                  tail + enq_ok,
                                                  state)))).astype(i32)
            else:
                new_state, ok = jm.step(state, f_e, v1_e, v2_e)
                new_state = new_state.astype(i32)
            can_lin = active & is_call & ok

            word = e // 32
            bit = i32(1) << (e % 32)
            new_lin = lin | jnp.where(w_iota == word, bit, i32(0))

            # ---- cache: exact full-key compare against ALL slots.
            # The insert slot comes from the carried Zobrist fold (each
            # lift/pop XORs the entry's zmix constant): the lookup
            # never consults the insert position, so the slot choice is
            # purely a retention policy — but retention quality needs
            # real diffusion (measured: FIFO cursors and direct
            # key-folds both leave ~40-60% more step-capped unknowns
            # than the Zobrist fold at equal slots) ----
            new_h = h_lin ^ zmix(e)
            if scalar:
                hm = (new_h ^ new_state) * i32(16777619)
            elif fifo:
                # fold the stepped value into the slot choice: same
                # bitset + different ring orders should prefer
                # different slots (retention only — lookup is exact)
                hm = (new_h ^ zmix(v1_e)) * i32(16777619)
            else:
                hm = new_h * i32(16777619)
            hm = hm ^ (hm >> 15)
            slot = hm & i32(cache_mask_c)                # [1, L]
            eq = cache_used[...] != 0                    # [C, L]
            for w in range(nw):
                eq = eq & (cache[:, w * LANES:(w + 1) * LANES]
                           == new_lin[w:w + 1, :])
            if scalar:  # unordered-queue keys are the bitset alone
                eq = eq & (cache[:, nw * LANES:(nw + 1) * LANES]
                           == new_state)
            elif fifo:  # ring rows complete the key (order matters)
                for j in range(S):
                    eq = eq & (
                        cache[:, (nw + j) * LANES:(nw + j + 1) * LANES]
                        == new_state[j:j + 1, :])
            found = jnp.max(eq.astype(i32), axis=0, keepdims=True) != 0

            do_lift = can_lin & ~found
            lift_completed = completed + jnp.where(crashed_e != 0, 0, 1)

            can_pop = depth > 0
            if uq:
                # exact inverse step (has_unstep): un-apply e2 instead
                # of restoring a snapshot — no stack_s at all
                v1_e2 = pick(mask_e2, v1_ref)
                f_e2 = pick(mask_e2, f_ref)
                mask_slot2 = s_iota == v1_e2
                pop_state = state + jnp.where(
                    mask_slot2, jnp.where(f_e2 == 0, -1, 1), 0)
            elif fifo:
                # exact inverse step: un-enqueue zeroes slot tail-1 and
                # retreats tail; un-dequeue restores the entry's value
                # at head-1 (the zeroed slot) and retreats head
                v1_e2 = pick(mask_e2, v1_ref)
                f_e2 = pick(mask_e2, f_ref)
                undo_enq = f_e2 == 0
                undo_deq = f_e2 == 1
                head = state[S:S + 1, :]
                tail = state[S + 1:S + 2, :]
                pop_state = jnp.where(
                    (s_iota == tail - 1) & undo_enq, 0,
                    jnp.where((s_iota == head - 1) & undo_deq,
                              v1_e2 + 1,
                              jnp.where(s_iota == S, head - undo_deq,
                                        jnp.where(s_iota == S + 1,
                                                  tail - undo_enq,
                                                  state)))).astype(i32)
            else:
                pop_state = pick(mask_d, stack_s)
            word2 = e2 // 32
            bit2 = i32(1) << (e2 % 32)
            pop_lin = lin & ~jnp.where(w_iota == word2, bit2, i32(0))
            pop_completed = completed - jnp.where(crashed_e2 != 0, 0, 1)

            advance = active & is_call & ~do_lift
            backtrack = active & ~is_call
            do_back = backtrack & can_pop

            # ---- counterexample tracking (native wgl_search.cpp
            # :329-333 semantics): at every return event, if the
            # current prefix is the deepest seen, snapshot it and the
            # entry we're stuck at — so INVALID lanes carry their
            # counterexample out of the kernel and the host never
            # re-searches them ----
            upd = backtrack & (depth > bestd)
            bestd_out = jnp.where(upd, depth, bestd)
            stuck_out = jnp.where(
                upd, jnp.where(node == 0, i32(-1), e), stuck)
            beststack_ref[...] = jnp.where(
                upd, stack_e[...], beststack_ref[...])

            # ---- linked list: raw reads, then the same scalar-fixup
            # algebra as the XLA dense form (round A never
            # materializes) ----
            mask_cn = onehot(m_pad, cn)
            mask_rn = onehot(m_pad, rn)
            mask_rn2 = onehot(m_pad, rn2)
            mask_cn2 = onehot(m_pad, cn2)
            nxt_cn = pick(mask_cn, nxt)
            prv_cn = pick(mask_cn, prv)
            nxt_rn = pick(mask_rn, nxt)
            prv_rn = pick(mask_rn, prv)
            nxt_rn2 = pick(mask_rn2, nxt)
            prv_rn2 = pick(mask_rn2, prv)
            nxt_cn2 = pick(mask_cn2, nxt)
            prv_cn2 = pick(mask_cn2, prv)
            nxt_0 = nxt[0:1, :]
            prv_0 = prv[0:1, :]
            nxt_node = pick(mask_node, nxt)

            posA_n = jnp.where(do_lift, prv_cn,
                               jnp.where(do_back, prv_rn2, zero))
            valA_n = jnp.where(do_lift, nxt_cn,
                               jnp.where(do_back, rn2, nxt_0))
            posA_p = jnp.where(do_lift, nxt_cn,
                               jnp.where(do_back, nxt_rn2, zero))
            valA_p = jnp.where(do_lift, prv_cn,
                               jnp.where(do_back, rn2, prv_0))

            rd_n1 = lambda i, raw: jnp.where(i == posA_n, valA_n, raw)  # noqa: E731,E501
            rd_p1 = lambda i, raw: jnp.where(i == posA_p, valA_p, raw)  # noqa: E731,E501
            posB_n = jnp.where(do_lift, rd_p1(rn, prv_rn),
                               jnp.where(do_back, rd_p1(cn2, prv_cn2),
                                         zero))
            valB_n = jnp.where(do_lift, rd_n1(rn, nxt_rn),
                               jnp.where(do_back, cn2, rd_n1(zero, nxt_0)))
            posB_p = jnp.where(do_lift, rd_n1(rn, nxt_rn),
                               jnp.where(do_back, rd_n1(cn2, nxt_cn2),
                                         zero))
            valB_p = jnp.where(do_lift, rd_p1(rn, prv_rn),
                               jnp.where(do_back, cn2, rd_p1(zero, prv_0)))
            rd_nout = lambda i, raw: jnp.where(  # noqa: E731
                i == posB_n, valB_n, rd_n1(i, raw))

            nxt[...] = jnp.where(
                m_iota == posB_n, valB_n,
                jnp.where(m_iota == posA_n, valA_n, nxt[...]))
            prv[...] = jnp.where(
                m_iota == posB_p, valB_p,
                jnp.where(m_iota == posA_p, valA_p, prv[...]))

            # ---- cache insert (zobrist-hashed slot) + stack push.
            # Always-overwrite is the MEASURED best retention at this
            # design point (512 deep 256-op lanes, 200k cap):
            # depth-preferential retention (protect shallow entries —
            # they guard bigger subtrees) LOST ~6% steps because
            # abandoned branches' shallow entries squat in slots, and
            # growing capacity loses outright: the no-dynamic-indexing
            # lookup is O(slots), so C=1024 cut steps 17.8M -> 6.9M
            # but wall ROSE 593ms -> 1521ms (r4); RE-MEASURED after the
            # r5 chunked-launch refactor (same shape, v5e): C=128
            # 730-750ms/16-17M steps, C=256 800-815ms/12-13M, C=512
            # 910-920ms/9-9.5M vs native 326ms/2.7M — capacity still
            # buys steps at a worse wall. SURVEY §7.1's HBM-resident
            # open-addressed table does not map to Mosaic: a per-lane
            # random slot needs a per-lane dynamic gather/scatter,
            # which the no-dynamic-lane-indexing model cannot express,
            # and per-step HBM round trips would cost ~100x the ~38ns
            # resident step. The bounded-vs-unbounded memo gap vs
            # native (~6x steps on exhaustive deep batches; ~1.4x at
            # the step-capped deep-4096 bench shape, `steps_ratio` in
            # the artifact) is structural to lane-vectorized VMEM
            # search, not a tuning miss. ----
            sl = (c_iota == slot) & do_lift              # [C, L]
            for w in range(nw):
                cache[:, w * LANES:(w + 1) * LANES] = jnp.where(
                    sl, new_lin[w:w + 1, :],
                    cache[:, w * LANES:(w + 1) * LANES])
            if scalar:
                cache[:, nw * LANES:(nw + 1) * LANES] = jnp.where(
                    sl, new_state,
                    cache[:, nw * LANES:(nw + 1) * LANES])
            elif fifo:
                for j in range(S):
                    cache[:, (nw + j) * LANES:(nw + j + 1) * LANES] = \
                        jnp.where(
                            sl, new_state[j:j + 1, :],
                            cache[:, (nw + j) * LANES:(nw + j + 1)
                                  * LANES])
            cache_used[...] = jnp.where(sl, i32(1), cache_used[...])

            push = (n_iota == depth) & do_lift
            stack_e[...] = jnp.where(push, e, stack_e[...])
            if scalar:  # the queues backtrack by inverse step instead
                stack_s[...] = jnp.where(push, state, stack_s[...])

            # ---- next scalars ----
            node_out = jnp.where(
                do_lift, rd_nout(zero, nxt_0),
                jnp.where(advance, rd_nout(node, nxt_node),
                          jnp.where(do_back, rd_nout(cn2, nxt_cn2), node)))
            state_out = jnp.where(
                do_lift, new_state,
                jnp.where(do_back, pop_state, state))
            lin_out = jnp.where(
                do_lift, new_lin, jnp.where(do_back, pop_lin, lin))
            h_out = jnp.where(
                do_lift, new_h,
                jnp.where(do_back, h_lin ^ zmix(e2), h_lin))
            depth_out = jnp.where(
                do_lift, depth + 1, jnp.where(do_back, depth - 1, depth))
            completed_out = jnp.where(
                do_lift, lift_completed,
                jnp.where(do_back, pop_completed, completed))
            verdict_out = jnp.where(
                do_lift & (lift_completed == n_completed), i32(VALID),
                jnp.where(backtrack & ~can_pop, i32(INVALID), verdict))

            return (node_out, state_out, lin_out, h_out, depth_out,
                    completed_out, steps + active.astype(i32), verdict_out,
                    bestd_out, stuck_out)

        out = jax.lax.while_loop(cond, body, init)
        final = jnp.where(out[7] == RUNNING, jnp.int32(UNKNOWN), out[7])
        verdict_ref[...] = final
        steps_ref[...] = out[6]
        depth_ref[...] = out[4]
        bestd_ref[...] = out[8]
        stuck_ref[...] = out[9]

    return kernel, m_pad


def _encode_flats(entries_list, jm, n_pad: int) -> dict:
    """Encode a whole batch ONCE into flat per-entry fact arrays.

    Splitting encode from layout lets the chunked launch pipeline and
    the two-pass survivor relaunch re-LAYOUT arbitrary lane subsets by
    pure numpy gathers instead of re-running the per-entry Python
    encoders (r5 profile: encoding was ~3 s of a 12 s 16k-lane check,
    and re-encoding survivors doubled it)."""
    m_pad = _m_pad(n_pad)
    n_lanes = len(entries_list)
    ns = np.array([len(es) for es in entries_list], np.int64)
    offs = np.concatenate([[0], np.cumsum(ns)])
    total = int(ns.sum())
    f_flat = v1_flat = v2_flat = None
    if isinstance(jm, mjit.JitModel):
        # scalar models: one interned batch pass (encode_batch) —
        # per-entry Python in the per-lane loop is the pack bottleneck
        try:
            f_flat, v1_flat, v2_flat = jm.encode_batch(
                entries_list, total)
        except TypeError:  # unhashable payload somewhere: lane-by-lane
            f_flat = None
    if f_flat is None:
        f_flat = np.empty(total, np.int32)
        v1_flat = np.empty(total, np.int32)
        v2_flat = np.empty(total, np.int32)
        pos = 0
        for es in entries_list:
            n = len(es)
            if n:
                (f_flat[pos:pos + n], v1_flat[pos:pos + n],
                 v2_flat[pos:pos + n]) = jm.encode_lane(es)
                pos += n
    nonempty = [es for es in entries_list if len(es)]
    cr_flat = (np.concatenate([es.crashed for es in nonempty])
               if nonempty else np.zeros(0, bool))
    # +1: node ids are positions shifted past the head sentinel 0
    # (history.entries guarantees call/ret positions are a permutation
    # of 0..2n-1; wgl_tpu.encode_entries asserts it)
    cp_flat = (np.concatenate([np.asarray(es.call_pos) for es in nonempty])
               if nonempty else np.zeros(0, np.int64)).astype(np.int32) + 1
    rp_flat = (np.concatenate([np.asarray(es.ret_pos) for es in nonempty])
               if nonempty else np.zeros(0, np.int64)).astype(np.int32) + 1

    lane_idx = np.repeat(np.arange(n_lanes), ns)

    # Duplicate call/ret positions would silently corrupt the kernel's
    # node->entry sum-reduction (two matching entries would ADD).
    # history.entries guarantees a per-lane permutation; guard it here
    # since this fast path no longer goes through encode_entries'
    # assert.
    occ = np.bincount(
        np.concatenate([lane_idx, lane_idx]) * np.int64(m_pad)
        + np.concatenate([cp_flat, rp_flat]).astype(np.int64))
    assert occ.max(initial=0) <= 1, \
        "duplicate call/ret node positions in Entries"

    # 16-bit value packing: NIL32 remaps to NIL16; anything else must
    # fit int16 below the sentinel. Histories with wider payloads fall
    # back to two full int32 value rows (same kernel, fatter transfer).
    # The decision is made ONCE over the whole batch, so every chunk
    # and the two-pass survivor relaunch share one layout (a flipped
    # row count would retrace the launcher's jit — a ~1s Mosaic
    # compile — mid-check).
    nil1 = v1_flat == mjit.NIL32
    nil2 = v2_flat == mjit.NIL32
    v16_fit = bool(
        np.all(nil1 | ((v1_flat >= -32768) & (v1_flat < NIL16)))
        and np.all(nil2 | ((v2_flat >= -32768) & (v2_flat < NIL16))))

    # Encode ONCE all the way to the packed transfer words: the meta
    # bit-pack and the 16-bit value pack are functions of the entry
    # alone, so computing them here turns every subsequent _layout (one
    # per pipelined chunk, plus the two-pass survivor relaunch) into a
    # single gather+scatter per row block — no per-chunk repacking and
    # none of the four (n_pad, width) intermediates the old layout
    # materialized per call.
    cr32 = cr_flat.astype(np.int32)
    meta_flat = (f_flat + 1) | (cr32 << 3) | (cp_flat << 4) \
        | (rp_flat << 16)
    if v16_fit:
        lo = np.where(nil1, NIL16, v1_flat) & 0xFFFF
        hi = np.where(nil2, NIL16, v2_flat) & 0xFFFF
        v16_flat = lo | (hi << 16)
    else:
        v16_flat = None

    return {
        "f": f_flat, "v1": v1_flat, "v2": v2_flat,
        "cr": cr32, "cp": cp_flat, "rp": rp_flat,
        "meta": meta_flat, "v16p": v16_flat,
        "ns": ns, "offs": offs, "v16_fit": v16_fit,
        "ncomp": np.array([es.n_completed for es in entries_list],
                          np.int32),
    }


def _layout(flats: dict, idx, n_pad: int,
            v16: bool | None = None,
            alloc=None) -> tuple[np.ndarray, int]:
    """Lay the lanes `idx` (None = all) out column-wise into the FEWEST
    bit-packed int32 rows. Only genuine per-entry facts cross the
    host->device boundary; the node->entry map and the initial linked
    list are derived in-kernel from the call/ret rows, and both payload
    values pack into one 16-bit-halved row whenever they fit (NIL32 ->
    the NIL16 sentinel). The tunnel moves ~4MB/s (raw) to ~9MB/s
    (compressible), so every dropped row is milliseconds: this layout
    is 2n+1 rows vs r3's 3n+m+1 — ~2.6x fewer bytes at the deep-4096
    bench shape.

    The packed words come precomputed from _encode_flats, so this is
    ONE fill + one flat scatter per row block — no (n_pad, width)
    intermediates. `alloc(rows, width) -> int32 buffer` lets the
    launch pipeline supply a pooled arena buffer instead of a fresh
    allocation per chunk; every row is overwritten, so the buffer's
    prior contents never leak.

    Padding lanes have n_completed == 0, so they go VALID at init and
    idle through the block's loop. Padded ENTRIES aim their call/ret
    positions at the trash row m_pad-1: m_pad >= 2*n_pad+2 (the +1 is
    odd, the tile is 8), so the trash row is outside every reachable
    node id and the kernel's node->entry reduction never matches it.

    Row blocks, all int32:
      [0:n)   meta: (f+1) | crashed<<3 | cp<<4 | rp<<16
              (f+1 fits 3 bits, cp/rp fit 12 — m_pad <= 2*1024+8)
      [n:2n)  (v1_16 & 0xFFFF) | v2_16<<16   when every value fits
              int16 (NIL32 encodes as NIL16); otherwise two separate
              int32 rows [n:2n) v1, [2n:3n) v2 — the launcher picks
              the unpack by row count
      [-1]    n | n_completed<<16
    """
    m_pad = _m_pad(n_pad)
    ns_all, offs = flats["ns"], flats["offs"]
    if idx is None:
        ns = ns_all
        sel = slice(None)
    else:
        idx = np.asarray(idx, np.int64)
        ns = ns_all[idx]
        if len(idx) and np.all(np.diff(idx) == 1):
            # contiguous lane range (the chunked-launch case): a plain
            # slice instead of a fancy-index copy of the flat arrays
            sel = slice(int(offs[idx[0]]), int(offs[idx[-1] + 1]))
        else:
            total_sel = int(ns.sum())
            cum = np.cumsum(ns) - ns
            sel = (np.repeat(offs[idx] - cum, ns)
                   + np.arange(total_sel, dtype=np.int64))
    n_lanes = len(ns)
    # block counts bucket to powers of two so re-batches (the two-pass
    # scheduler's survivor pass) reuse compiled kernels instead of
    # paying a fresh pallas trace per exact width
    n_blocks = (n_lanes + LANES - 1) // LANES
    n_blocks = 1 if n_blocks <= 1 else _next_pow2(n_blocks)
    width = n_blocks * LANES

    ncomp = flats["ncomp"] if idx is None else flats["ncomp"][idx]
    if v16 is None:
        v16 = flats["v16_fit"]

    meta_flat = flats["meta"][sel]
    total = len(meta_flat)
    lane_idx = np.repeat(np.arange(n_lanes), ns)
    row_idx = np.arange(total) - np.repeat(np.cumsum(ns) - ns, ns)

    rows = (2 if v16 else 3) * n_pad + 1
    buf = (np.empty((rows, width), np.int32) if alloc is None
           else alloc(rows, width))
    assert buf.shape == (rows, width) and buf.dtype == np.int32
    # padded entries AND padding lanes share one meta word: f = -1
    # encodes as 0, crashed 0, call/ret aimed at the trash row m_pad-1
    mb = buf[0:n_pad]
    mb.fill(((m_pad - 1) << 4) | ((m_pad - 1) << 16))
    mb[row_idx, lane_idx] = meta_flat
    if v16:
        vv = buf[n_pad:2 * n_pad]
        vv.fill(NIL16 | (NIL16 << 16))  # padding entries: both NIL
        v16p = flats["v16p"]
        if v16p is None:  # caller forced v16 on a batch packed wide
            v1_flat, v2_flat = flats["v1"][sel], flats["v2"][sel]
            lo = np.where(v1_flat == mjit.NIL32, NIL16, v1_flat) & 0xFFFF
            hi = np.where(v2_flat == mjit.NIL32, NIL16, v2_flat) & 0xFFFF
            vv[row_idx, lane_idx] = lo | (hi << 16)
        else:
            vv[row_idx, lane_idx] = v16p[sel]
    else:
        v1 = buf[n_pad:2 * n_pad]
        v2 = buf[2 * n_pad:3 * n_pad]
        v1.fill(mjit.NIL32)
        v2.fill(mjit.NIL32)
        v1[row_idx, lane_idx] = flats["v1"][sel]
        v2[row_idx, lane_idx] = flats["v2"][sel]

    last = buf[-1]
    last.fill(0)
    last[:n_lanes] = ns.astype(np.int32) | (ncomp << 16)
    return buf, n_blocks


def _pack(entries_list, jm, n_pad: int,
          v16: bool | None = None) -> tuple[np.ndarray, int]:
    """Encode + lay out a whole batch (see _encode_flats/_layout —
    split so chunked launches re-layout subsets without re-encoding)."""
    flats = _encode_flats(entries_list, jm, n_pad)
    return _layout(flats, None, n_pad, v16)


class _HostArena:
    """Persistent pack-buffer pool for the launch pipeline.

    _layout scatters each chunk into a buffer drawn from here instead
    of allocating (and page-faulting) rows*width*4 fresh bytes per
    chunk — ~4 MB per chunk at the deep-16384 shape, twice per check
    with the survivor pass, and again on every subsequent check of the
    same shape. `depth` slots rotate per (rows, width) shape, which is
    exactly the double-buffer discipline: chunk i+1 packs into one
    buffer while chunk i's transfer/kernel may still be reading the
    other, and take() re-issues a buffer only after the FENCE its last
    launch attached has resolved. The fence is the launch's device-side
    verdict handle: on backends where device_put aliases host memory
    (CPU jax zero-copies numpy arrays) output readiness implies the
    kernel is done reading the input, while on the tunnel backend the
    input bytes were already serialized at dispatch and the fence only
    throttles the pipeline to `depth` chunks in flight. If every slot
    of a shape is busy (a third concurrent taker), the caller gets a
    transient unpooled buffer rather than blocking."""

    def __init__(self, depth: int = 2):
        self.depth = depth
        self._slots: dict = {}
        self._lock = threading.Lock()

    def take(self, rows: int, width: int):
        """Return (buffer, slot); slot is None for transient buffers.
        Blocks until the slot's previous launch has consumed it."""
        key = (rows, width)
        with self._lock:
            slots = self._slots.setdefault(key, [])
            slot = next((s for s in slots if not s["busy"]), None)
            if slot is None:
                if len(slots) >= self.depth:
                    return np.empty((rows, width), np.int32), None
                slot = {"buf": np.empty((rows, width), np.int32),
                        "busy": False, "fence": None}
                slots.append(slot)
            slot["busy"] = True
            fence, slot["fence"] = slot["fence"], None
        if fence is not None:
            try:
                fence.block_until_ready()
            except Exception:  # stale/errored handle: buffer is safe
                pass
        return slot["buf"], slot

    def release(self, slot, fence) -> None:
        """Hand a pooled buffer back, fenced by its launch's output."""
        if slot is None:
            return
        with self._lock:
            slot["fence"] = fence
            slot["busy"] = False


_arena = _HostArena()


_kernel_cache: dict = {}


def _launcher(jm, n_pad: int, interpret: bool, n_blocks: int,
              n_state: int = 1, cache_slots: int = CACHE_SLOTS,
              mesh=None):
    """One jitted pallas_call per (model, shape, blocks, cache) —
    building the call is ~1 s of host tracing, dwarfing the sub-ms
    kernel, so it must happen once, not per invocation. The step
    budget is a runtime input, so every cap shares one compiled
    kernel.

    With a `mesh` (one "blocks" axis), the launch shard_maps over it:
    blocks are independent by construction, so each device runs
    n_blocks/mesh.size grid programs over its own column shard and the
    only cross-device traffic is the sharded result fetch — the same
    deal-the-lanes scaling story as wgl_tpu's mesh path
    (wgl_tpu.py:677-707), now for the flagship engine."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    key = (jm.name, n_pad, interpret, n_blocks, n_state, cache_slots,
           mesh)
    if key in _kernel_cache:
        return _kernel_cache[key]

    scalar = isinstance(jm, mjit.JitModel)
    fifo = getattr(jm, "name", "") == "fifo-queue"
    key_words = (_nw(n_pad) + (n_state - 8) if fifo
                 else _nw(n_pad) + 1 if scalar else _nw(n_pad))
    kernel, m_pad = _make_kernel(jm, n_pad, n_state, cache_slots)
    nw = _nw(n_pad)

    def spec(rows):
        return pl.BlockSpec((rows, LANES), lambda i: (0, i))

    in_specs = [
        spec(n_pad), spec(n_pad), spec(n_pad), spec(n_pad),
        spec(n_pad), spec(n_pad),
        spec(1), spec(1), spec(1),
    ]
    # under a mesh each device runs its share of the (independent)
    # blocks; the pallas grid and result width are per-shard
    n_dev = mesh.size if mesh is not None else 1
    assert n_blocks % n_dev == 0, (n_blocks, n_dev)
    blocks_local = n_blocks // n_dev
    width = blocks_local * LANES
    out_specs = [spec(1)] * 5 + [spec(n_pad)]
    out_shape = (
        [jax.ShapeDtypeStruct((1, width), jnp.int32)] * 5
        + [jax.ShapeDtypeStruct((n_pad, width), jnp.int32)]
    )
    call = pl.pallas_call(
        kernel,
        grid=(blocks_local,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((m_pad, LANES), jnp.int32),   # nxt
            pltpu.VMEM((m_pad, LANES), jnp.int32),   # prv
            pltpu.VMEM((n_pad, LANES), jnp.int32),   # stack_e
            # stack_s is untouched for the queues (inverse-step
            # backtracking); keep a token row so the arity is fixed
            pltpu.VMEM((n_pad if scalar else 8, LANES), jnp.int32),
            pltpu.VMEM((cache_slots, key_words * LANES), jnp.int32),
            pltpu.VMEM((cache_slots, LANES), jnp.int32),
        ],
        interpret=interpret,
    )

    def body(buf, msteps):
        # unpack the single bit-packed transfer buffer (layout in
        # _pack; the row count says whether values are 16-bit-packed)
        # — all fused into the dispatch
        i32 = jnp.int32
        meta = buf[0:n_pad]
        f32 = (meta & 7) - 1
        crashed = (meta >> 3) & 1
        cp = (meta >> 4) & 0xFFF
        rp = (meta >> 16) & 0xFFF
        if buf.shape[0] == 2 * n_pad + 1:  # 16-bit-packed values
            raw = buf[n_pad:2 * n_pad]
            lo = ((raw & 0xFFFF) ^ 0x8000) - 0x8000  # sign-extend
            hi = raw >> 16                           # arithmetic: done
            nil = i32(int(mjit.NIL32))
            v1 = jnp.where(lo == NIL16, nil, lo)
            v2 = jnp.where(hi == NIL16, nil, hi)
        else:
            v1 = buf[n_pad:2 * n_pad]
            v2 = buf[2 * n_pad:3 * n_pad]
        last = buf[-1:]
        nn = last & 0xFFFF
        ncomp = last >> 16
        verdict, steps, depth, bestd, stuck, beststack = call(
            f32, v1, v2, crashed, cp, rp, nn, ncomp, msteps,
        )
        # TWO result arrays, fetched separately: the 5-row verdict
        # block (0 verdict, 1 steps, 2 depth, 3 best depth, 4 stuck
        # entry) is all a VALID batch ever needs; the n_pad-row best
        # stack ships as int16 (entry ids < n_pad <= 1024) and is only
        # fetched when some lane refuted — at the tunnel's ~3-4MB/s
        # fetch rate it would otherwise dominate the result path.
        small = jnp.concatenate(
            [verdict, steps, depth, bestd, stuck], axis=0)
        return small, beststack.astype(jnp.int16)

    if mesh is None:
        # the packed buffer and step row arrive as fresh host arrays
        # and are consumed exactly once, so their device copies are
        # donated: the unpack reuses them in place instead of holding
        # transfer + unpacked copies live. Not under interpret — the
        # CPU backend can't donate (and zero-copies numpy inputs, so
        # donating would alias the host arena).
        run = jax.jit(body,
                      donate_argnums=() if interpret else (0, 1))
    else:
        from jax.sharding import PartitionSpec as P
        # jax.shard_map only exists on newer jax; the experimental
        # module spans every version this repo supports
        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:
            from jax.experimental.shard_map import shard_map

        # every input/output row block is columnwise-independent, so
        # sharding the width axis is exact; replication checking off —
        # pallas calls don't carry replication info (the kwarg was
        # renamed check_rep -> check_vma in jax 0.8)
        try:
            sharded = shard_map(
                body, mesh=mesh,
                in_specs=(P(None, "blocks"), P(None, "blocks")),
                out_specs=(P(None, "blocks"), P(None, "blocks")),
                check_vma=False)
        except TypeError:
            sharded = shard_map(
                body, mesh=mesh,
                in_specs=(P(None, "blocks"), P(None, "blocks")),
                out_specs=(P(None, "blocks"), P(None, "blocks")),
                check_rep=False)
        run = jax.jit(sharded)

    _kernel_cache[key] = run
    return run


def analysis_batch(model, entries_list, max_steps: int | None = None,
                   interpret: bool | None = None,
                   devices=None,
                   chunk_blocks: int | None = None) -> list:
    """Check a batch of independent histories, 128 lanes per kernel
    program. Raises on ineligible models/sizes — callers probe with
    `eligible` first (checker/linearizable routes here for scalar
    models; everything else uses ops/wgl_tpu).

    `devices`: >1 jax devices shard the batch's 128-lane blocks over a
    1-D "blocks" mesh via shard_map — each device searches its own
    share (blocks are independent), the production multi-chip path for
    the flagship engine. The driver's dryrun exercises it on a virtual
    CPU mesh (__graft_entry__.dryrun_multichip).

    `chunk_blocks` overrides CHUNK_BLOCKS (blocks per pipelined launch
    chunk) — production uses the default; tests shrink it to exercise
    the chunked path at CPU-sized batches."""
    jm = mjit.for_model(model)
    if jm is None:
        raise ValueError(f"no kernel model for {model!r}")
    entries_list = [es if isinstance(es, Entries) else make_entries(es)
                    for es in entries_list]
    if not entries_list:
        return []
    if max_steps is None:
        max_steps = DEFAULT_MAX_STEPS
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    mesh = None
    if devices is not None and len(devices) > 1:
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(devices), ("blocks",))
    n_pad = _pad_size(max(len(es) for es in entries_list))
    if not eligible(jm, n_pad):
        raise ValueError(
            f"pallas-vec path ineligible: model={jm.name} n_pad={n_pad}")
    for es in entries_list:
        if not jm.lane_eligible(es):
            raise ValueError("lane has no int32 encoding")

    n_state = _state_pad(jm, entries_list)
    cache_slots = CACHE_SLOTS
    if getattr(jm, "name", "") == "fifo-queue":
        ring = n_state - 8
        if ring > FIFO_MAX_RING:
            raise ValueError(
                f"fifo ring {ring} > {FIFO_MAX_RING}: memo keys would "
                "overflow the VMEM cache budget — use the XLA path")
        # ring rows ride every cache slot; shrink the slot count so the
        # cache stays within its VMEM budget
        key_bytes = (_nw(n_pad) + ring) * LANES * 4
        cache_slots = max(8, min(
            CACHE_SLOTS, _next_pow2(CACHE_VMEM_BUDGET // key_bytes + 1)
            // 2))
    flats = _encode_flats(entries_list, jm, n_pad)
    n = len(entries_list)
    cb = CHUNK_BLOCKS if chunk_blocks is None else max(1, int(chunk_blocks))

    def launch(idx, cap):
        """Launch the lanes `idx` (None = all) at step cap `cap`.

        The pipelined dispatch path. Batches wider than `cb` blocks
        split into chunks, each laid out into a pooled arena buffer
        and DISPATCHED before the first is fetched: jax dispatch is
        async, so chunk i's transfer+kernel overlaps chunk i+1's
        host-side layout (double-buffered — the arena re-issues a
        buffer only once its previous launch's fence resolves), and
        the layout itself is superlinear in buffer width
        (cache-thrashing scattered column writes — r5 measured a
        16k-lane pack at 1.5 s in one 128-block buffer vs ~0.5 s as
        two 64-block chunks, and end-to-end 2.0 s -> 0.8 s). The
        verdict gather is DEFERRED: every chunk's device->host copy
        is kicked off before any chunk is materialized, so fetches
        stream back-to-back instead of round-tripping per chunk.

        Returns (small, best): small is the fetched (5, n_sel) verdict
        block; best() lazily fetches the counterexample stacks."""
        step = cb * LANES
        if idx is None and (mesh is not None or n <= step):
            chunk_idx: list = [None]
        else:
            base = np.arange(n, dtype=np.int64) if idx is None \
                else np.asarray(idx, np.int64)
            if mesh is not None or len(base) <= step:
                # a mesh launch stays single-shot: the mesh itself is
                # the parallelism, and per-chunk launches would leave
                # devices idle between dispatches
                chunk_idx = [base]
            else:
                chunk_idx = [base[i:i + step]
                             for i in range(0, len(base), step)]
        handles = []
        for ch in chunk_idx:
            slot_box: list = []

            def alloc(rows, width, _box=slot_box):
                buf, slot = _arena.take(rows, width)
                _box.append(slot)
                return buf

            packed, n_blocks = _layout(flats, ch, n_pad, alloc=alloc)
            if mesh is not None and n_blocks % mesh.size:
                # pad with empty-lane columns (n = ncomp = 0: VALID at
                # init, idle) so every device gets whole blocks
                pad_to = -(-n_blocks // mesh.size) * mesh.size
                packed = np.pad(
                    packed, ((0, 0), (0, (pad_to - n_blocks) * LANES)))
                n_blocks = pad_to
            run = _launcher(jm, n_pad, interpret, n_blocks, n_state,
                            cache_slots, mesh)
            msteps = np.full((1, n_blocks * LANES), cap, np.int32)
            w = n if ch is None else len(ch)
            out = run(packed, msteps)
            # fence: the arena may re-issue this chunk's buffer only
            # once the launch that read it has produced its verdicts
            _arena.release(slot_box[0] if slot_box else None, out[0])
            handles.append((out, w))
        # deferred gather: start EVERY chunk's device->host verdict
        # copy before materializing any — fetches stream while later
        # chunks' kernels are still running
        if len(handles) > 1:
            for (small_dev, _bd), _w in handles:
                try:
                    small_dev.copy_to_host_async()
                except (AttributeError, NotImplementedError):
                    pass
        smalls, bests = [], []
        for (small_dev, best_dev), w in handles:
            # numpy fetch of the small block is the completion sync
            # (block_until_ready does not reliably block for pallas
            # results on the tunnel backend); the best-stack array
            # STAYS on device and is fetched lazily — only a refuted
            # lane ever reads it. When the verdicts show refutations,
            # the fetch starts ASYNCHRONOUSLY here so it streams while
            # the host builds the valid lanes' results.
            small = np.asarray(small_dev)[:, :w]
            if (small[0] == INVALID).any():
                try:
                    best_dev.copy_to_host_async()
                except (AttributeError, NotImplementedError):
                    pass
            smalls.append(small)
            bests.append((best_dev, w))
        small = (smalls[0] if len(smalls) == 1
                 else np.concatenate(smalls, axis=1))
        cell: list = []

        def best():
            if not cell:
                parts = [np.asarray(bd)[:, :w] for bd, w in bests]
                cell.append(parts[0] if len(parts) == 1
                            else np.concatenate(parts, axis=1))
            return cell[0]

        return small, best

    def result(es, small, best, i, extra_steps=0):
        v, s = small[0][i], int(small[1][i]) + extra_steps
        if v == VALID:
            return WGLResult(valid=True, steps=s)
        if v == INVALID:
            # the kernel tracked its own counterexample (deepest legal
            # prefix + stuck entry, wgl_search.cpp:329-341 semantics) —
            # no host re-search
            stuck, bestd = int(small[4][i]), int(small[3][i])
            op = es.invokes[stuck] if stuck >= 0 else None
            bl = [es.invokes[int(e)]
                  for e in best()[: max(0, bestd), i]]
            return WGLResult(
                valid=False, op=op, best_linearization=bl, steps=s)
        return WGLResult(valid="unknown", steps=s)

    # Two-pass scheduling: lanes in a 128-wide block run in lockstep,
    # so ONE deep lane holds its whole block at the full budget —
    # scattered hard lanes make every block run ~max_steps iterations.
    # Pass 1 runs everyone under a small cap (most lanes resolve in
    # hundreds of steps); survivors are repacked DENSELY so only their
    # few blocks pay the deep budget. Only worth the second dispatch's
    # fixed round trip (~110ms) when the full budget dwarfs the pass-1
    # cap and there is more than one block to densify. Re-measured
    # after the r5 chunked-launch refactor (VERDICT r4 item 8), fresh
    # seeds, k=2, on the v5e: scattered-hard 1024 lanes at a 200k cap
    # 632-680ms two-pass vs 932-990ms single (-32%); all-valid 1024
    # lanes at 2M indistinguishable (survivors=0 skips pass 2); and at
    # deep-4096/16384's 4k cap FORCING it on loses 25-40% — which the
    # `8 *` threshold already excludes (4000 < 8*512: the gate is OFF
    # there by design, not by accident).
    two_pass = (max_steps > 8 * PASS1_CAP
                and len(entries_list) > LANES)
    pass1_cap = min(PASS1_CAP, max_steps) if two_pass else max_steps
    small1, best1 = launch(None, pass1_cap)
    survivors = [i for i in range(n) if small1[0][i] == UNKNOWN]
    surv_set = set(survivors)
    results: list = [None] * n
    for i, es in enumerate(entries_list):
        if i not in surv_set:
            results[i] = result(es, small1, best1, i)
    if survivors and max_steps > pass1_cap:
        small2, best2 = launch(survivors, max_steps)
        for j, i in enumerate(survivors):
            # pass-1 work is genuinely spent: report it in the total
            results[i] = result(entries_list[i], small2, best2, j,
                                extra_steps=int(small1[1][i]))
    elif survivors:
        for i in survivors:
            results[i] = result(entries_list[i], small1, best1, i)
    return results


def probe() -> bool:
    """Compile-and-run one minimal lane through the full batch path
    (encode, pack, Mosaic compile, launch, fetch). The supervisor's
    first-compile probe (checker/supervisor.py) runs this in a
    SUBPROCESS: a FATAL Mosaic/XLA abort here kills the probe child,
    not the analysis — the parent merely quarantines the engine."""
    from ..history import Op
    from ..models import CASRegister

    h = [Op(0, "invoke", "write", 1, time=0, index=0),
         Op(0, "ok", "write", 1, time=1, index=1)]
    (r,) = analysis_batch(CASRegister(None), [h], max_steps=10_000)
    return r.valid is True
