"""The WGL search as ONE Pallas (Mosaic) kernel per 128-lane block,
with the lanes VECTORIZED across the TPU's lane dimension.

ops/wgl_tpu.py runs the DFS as a lax.while_loop of XLA ops: every
gather/scatter in the body is its own kernel launch per iteration
(~tens of us on this backend), so whole-batch throughput tops out
around a few hundred thousand steps/s however many lanes are vmapped.
ops/wgl_pallas.py moved the loop inside one Mosaic kernel but ran one
lane per sequential grid program, leaving the scalar unit
pointer-chasing (~86 us/step). This module keeps the whole search
inside one kernel AND runs 128 lanes per program in lockstep on the
vector unit:

- every per-lane scalar (node, state, depth, ...) is a (1, 128) row;
- every table (per-entry facts, node maps, the nxt/prv linked list,
  the undo stack) is an (R, 128) VMEM block, one column per lane;
- every data-dependent read is a ONE-HOT masked reduction over the
  sublane axis and every write a predicated full-array select — there
  is no dynamic indexing at all, which sidesteps Mosaic's
  no-dynamic-lane-indexing and scalar-store constraints entirely and
  keeps every op on the VPU;
- the memo cache is exact full-key compare against ALL slots
  (direct-mapped insert by hash). Pruning differs from the host's
  unbounded 8-probe memo — step counts may differ — but any
  exact-compare cache is sound, so VERDICTS are bit-identical to the
  host search (asserted by the parity tests).

Blocks of 128 lanes run as sequential grid programs; within a block,
lanes that finish idle (gated) until the block's while loop drains.

Scope: scalar kernel models (cas-register / register / mutex — one
int32 state, state_in_key) and histories up to MAX_PAD entries.
Everything else routes to ops/wgl_tpu.py.

On non-TPU backends the kernel runs in pallas interpret mode (the CPU
test suite uses this for parity); on TPU it compiles via Mosaic.
"""

from __future__ import annotations

import logging

import numpy as np

import jax
import jax.numpy as jnp

from ..history import Entries, entries as make_entries
from ..models import jit as mjit
from .wgl_host import WGLResult, recover_invalid
from .wgl_tpu import (RUNNING, VALID, INVALID, UNKNOWN,
                      DEFAULT_MAX_STEPS, _next_pow2,
                      _zobrist_table, encode_entries)

log = logging.getLogger("jepsen_tpu.ops.wgl_pallas_vec")

LANES = 128                  # lanes per grid program (one vreg row)
CACHE_SLOTS = 128            # direct-mapped exact-key cache rows
MAX_PAD = 1024               # bitset words stay a small sublane block


def _m_pad(n_pad: int) -> int:
    """Node-array rows (2*n_pad+1) padded to the sublane tile."""
    return ((2 * n_pad + 1 + 7) // 8) * 8


def _nw(n_pad: int) -> int:
    return max(1, (n_pad + 31) // 32)


def _nw_pad(n_pad: int) -> int:
    return ((_nw(n_pad) + 7) // 8) * 8


def eligible(jm, n_pad: int) -> bool:
    """Scalar one-word models only; the queue models carry vector
    state that doesn't fit the one-lane-per-column layout."""
    return (isinstance(jm, mjit.JitModel)
            and jm.state_in_key
            and n_pad <= MAX_PAD)


def _make_kernel(jm, n_pad: int, max_steps: int):
    from jax.experimental import pallas as pl  # noqa: F401

    m_pad = _m_pad(n_pad)
    nw = _nw(n_pad)
    nw_pad = _nw_pad(n_pad)
    # plain Python ints — jnp values created outside the kernel would
    # be captured tracers, which pallas rejects
    init_state_c = int(jm.init_state)
    fnv_basis_c = int(np.uint32(2166136261).astype(np.int32))
    cache_mask_c = CACHE_SLOTS - 1

    def kernel(f_ref, v1_ref, v2_ref, crashed_ref, call_ref, ret_ref,
               entry_ref, is_call_ref, nxt0_ref, prv0_ref, ncomp_ref,
               ztab_ref,
               verdict_ref, steps_ref, depth_ref,
               nxt, prv, stack_e, stack_s, cache, cache_used):
        i32 = jnp.int32
        m_iota = jax.lax.broadcasted_iota(i32, (m_pad, LANES), 0)
        n_iota = jax.lax.broadcasted_iota(i32, (n_pad, LANES), 0)
        w_iota = jax.lax.broadcasted_iota(i32, (nw_pad, LANES), 0)
        c_iota = jax.lax.broadcasted_iota(i32, (CACHE_SLOTS, LANES), 0)

        # --- per-program init (scratch persists across programs; a
        # stale cache entry from another block would wrongly match) ---
        nxt[...] = nxt0_ref[...]
        prv[...] = prv0_ref[...]
        cache[...] = jnp.zeros((CACHE_SLOTS, (nw + 1) * LANES), i32)
        cache_used[...] = jnp.zeros((CACHE_SLOTS, LANES), i32)

        n_completed = ncomp_ref[...]                     # [1, L]

        def rd(ref, rows, idx):
            """ref[idx] per lane as a one-hot masked reduction.
            Out-of-range idx (e.g. depth-1 at depth 0) yields zeros;
            every consumer of such a read is gated."""
            iota = {m_pad: m_iota, n_pad: n_iota}[rows]
            mask = iota == idx                           # [rows, L]
            return jnp.sum(jnp.where(mask, ref[...], 0),
                           axis=0, keepdims=True)        # [1, L]

        def mix_hash(h_lin, state):
            h = ((h_lin ^ state) * i32(16777619)).astype(jnp.uint32)
            h = (h ^ (h >> 15)) * jnp.uint32(0x85EBCA6B)
            return (h ^ (h >> 13)).astype(i32)

        init = (
            nxt0_ref[0:1, :],                            # node
            jnp.full((1, LANES), init_state_c, i32),     # state
            jnp.zeros((nw_pad, LANES), i32),             # lin bitset
            jnp.full((1, LANES), fnv_basis_c, i32),      # h_lin
            jnp.zeros((1, LANES), i32),                  # depth
            jnp.zeros((1, LANES), i32),                  # completed
            jnp.zeros((1, LANES), i32),                  # steps
            jnp.where(n_completed == 0, i32(VALID), i32(RUNNING)),
        )

        def cond(st):
            return jnp.any((st[7] == RUNNING) & (st[6] < max_steps))

        def body(st):
            node, state, lin, h_lin, depth, completed, steps, verdict = st
            active = (verdict == RUNNING) & (steps < max_steps)
            zero = jnp.zeros((1, LANES), i32)

            e = rd(entry_ref, m_pad, node)
            is_call = (node != 0) & (rd(is_call_ref, m_pad, node) != 0)

            e2 = rd(stack_e, n_pad, depth - 1)

            f_e = rd(f_ref, n_pad, e)
            v1_e = rd(v1_ref, n_pad, e)
            v2_e = rd(v2_ref, n_pad, e)
            crashed_e = rd(crashed_ref, n_pad, e)
            cn = rd(call_ref, n_pad, e)
            rn = rd(ret_ref, n_pad, e)
            z_e = rd(ztab_ref, n_pad, e)
            f_e2 = rd(f_ref, n_pad, e2)
            v1_e2 = rd(v1_ref, n_pad, e2)    # noqa: F841 (symmetry)
            crashed_e2 = rd(crashed_ref, n_pad, e2)
            cn2 = rd(call_ref, n_pad, e2)
            rn2 = rd(ret_ref, n_pad, e2)
            z_e2 = rd(ztab_ref, n_pad, e2)
            del f_e2, v1_e2

            new_state, ok = jm.step(state, f_e, v1_e, v2_e)
            new_state = new_state.astype(i32)
            can_lin = active & is_call & ok

            word = e // 32
            bit = i32(1) << (e % 32)
            new_lin = lin | jnp.where(w_iota == word, bit, i32(0))
            new_h = h_lin ^ z_e

            # ---- cache: exact full-key compare against ALL slots ----
            hmix = mix_hash(new_h, new_state)
            slot = hmix & i32(cache_mask_c)              # [1, L]
            eq = cache_used[...] != 0                    # [C, L]
            for w in range(nw):
                eq = eq & (cache[:, w * LANES:(w + 1) * LANES]
                           == new_lin[w:w + 1, :])
            eq = eq & (cache[:, nw * LANES:(nw + 1) * LANES] == new_state)
            found = jnp.max(eq.astype(i32), axis=0, keepdims=True) != 0

            do_lift = can_lin & ~found
            lift_completed = completed + jnp.where(crashed_e != 0, 0, 1)

            can_pop = depth > 0
            pop_state = rd(stack_s, n_pad, depth - 1)
            word2 = e2 // 32
            bit2 = i32(1) << (e2 % 32)
            pop_lin = lin & ~jnp.where(w_iota == word2, bit2, i32(0))
            pop_completed = completed - jnp.where(crashed_e2 != 0, 0, 1)

            advance = active & is_call & ~do_lift
            backtrack = active & ~is_call
            do_back = backtrack & can_pop

            # ---- linked list: raw reads, then the same scalar-fixup
            # algebra as the XLA dense form (round A never
            # materializes) ----
            nxt_cn = rd(nxt, m_pad, cn)
            prv_cn = rd(prv, m_pad, cn)
            nxt_rn = rd(nxt, m_pad, rn)
            prv_rn = rd(prv, m_pad, rn)
            nxt_rn2 = rd(nxt, m_pad, rn2)
            prv_rn2 = rd(prv, m_pad, rn2)
            nxt_cn2 = rd(nxt, m_pad, cn2)
            prv_cn2 = rd(prv, m_pad, cn2)
            nxt_0 = nxt[0:1, :]
            prv_0 = prv[0:1, :]
            nxt_node = rd(nxt, m_pad, node)

            posA_n = jnp.where(do_lift, prv_cn,
                               jnp.where(do_back, prv_rn2, zero))
            valA_n = jnp.where(do_lift, nxt_cn,
                               jnp.where(do_back, rn2, nxt_0))
            posA_p = jnp.where(do_lift, nxt_cn,
                               jnp.where(do_back, nxt_rn2, zero))
            valA_p = jnp.where(do_lift, prv_cn,
                               jnp.where(do_back, rn2, prv_0))

            rd_n1 = lambda i, raw: jnp.where(i == posA_n, valA_n, raw)  # noqa: E731,E501
            rd_p1 = lambda i, raw: jnp.where(i == posA_p, valA_p, raw)  # noqa: E731,E501
            posB_n = jnp.where(do_lift, rd_p1(rn, prv_rn),
                               jnp.where(do_back, rd_p1(cn2, prv_cn2),
                                         zero))
            valB_n = jnp.where(do_lift, rd_n1(rn, nxt_rn),
                               jnp.where(do_back, cn2, rd_n1(zero, nxt_0)))
            posB_p = jnp.where(do_lift, rd_n1(rn, nxt_rn),
                               jnp.where(do_back, rd_n1(cn2, nxt_cn2),
                                         zero))
            valB_p = jnp.where(do_lift, rd_p1(rn, prv_rn),
                               jnp.where(do_back, cn2, rd_p1(zero, prv_0)))
            rd_nout = lambda i, raw: jnp.where(  # noqa: E731
                i == posB_n, valB_n, rd_n1(i, raw))

            nxt[...] = jnp.where(
                m_iota == posB_n, valB_n,
                jnp.where(m_iota == posA_n, valA_n, nxt[...]))
            prv[...] = jnp.where(
                m_iota == posB_p, valB_p,
                jnp.where(m_iota == posA_p, valA_p, prv[...]))

            # ---- cache insert (direct-mapped) + stack push ----
            sl = (c_iota == slot) & do_lift              # [C, L]
            for w in range(nw):
                cache[:, w * LANES:(w + 1) * LANES] = jnp.where(
                    sl, new_lin[w:w + 1, :],
                    cache[:, w * LANES:(w + 1) * LANES])
            cache[:, nw * LANES:(nw + 1) * LANES] = jnp.where(
                sl, new_state, cache[:, nw * LANES:(nw + 1) * LANES])
            cache_used[...] = jnp.where(sl, i32(1), cache_used[...])

            push = (n_iota == depth) & do_lift
            stack_e[...] = jnp.where(push, e, stack_e[...])
            stack_s[...] = jnp.where(push, state, stack_s[...])

            # ---- next scalars ----
            node_out = jnp.where(
                do_lift, rd_nout(zero, nxt_0),
                jnp.where(advance, rd_nout(node, nxt_node),
                          jnp.where(do_back, rd_nout(cn2, nxt_cn2), node)))
            state_out = jnp.where(
                do_lift, new_state,
                jnp.where(do_back, pop_state, state))
            lin_out = jnp.where(
                do_lift, new_lin, jnp.where(do_back, pop_lin, lin))
            h_out = jnp.where(
                do_lift, new_h,
                jnp.where(do_back, h_lin ^ z_e2, h_lin))
            depth_out = jnp.where(
                do_lift, depth + 1, jnp.where(do_back, depth - 1, depth))
            completed_out = jnp.where(
                do_lift, lift_completed,
                jnp.where(do_back, pop_completed, completed))
            verdict_out = jnp.where(
                do_lift & (lift_completed == n_completed), i32(VALID),
                jnp.where(backtrack & ~can_pop, i32(INVALID), verdict))

            return (node_out, state_out, lin_out, h_out, depth_out,
                    completed_out, steps + active.astype(i32), verdict_out)

        out = jax.lax.while_loop(cond, body, init)
        final = jnp.where(out[7] == RUNNING, jnp.int32(UNKNOWN), out[7])
        verdict_ref[...] = final
        steps_ref[...] = out[6]
        depth_ref[...] = out[4]

    return kernel, m_pad


def _pack(entries_list, jm, n_pad: int) -> tuple[dict, int]:
    """Pack lanes column-wise into [rows, n_blocks*LANES] arrays.
    Padding lanes have n_completed == 0, so they go VALID at init and
    idle through the block's loop."""
    ents = [encode_entries(es, jm, n_pad) for es in entries_list]
    m_pad = _m_pad(n_pad)
    n_lanes = len(ents)
    n_blocks = (n_lanes + LANES - 1) // LANES
    width = n_blocks * LANES

    def col(key, rows):
        out = np.zeros((rows, width), np.int32)
        for i, e in enumerate(ents):
            a = np.asarray(e[key]).astype(np.int32)
            out[:a.shape[0], i] = a
        return out

    packed = {
        "f": col("f", n_pad),
        "v1": col("v1", n_pad),
        "v2": col("v2", n_pad),
        "crashed": col("crashed", n_pad),
        "call_node": col("call_node", n_pad),
        "ret_node": col("ret_node", n_pad),
        "node_entry": col("node_entry", m_pad),
        "node_is_call": col("node_is_call", m_pad),
        "nxt0": col("nxt0", m_pad),
        "prv0": col("prv0", m_pad),
        "n_completed": np.zeros((1, width), np.int32),
        "ztab": np.broadcast_to(
            _zobrist_table(n_pad).astype(np.int32)[:, None],
            (n_pad, width)).copy(),
    }
    for i, e in enumerate(ents):
        packed["n_completed"][0, i] = e["n_completed"]
    return packed, n_blocks


_kernel_cache: dict = {}


def _launcher(jm, n_pad: int, max_steps: int, interpret: bool,
              n_blocks: int):
    """One jitted pallas_call per (model, shape, blocks) — building the
    call is ~1 s of host tracing, dwarfing the sub-ms kernel, so it
    must happen once, not per invocation."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    key = (jm.name, n_pad, max_steps, interpret, n_blocks)
    if key in _kernel_cache:
        return _kernel_cache[key]

    kernel, m_pad = _make_kernel(jm, n_pad, max_steps)
    nw = _nw(n_pad)

    def spec(rows):
        return pl.BlockSpec((rows, LANES), lambda i: (0, i))

    in_specs = [
        spec(n_pad), spec(n_pad), spec(n_pad), spec(n_pad),
        spec(n_pad), spec(n_pad),
        spec(m_pad), spec(m_pad), spec(m_pad), spec(m_pad),
        spec(1), spec(n_pad),
    ]
    width = n_blocks * LANES
    out_specs = [spec(1)] * 3
    out_shape = [jax.ShapeDtypeStruct((1, width), jnp.int32)] * 3
    call = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((m_pad, LANES), jnp.int32),   # nxt
            pltpu.VMEM((m_pad, LANES), jnp.int32),   # prv
            pltpu.VMEM((n_pad, LANES), jnp.int32),   # stack_e
            pltpu.VMEM((n_pad, LANES), jnp.int32),   # stack_s
            pltpu.VMEM((CACHE_SLOTS, (nw + 1) * LANES), jnp.int32),
            pltpu.VMEM((CACHE_SLOTS, LANES), jnp.int32),
        ],
        interpret=interpret,
    )

    @jax.jit
    def run(packed):
        return call(
            packed["f"], packed["v1"], packed["v2"], packed["crashed"],
            packed["call_node"], packed["ret_node"],
            packed["node_entry"], packed["node_is_call"],
            packed["nxt0"], packed["prv0"], packed["n_completed"],
            packed["ztab"],
        )

    _kernel_cache[key] = run
    return run


def analysis_batch(model, entries_list, max_steps: int | None = None,
                   interpret: bool | None = None) -> list:
    """Check a batch of independent histories, 128 lanes per kernel
    program. Raises on ineligible models/sizes — callers probe with
    `eligible` first (checker/linearizable routes here for scalar
    models; everything else uses ops/wgl_tpu)."""
    jm = mjit.for_model(model)
    if jm is None:
        raise ValueError(f"no kernel model for {model!r}")
    entries_list = [es if isinstance(es, Entries) else make_entries(es)
                    for es in entries_list]
    if not entries_list:
        return []
    if max_steps is None:
        max_steps = DEFAULT_MAX_STEPS
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    n_pad = max(_next_pow2(max(len(es) for es in entries_list)), 32)
    if not eligible(jm, n_pad):
        raise ValueError(
            f"pallas-vec path ineligible: model={jm.name} n_pad={n_pad}")
    for es in entries_list:
        if not jm.lane_eligible(es):
            raise ValueError("lane has no int32 encoding")

    packed, n_blocks = _pack(entries_list, jm, n_pad)
    run = _launcher(jm, n_pad, max_steps, interpret, n_blocks)
    verdicts, steps, depths = jax.block_until_ready(run(packed))
    verdicts = np.asarray(verdicts).reshape(-1)
    steps = np.asarray(steps).reshape(-1)

    results = []
    for i, es in enumerate(entries_list):
        v, s = verdicts[i], int(steps[i])
        if v == VALID:
            results.append(WGLResult(valid=True, steps=s))
        elif v == INVALID:
            # counterexample recovery host-side, native engine
            # preferred — same fallback chain as wgl_tpu's invalid path
            results.append(recover_invalid(model, es))
        else:
            results.append(WGLResult(valid="unknown", steps=s))
    return results
