"""Wing-Gong-Lowe linearizability search, host implementation.

Parity target: knossos.wgl/analysis (SURVEY.md SS2.2; invoked from the
reference's jepsen.checker/linearizable, checker.clj:116-141). The
algorithm is Lowe's refinement of Wing & Gong's tree search ("Testing for
linearizability", Lowe 2016): a depth-first search over the orders in
which concurrent operations could have taken effect, pruned by a
memoization cache of (linearized-bitset, model-state) pairs.

Mechanics: the history's call/return events form a doubly-linked list in
real-time order. The search repeatedly tries to linearize some operation
whose call precedes the first un-linearized return ("minimal" operations);
linearizing an op *lifts* (unlinks) its two events and records
(op, previous-state) on an undo stack. Hitting a return event means no
minimal op could be linearized — pop the stack and resume after the
popped op's call. The history is linearizable iff every *completed*
operation gets linearized.

Crash semantics: an op whose outcome is unknown (:info completion or no
completion) has its return at infinity — it stays available for
linearization forever, but is never *required* to linearize (the op may
simply never have happened). Failed ops are excluded before the search
(they definitely did not happen). This matches knossos's handling of
jepsen's determinacy rules (core.clj:271-304).

This module is the semantics oracle for ops/wgl_tpu.py and the fallback
path for models with no int32 state encoding (queues, sets).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass
from typing import Any

from ..history import Entries, Op, entries as make_entries
from ..models import Model, inconsistent


@dataclass
class WGLResult:
    valid: Any  # True | False | "unknown"
    op: Op | None = None  # the op at whose return the search died
    best_linearization: list | None = None  # ops of the deepest prefix found
    final_state: Any = None
    cache_size: int = 0
    steps: int = 0

    def to_dict(self) -> dict:
        d = {"valid": self.valid}
        if self.op is not None:
            d["op"] = self.op.to_dict()
        if self.best_linearization is not None:
            d["best_linearization"] = [o.to_dict() for o in self.best_linearization]
        d["cache_size"] = self.cache_size
        d["steps"] = self.steps
        return d


def analysis(
    model: Model,
    history,
    time_limit: float | None = None,
    max_steps: int | None = None,
) -> WGLResult:
    """Check linearizability of `history` against `model`.

    history may be a raw sequence of Ops (invokes + completions) or an
    already-built Entries. Returns WGLResult with valid in
    {True, False, "unknown"} — "unknown" on time/step budget exhaustion,
    mirroring knossos's :unknown verdicts.
    """
    es = history if isinstance(history, Entries) else make_entries(history)
    n = len(es)
    if es.n_completed == 0:
        # Nothing is *required* to linearize: every op either failed
        # (excluded) or crashed (may never have happened).
        return WGLResult(valid=True, final_state=model)

    # Event list: 2 nodes per entry at positions call_pos/ret_pos.
    # node id = event position + 1 (0 is the head sentinel).
    n_nodes = 2 * n + 1
    nxt = list(range(1, n_nodes + 1))
    nxt[-1] = 0  # last node -> sentinel (treated as end)
    prv = list(range(-1, n_nodes - 1))
    prv[0] = 0
    node_entry = [0] * n_nodes  # node -> entry id (undefined for sentinel)
    node_is_call = [False] * n_nodes
    call_node = [0] * n
    ret_node = [0] * n
    for e in range(n):
        c = int(es.call_pos[e]) + 1
        r = int(es.ret_pos[e]) + 1
        call_node[e] = c
        ret_node[e] = r
        node_entry[c] = e
        node_entry[r] = e
        node_is_call[c] = True

    END = 0  # running off the end lands on the sentinel via nxt[-1] = 0

    def lift(e: int) -> None:
        for nd in (call_node[e], ret_node[e]):
            p, q = prv[nd], nxt[nd]
            nxt[p] = q
            if q != END:
                prv[q] = p

    def unlift(e: int) -> None:
        for nd in (ret_node[e], call_node[e]):
            p, q = prv[nd], nxt[nd]
            nxt[p] = nd
            if q != END:
                prv[q] = nd

    fs = es.f
    vals = es.value_out
    crashed = es.crashed
    n_completed = es.n_completed

    state: Any = model
    linearized = 0
    completed_done = 0
    cache: set = {(0, model)}
    stack: list = []  # (entry, prev_state)
    best_depth = -1
    best_stack_entries: list = []
    stuck_entry: int | None = None

    node = nxt[0]
    steps = 0
    deadline = None if time_limit is None else _time.monotonic() + time_limit
    CHECK_EVERY = 4096

    while True:
        steps += 1
        if max_steps is not None and steps > max_steps:
            return WGLResult(valid="unknown", cache_size=len(cache), steps=steps)
        if (
            deadline is not None
            and steps % CHECK_EVERY == 0
            and _time.monotonic() > deadline
        ):
            return WGLResult(valid="unknown", cache_size=len(cache), steps=steps)

        if node != END and node_is_call[node]:
            e = node_entry[node]
            new_state = state.step(fs[e], vals[e])
            advanced = False
            if not inconsistent(new_state):
                new_lin = linearized | (1 << e)
                key = (new_lin, new_state)
                if key not in cache:
                    cache.add(key)
                    stack.append((e, state))
                    state = new_state
                    linearized = new_lin
                    if not crashed[e]:
                        completed_done += 1
                    lift(e)
                    if completed_done == n_completed:
                        return WGLResult(
                            valid=True,
                            best_linearization=[es.invokes[i] for i, _ in stack],
                            final_state=state,
                            cache_size=len(cache),
                            steps=steps,
                        )
                    node = nxt[0]
                    advanced = True
            if not advanced:
                node = nxt[node]
        else:
            # Return event (or end of list): nothing minimal linearizes.
            if len(stack) > best_depth:
                best_depth = len(stack)
                best_stack_entries = [i for i, _ in stack]
                stuck_entry = node_entry[node] if node != END else None
            if not stack:
                op = es.invokes[stuck_entry] if stuck_entry is not None else None
                return WGLResult(
                    valid=False,
                    op=op,
                    best_linearization=[es.invokes[i] for i in best_stack_entries],
                    cache_size=len(cache),
                    steps=steps,
                )
            e, prev_state = stack.pop()
            state = prev_state
            linearized &= ~(1 << e)
            if not crashed[e]:
                completed_done -= 1
            unlift(e)
            node = nxt[call_node[e]]


def check(model: Model, history, **kw) -> dict:
    """Convenience: analysis() as a plain dict."""
    return analysis(model, history, **kw).to_dict()


def recover_invalid(model: Model, es) -> WGLResult:
    """Re-run the search host-side to recover counterexample details
    for a lane an accelerator kernel already proved invalid (verdicts
    agree by construction). Prefers the native C++ engine (~13x this
    module); NativeUnavailable quietly falls back, any other native
    failure is logged so real engine bugs can't hide behind the
    fallback."""
    import logging

    try:
        from . import wgl_native
        native_unavailable = wgl_native.NativeUnavailable
    except ImportError as e:  # wgl_native itself failed to import
        logging.getLogger("jepsen_tpu.ops").warning(
            "native engine unavailable (%s); using the Python oracle", e)
        return analysis(model, es)
    try:
        return wgl_native.analysis(model, es)
    except Exception as e:
        if not isinstance(e, native_unavailable):
            logging.getLogger("jepsen_tpu.ops").warning(
                "native counterexample recovery failed (%s); "
                "falling back to the Python oracle", e)
        return analysis(model, es)
