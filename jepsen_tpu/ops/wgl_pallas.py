"""The WGL search as ONE Pallas (Mosaic) TPU kernel per lane.

ops/wgl_tpu.py expresses the DFS as a lax.while_loop of fused XLA ops;
every loop iteration pays multi-kernel dispatch overhead, which
dominates for the short lanes the independent checker produces. This
module compiles the ENTIRE search loop into a single Mosaic kernel —
one launch per batch, zero per-step dispatch — with the lane axis as
the pallas grid.

Mosaic constraints shape the data layout:
- per-entry arrays are (n_pad, 1) int32 so every data-dependent index
  is in the SUBLANE dimension (dynamic lane indexing is rejected);
- scalar stores are expressed as (1, 1) dynamic-slice stores;
- the linearized bitset lives in a (1, 128) int32 row updated with
  iota-mask vector ops (32 bits per lane → histories up to 4064
  entries), and the model state is packed into the row's last lane —
  the row itself is then the exact memo key;
- the memo cache is VMEM scratch: (2^CACHE_BITS, 128) key rows plus a
  (2^CACHE_BITS, 1) used column, re-zeroed at the start of each grid
  program (scratch persists across programs).

Scope: scalar kernel models only (cas-register / register / mutex:
one-int32 state, state_in_key). Vector-state models and histories
beyond the bitset-row capacity use ops/wgl_tpu. The algorithm, search
order, and Zobrist bucket selection match wgl_tpu/wgl_host exactly, so
verdicts are identical, and step counts match the host search whenever
the kernel's bounded cache (2^CACHE_BITS rows vs the host's unbounded
memo set) doesn't evict — evictions only cost pruning, never
soundness, but they can make kernel step counts exceed the host's.

On non-TPU backends the kernel runs in pallas interpret mode (used by
the CPU test suite for parity); on TPU it compiles via Mosaic.

MEASURED RESULT (v5e, 34 x ~300-op CAS lanes): correct verdicts and
step counts, but ~0.5x the XLA kernel's throughput — Mosaic's grid
runs lane programs sequentially on one TensorCore, and each DFS step's
~30 data-dependent scalar VMEM accesses cost ~86us/step (cache size
and probe count are immaterial; the dynamic accesses dominate). This
confirms SURVEY §7.4's "irregular search on SIMD hardware" analysis:
the XLA kernel's vmapped lockstep batching amortizes dispatch better
than Mosaic's scalar unit handles pointer-chasing. The module stays as
a parity-tested alternative (checker/linearizable does NOT route here)
so future Mosaic scalar-memory improvements can be re-measured by
calling wgl_pallas.analysis_batch directly on the bench workload.
"""

from __future__ import annotations

import logging

import numpy as np

import jax
import jax.numpy as jnp

from ..history import Entries, entries as make_entries
from ..models import jit as mjit
from .wgl_host import WGLResult, recover_invalid
from .wgl_tpu import (RUNNING, VALID, INVALID, UNKNOWN,
                      DEFAULT_MAX_STEPS, N_PROBES, _next_pow2,
                      _zobrist_table, encode_entries)

log = logging.getLogger("jepsen_tpu.ops.wgl_pallas")

CACHE_BITS = 11  # 2048 rows * 128 lanes * 4 B = 1 MB VMEM per program
ROW = 128
STATE_LANE = ROW - 1          # lane 127 carries the model state
MAX_WORDS = ROW - 1           # bitset words 0..126
MAX_PAD = MAX_WORDS * 32      # 4064 entries


def _m_pad(n_pad: int) -> int:
    """Node-array size (2*n_pad+1) padded to Mosaic's sublane tile."""
    return ((2 * n_pad + 1 + 7) // 8) * 8


def eligible(jm, n_pad: int) -> bool:
    """Scalar models whose bitset fits the row layout."""
    return (isinstance(jm, mjit.JitModel)
            and jm.state_in_key
            and n_pad <= MAX_PAD)


def _make_kernel(jm, n_pad: int, max_steps: int):
    from jax.experimental import pallas as pl

    m_pad = _m_pad(n_pad)
    cache_size = 1 << CACHE_BITS
    # plain Python ints — jnp values created outside the kernel would
    # be captured tracers, which pallas rejects
    mask_c = cache_size - 1
    init_state_c = int(jm.init_state)
    fnv_basis_c = int(np.uint32(2166136261).astype(np.int32))

    def kernel(f_ref, v1_ref, v2_ref, crashed_ref, call_ref, ret_ref,
               entry_ref, is_call_ref, nxt0_ref, prv0_ref, ncomp_ref,
               ztab_ref,
               verdict_ref, steps_ref, depth_ref,
               nxt, prv, stack_e, stack_s, cache_keys, cache_used):
        mask = jnp.int32(mask_c)
        init_state = jnp.int32(init_state_c)
        fnv_basis = jnp.int32(fnv_basis_c)
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (1, ROW), 1)
        # --- per-program init (scratch persists across programs) ---
        nxt[...] = nxt0_ref[0]
        prv[...] = prv0_ref[0]
        cache_keys[...] = jnp.zeros((cache_size, ROW), jnp.int32)
        cache_used[...] = jnp.zeros((cache_size, 1), jnp.int32)

        n_completed = ncomp_ref[0, 0, 0]

        def ld(ref, i):
            return ref[0, i, 0]

        def st1(ref, i, v):
            ref[pl.ds(i, 1), :] = jnp.full((1, 1), v, jnp.int32)

        def mix_hash(h_lin, state):
            h = ((h_lin ^ state) * jnp.int32(16777619)).astype(jnp.uint32)
            h = (h ^ (h >> 15)) * jnp.uint32(0x85EBCA6B)
            return (h ^ (h >> 13)).astype(jnp.int32)

        init = (
            ld(nxt0_ref, 0),                 # node
            init_state,                      # state
            jnp.where(lane_iota == STATE_LANE, init_state,
                      jnp.int32(0)),         # row: bits + state lane
            fnv_basis,                       # h_lin
            jnp.int32(0),                    # depth
            jnp.int32(0),                    # completed_done
            jnp.int32(0),                    # steps
            jnp.where(n_completed == 0, jnp.int32(VALID),
                      jnp.int32(RUNNING)),   # verdict
        )

        def cond(st):
            return (st[7] == RUNNING) & (st[6] < max_steps)

        def body(st):
            node, state, row, h_lin, depth, completed, steps, _v = st

            e = ld(entry_ref, node)
            is_call = (node != 0) & (ld(is_call_ref, node) != 0)

            new_state, ok = jm.step(state, ld(f_ref, e), ld(v1_ref, e),
                                    ld(v2_ref, e))
            new_state = new_state.astype(jnp.int32)
            can_lin = is_call & ok

            bitmask = jnp.where(lane_iota == e // 32,
                                jnp.int32(1) << (e % 32), jnp.int32(0))
            new_row = jnp.where(lane_iota == STATE_LANE, new_state,
                                row | bitmask)
            new_h = h_lin ^ ld(ztab_ref, e)

            # ---- cache probe: unrolled, exact full-row compare ----
            h = mix_hash(new_h, new_state)
            found = jnp.int32(0)
            ins = jnp.int32(-1)
            last_slot = jnp.int32(0)
            for p in range(N_PROBES):
                slot = (h + p) & mask
                used_p = cache_used[slot, 0]
                row_p = cache_keys[pl.ds(slot, 1), :]
                match = (used_p != 0) & jnp.all(row_p == new_row)
                found = found | match.astype(jnp.int32)
                ins = jnp.where((ins < 0) & (used_p == 0), slot, ins)
                last_slot = slot
            ins = jnp.where(ins < 0, last_slot, ins)

            do_lift = can_lin & (found == 0)
            advance = is_call & ~do_lift
            backtrack = ~is_call

            lift_completed = completed + jnp.where(
                ld(crashed_ref, e) != 0, 0, 1)

            # ---- backtrack candidate ----
            can_pop = depth > 0
            dtop = jnp.maximum(depth - 1, 0)
            e2 = stack_e[dtop, 0]
            pop_state = stack_s[dtop, 0]
            cn2 = ld(call_ref, e2)
            rn2 = ld(ret_ref, e2)
            bitmask2 = jnp.where(lane_iota == e2 // 32,
                                 jnp.int32(1) << (e2 % 32), jnp.int32(0))
            pop_row = jnp.where(lane_iota == STATE_LANE, pop_state,
                                row & ~bitmask2)
            pop_completed = completed - jnp.where(
                ld(crashed_ref, e2) != 0, 0, 1)
            do_back = backtrack & can_pop

            cn = ld(call_ref, e)
            rn = ld(ret_ref, e)

            # ---- linked-list: two rounds of predicated stores,
            # reads of each round made BEFORE its stores (exactly the
            # sequential semantics of ops/wgl_tpu.py) ----
            zero = jnp.int32(0)
            prv_cn, nxt_cn = prv[cn, 0], nxt[cn, 0]
            prv_rn2, nxt_rn2 = prv[rn2, 0], nxt[rn2, 0]
            nxt_s0, prv_s0 = nxt[0, 0], prv[0, 0]
            posA_n = jnp.where(do_lift, prv_cn,
                               jnp.where(do_back, prv_rn2, zero))
            valA_n = jnp.where(do_lift, nxt_cn,
                               jnp.where(do_back, rn2, nxt_s0))
            posA_p = jnp.where(do_lift, nxt_cn,
                               jnp.where(do_back, nxt_rn2, zero))
            valA_p = jnp.where(do_lift, prv_cn,
                               jnp.where(do_back, rn2, prv_s0))
            st1(nxt, posA_n, valA_n)
            st1(prv, posA_p, valA_p)

            prv_rn, nxt_rn = prv[rn, 0], nxt[rn, 0]
            prv_cn2, nxt_cn2 = prv[cn2, 0], nxt[cn2, 0]
            nxt_s1, prv_s1 = nxt[0, 0], prv[0, 0]
            posB_n = jnp.where(do_lift, prv_rn,
                               jnp.where(do_back, prv_cn2, zero))
            valB_n = jnp.where(do_lift, nxt_rn,
                               jnp.where(do_back, cn2, nxt_s1))
            posB_p = jnp.where(do_lift, nxt_rn,
                               jnp.where(do_back, nxt_cn2, zero))
            valB_p = jnp.where(do_lift, prv_rn,
                               jnp.where(do_back, cn2, prv_s1))
            st1(nxt, posB_n, valB_n)
            st1(prv, posB_p, valB_p)

            # ---- cache insert + stacks (predicated) ----
            old_row = cache_keys[pl.ds(ins, 1), :]
            cache_keys[pl.ds(ins, 1), :] = jnp.where(
                do_lift, new_row, old_row)
            st1(cache_used, ins,
                cache_used[ins, 0] | do_lift.astype(jnp.int32))
            dpush = jnp.minimum(depth, n_pad - 1)
            st1(stack_e, dpush,
                jnp.where(do_lift, e, stack_e[dpush, 0]))
            st1(stack_s, dpush,
                jnp.where(do_lift, state, stack_s[dpush, 0]))

            # ---- select next scalars (post-store linked-list reads) --
            node_out = jnp.where(
                do_lift, nxt[0, 0],
                jnp.where(advance, nxt[node, 0],
                          jnp.where(can_pop, nxt[cn2, 0], node)))
            state_out = jnp.where(
                do_lift, new_state,
                jnp.where(advance, state,
                          jnp.where(can_pop, pop_state, state)))
            row_out = jnp.where(
                do_lift, new_row,
                jnp.where(do_back, pop_row, row))
            h_out = jnp.where(
                do_lift, new_h,
                jnp.where(do_back, h_lin ^ ld(ztab_ref, e2), h_lin))
            depth_out = jnp.where(
                do_lift, depth + 1,
                jnp.where(do_back, depth - 1, depth))
            completed_out = jnp.where(
                do_lift, lift_completed,
                jnp.where(do_back, pop_completed, completed))
            verdict = jnp.where(
                do_lift & (lift_completed == n_completed),
                jnp.int32(VALID),
                jnp.where(backtrack & ~can_pop, jnp.int32(INVALID),
                          jnp.int32(RUNNING)))

            return (node_out, state_out, row_out, h_out, depth_out,
                    completed_out, steps + 1, verdict)

        out = jax.lax.while_loop(cond, body, init)
        final = jnp.where(out[7] == RUNNING, jnp.int32(UNKNOWN), out[7])
        verdict_ref[...] = jnp.full((1, 1, 1), final, jnp.int32)
        steps_ref[...] = jnp.full((1, 1, 1), out[6], jnp.int32)
        depth_ref[...] = jnp.full((1, 1, 1), out[4], jnp.int32)

    return kernel, m_pad


def _pack(entries_list, jm, n_pad: int) -> dict:
    """Stack encoded lanes as (lanes, X, 1) int32 arrays."""
    ents = [encode_entries(es, jm, n_pad) for es in entries_list]
    m_pad = _m_pad(n_pad)

    def col(key, size):
        out = np.zeros((len(ents), size, 1), np.int32)
        for i, e in enumerate(ents):
            a = np.asarray(e[key]).astype(np.int32)
            out[i, :a.shape[0], 0] = a
        return out

    return {
        "f": col("f", n_pad),
        "v1": col("v1", n_pad),
        "v2": col("v2", n_pad),
        "crashed": col("crashed", n_pad),
        "call_node": col("call_node", n_pad),
        "ret_node": col("ret_node", n_pad),
        "node_entry": col("node_entry", m_pad),
        "node_is_call": col("node_is_call", m_pad),
        "nxt0": col("nxt0", m_pad),
        "prv0": col("prv0", m_pad),
        "n_completed": np.array(
            [[[e["n_completed"]]] for e in ents], np.int32),
    }


_kernel_cache: dict = {}


def _launcher(jm, n_pad: int, max_steps: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    key = (jm.name, n_pad, max_steps, interpret)
    if key in _kernel_cache:
        return _kernel_cache[key]

    kernel, m_pad = _make_kernel(jm, n_pad, max_steps)
    cache_size = 1 << CACHE_BITS

    def spec(size):
        return pl.BlockSpec((1, size, 1), lambda i: (i, 0, 0))

    def run(packed):
        lanes = packed["f"].shape[0]
        ztab = _zobrist_table(n_pad).astype(np.int32).reshape(1, n_pad, 1)
        in_specs = [
            spec(n_pad), spec(n_pad), spec(n_pad), spec(n_pad),
            spec(n_pad), spec(n_pad),
            spec(m_pad), spec(m_pad), spec(m_pad), spec(m_pad),
            pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n_pad, 1), lambda i: (0, 0, 0)),
        ]
        out_specs = [pl.BlockSpec((1, 1, 1), lambda i: (i, 0, 0))] * 3
        out_shape = [jax.ShapeDtypeStruct((lanes, 1, 1), jnp.int32)] * 3
        call = pl.pallas_call(
            kernel,
            grid=(lanes,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((m_pad, 1), jnp.int32),   # nxt
                pltpu.VMEM((m_pad, 1), jnp.int32),   # prv
                pltpu.VMEM((n_pad, 1), jnp.int32),   # stack_e
                pltpu.VMEM((n_pad, 1), jnp.int32),   # stack_s
                pltpu.VMEM((cache_size, ROW), jnp.int32),
                pltpu.VMEM((cache_size, 1), jnp.int32),
            ],
            interpret=interpret,
        )
        return call(
            packed["f"], packed["v1"], packed["v2"], packed["crashed"],
            packed["call_node"], packed["ret_node"],
            packed["node_entry"], packed["node_is_call"],
            packed["nxt0"], packed["prv0"], packed["n_completed"], ztab,
        )

    _kernel_cache[key] = run
    return run


def analysis_batch(model, entries_list, max_steps: int | None = None,
                   interpret: bool | None = None) -> list:
    """Check a batch of independent histories with the pallas kernel.
    Raises on ineligible models/sizes. NOT part of production dispatch
    (see the module docstring's measured numbers) — callers opt in
    explicitly, as tests/test_wgl_pallas.py does."""
    jm = mjit.for_model(model)
    if jm is None:
        raise ValueError(f"no kernel model for {model!r}")
    entries_list = [es if isinstance(es, Entries) else make_entries(es)
                    for es in entries_list]
    if not entries_list:
        return []
    if max_steps is None:
        max_steps = DEFAULT_MAX_STEPS
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    n_pad = max(_next_pow2(max(len(es) for es in entries_list)), 8)
    if n_pad > MAX_PAD:
        # the row layout caps at MAX_PAD (a multiple of 8, not of 2):
        # histories between the last power of two and the cap still fit
        n_pad = MAX_PAD
    if not eligible(jm, n_pad) \
            or max(len(es) for es in entries_list) > n_pad:
        raise ValueError(
            f"pallas path ineligible: model={jm.name} n_pad={n_pad}")
    for es in entries_list:
        if not jm.lane_eligible(es):
            raise ValueError("lane has no int32 encoding")

    packed = _pack(entries_list, jm, n_pad)
    run = _launcher(jm, n_pad, max_steps, interpret)
    verdicts, steps, depths = jax.block_until_ready(run(packed))
    verdicts = np.asarray(verdicts).reshape(-1)
    steps = np.asarray(steps).reshape(-1)

    results = []
    for es, v, s in zip(entries_list, verdicts, steps):
        if v == VALID:
            results.append(WGLResult(valid=True, steps=int(s)))
        elif v == INVALID:
            # counterexample recovery, native engine preferred — the
            # same fallback chain as wgl_tpu's invalid path
            results.append(recover_invalid(model, es))
        else:
            results.append(WGLResult(valid="unknown", steps=int(s)))
    return results
