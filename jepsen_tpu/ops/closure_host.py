"""Transitive closure of boolean dependency graphs on the host.

The cycle checker (checker/cycle) reduces Elle-style anomaly detection
to reachability over ww/wr/rw adjacency matrices: a transaction sits on
a dependency cycle iff it can reach itself through at least one edge.
This module is the always-available floor of the closure engine ladder
(checker/supervisor.py CLOSURE_LADDER): an iterative DFS per source
node over adjacency lists — O(n·(n+e)), no third-party deps, and the
semantics oracle the device engine (ops/closure_tpu.py) is
parity-tested against.

All closures here are *irreflexive-path* closures: ``reach[i, j]`` is
True iff there is a path of length >= 1 from i to j, so ``reach[i, i]``
marks a genuine cycle through i, never the trivial empty path.
"""

from __future__ import annotations

import numpy as np


def reach(adj: np.ndarray) -> np.ndarray:
    """Reachability-by-at-least-one-edge matrix of a dense boolean
    adjacency matrix: out[i, j] iff a path i -> ... -> j with >= 1 edge
    exists. Iterative DFS from every source over adjacency lists."""
    a = np.asarray(adj, dtype=bool)
    n = a.shape[0]
    if a.shape != (n, n):
        raise ValueError(f"adjacency must be square, got {a.shape}")
    out = np.zeros((n, n), dtype=bool)
    if n == 0:
        return out
    succs = [np.flatnonzero(a[i]).tolist() for i in range(n)]
    for src in range(n):
        seen = out[src]
        # Seed with src's direct successors, then walk: standard
        # explicit-stack DFS (no recursion limit at n=512+).
        stack = [v for v in succs[src] if not seen[v]]
        for v in stack:
            seen[v] = True
        while stack:
            u = stack.pop()
            for v in succs[u]:
                if not seen[v]:
                    seen[v] = True
                    stack.append(v)
    return out


def reach_batch(adjs, max_steps=None, time_limit=None) -> list:
    """Closure of each adjacency matrix in `adjs`, aligned with the
    input. Signature matches the supervisor engine-runner convention
    (checker/supervisor.py): budgets are accepted for uniformity — the
    host walk is exact and terminates without them."""
    return [reach(a) for a in adjs]


def cyclic_nodes(reach_m: np.ndarray) -> np.ndarray:
    """Indices of nodes lying on at least one cycle (diagonal of the
    path closure)."""
    return np.flatnonzero(np.diagonal(reach_m))


def same_scc(reach_m: np.ndarray) -> np.ndarray:
    """Pairwise strongly-connected-component membership: i and j share
    an SCC iff each reaches the other (a node shares with itself only
    when it is on a cycle — consistent with the irreflexive closure;
    callers wanting reflexive SCCs OR in the identity)."""
    return reach_m & reach_m.T


def shortest_cycle_path(adj: np.ndarray, start: int, goal: int) -> list | None:
    """Shortest path start -> goal over `adj` (BFS), as a node list
    [start, ..., goal]; None when unreachable. With start == goal this
    finds the shortest nontrivial cycle through the node. Used by the
    anomaly classifier to recover a concrete witness cycle on the host
    once the closure engines have flagged an SCC."""
    a = np.asarray(adj, dtype=bool)
    n = a.shape[0]
    prev = np.full(n, -1, dtype=np.int64)
    frontier = [int(v) for v in np.flatnonzero(a[start])]
    for v in frontier:
        prev[v] = start
    visited = np.zeros(n, dtype=bool)
    visited[frontier] = True
    while frontier and not visited[goal]:
        nxt = []
        for u in frontier:
            for v in np.flatnonzero(a[u]):
                if not visited[v]:
                    visited[v] = True
                    prev[v] = u
                    nxt.append(int(v))
        frontier = nxt
    if not visited[goal]:
        return None
    path = [goal]
    while path[-1] != start or len(path) == 1:
        p = int(prev[path[-1]])
        path.append(p)
        if p == start:
            break
    return path[::-1]
