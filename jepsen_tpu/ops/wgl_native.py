"""Native (C++) Wing–Gong–Lowe search: the GIL-free host engine for
models with int32 kernel encodings (native/wgl_search.cpp). Same
algorithm and verdicts as ops/wgl_host.py; roughly two orders of
magnitude faster than the pure-Python fallback, which matters exactly
where the TPU kernel doesn't apply (no accelerator attached, or payload
shapes the kernel codec rejects are absent but the device is).

The shared library is compiled on first use with the toolchain the
environment guarantees (g++), cached next to the source keyed by a
source hash — the same compile-on-demand posture as the on-node clock
tools (nemesis/time.py)."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

import numpy as np

from ..history import Entries, entries as make_entries
from ..models import Model
from ..models import jit as mjit
from .wgl_host import WGLResult

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                           "native")
_SOURCE = os.path.join(_NATIVE_DIR, "wgl_search.cpp")

_MODEL_KINDS = {
    "cas-register": 0,
    "register": 1,
    "mutex": 2,
    "unordered-queue": 3,
    "fifo-queue": 4,
}

_lock = threading.Lock()
_lib = None


class NativeUnavailable(Exception):
    """No compiler, or the model/history has no native encoding."""


def _build_lib():
    with open(_SOURCE, "rb") as fh:
        digest = hashlib.sha256(fh.read()).hexdigest()[:16]
    cache_dir = os.path.join(tempfile.gettempdir(), "jepsen-tpu-native")
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"libwglsearch-{digest}.so")
    if not os.path.exists(so_path):
        tmp = so_path + f".tmp{os.getpid()}"
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SOURCE],
                check=True, capture_output=True, text=True,
            )
        except (OSError, subprocess.CalledProcessError) as e:
            raise NativeUnavailable(
                f"can't build native search: {e}") from e
        os.replace(tmp, so_path)
    lib = ctypes.CDLL(so_path)
    lib.wgl_search.restype = ctypes.c_longlong
    lib.wgl_search.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.c_int32, ctypes.c_int,
        ctypes.c_longlong, ctypes.c_double,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_longlong),
    ]
    return lib


def _get_lib():
    global _lib
    with _lock:
        if _lib is None:
            _lib = _build_lib()
        return _lib


def _resolve(model: Model, es: Entries):
    """The JitModel for (model, es), or None — one eligibility scan."""
    jm = mjit.for_model(model)
    if jm is None or jm.name not in _MODEL_KINDS \
            or not jm.lane_eligible(es):
        return None
    return jm


def eligible(model: Model, es: Entries) -> bool:
    return _resolve(model, es) is not None


def analysis(
    model: Model,
    history,
    time_limit: float | None = None,
    max_steps: int | None = None,
) -> WGLResult:
    """Check linearizability with the native engine. Raises
    NativeUnavailable when the model/history has no native encoding or
    no compiler exists — callers fall back to the host search."""
    es = history if isinstance(history, Entries) else make_entries(history)
    jm = _resolve(model, es)  # one scan: eligibility + model resolution
    if jm is None:
        raise NativeUnavailable(f"no native encoding for {model!r}")
    lib = _get_lib()

    n = len(es)
    if es.n_completed == 0:
        return WGLResult(valid=True, final_state=model)

    f, v1, v2 = jm.encode_lane(es)
    crashed = np.ascontiguousarray(es.crashed, np.uint8)
    call_pos = np.ascontiguousarray(es.call_pos, np.int64)
    ret_pos = np.ascontiguousarray(es.ret_pos, np.int64)

    width = jm.lane_width(es)
    init_state = int(jm.init_vec(max(1, width))[0])

    out_valid = ctypes.c_int(2)
    out_stuck = ctypes.c_int(-1)
    out_best = (ctypes.c_int * max(1, n))()
    out_best_len = ctypes.c_int(0)
    out_cache = ctypes.c_longlong(0)

    def ptr(arr, ctype):
        return arr.ctypes.data_as(ctypes.POINTER(ctype))

    steps = lib.wgl_search(
        n,
        ptr(f, ctypes.c_int32), ptr(v1, ctypes.c_int32),
        ptr(v2, ctypes.c_int32), ptr(crashed, ctypes.c_uint8),
        ptr(call_pos, ctypes.c_int64), ptr(ret_pos, ctypes.c_int64),
        _MODEL_KINDS[jm.name], init_state, max(1, width),
        # None disables a budget (sentinel -1); explicit values are
        # clamped at 0 so an overshot (negative) budget means "already
        # expired" exactly like wgl_host, never "unbounded"
        ctypes.c_longlong(-1 if max_steps is None else max(0, max_steps)),
        ctypes.c_double(-1.0 if time_limit is None
                        else max(0.0, time_limit)),
        ctypes.byref(out_valid), ctypes.byref(out_stuck),
        out_best, ctypes.byref(out_best_len), ctypes.byref(out_cache),
    )

    best = [es.invokes[out_best[i]] for i in range(out_best_len.value)]
    if out_valid.value == 1:
        return WGLResult(valid=True, best_linearization=best,
                         cache_size=out_cache.value, steps=int(steps))
    if out_valid.value == 0:
        op = (es.invokes[out_stuck.value]
              if out_stuck.value >= 0 else None)
        return WGLResult(valid=False, op=op, best_linearization=best,
                         cache_size=out_cache.value, steps=int(steps))
    return WGLResult(valid="unknown", cache_size=out_cache.value,
                     steps=int(steps))


def analysis_batch(
    model: Model,
    entries_list,
    max_steps: int | None = None,
    time_limit: float | None = None,
    max_workers: int = 16,
) -> list[WGLResult]:
    """Check many independent histories with the native engine, fanned
    over a thread pool (ctypes drops the GIL for the search's duration,
    so lanes genuinely run in parallel on multi-core control nodes —
    the reference's bounded-pmap per-key checking,
    independent.clj:269-287). Raises NativeUnavailable when the library
    won't build or ANY lane has no native encoding: the supervised
    ladder (checker/supervisor.py) treats that as "demote the chunk",
    keeping this engine's contract all-or-nothing per call."""
    ess = [es if isinstance(es, Entries) else make_entries(es)
           for es in entries_list]
    _get_lib()  # raises NativeUnavailable without a toolchain
    for i, es in enumerate(ess):
        if not eligible(model, es):
            raise NativeUnavailable(
                f"lane {i} has no native encoding for {model!r}")

    def one(es):
        return analysis(model, es, time_limit=time_limit,
                        max_steps=max_steps)

    workers = min(len(ess), os.cpu_count() or 1, max_workers)
    if workers > 1 and len(ess) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as pool:
            return list(pool.map(one, ess))
    return [one(es) for es in ess]


def probe() -> bool:
    """Compile the library and run one trivial lane end-to-end. The
    supervisor's first-compile probe runs this in a subprocess so a
    toolchain crash is contained (checker/supervisor.py)."""
    from ..history import Op
    from ..models import CASRegister

    h = [Op(0, "invoke", "write", 1, time=0, index=0),
         Op(0, "ok", "write", 1, time=1, index=1)]
    return analysis(CASRegister(None), h, max_steps=10_000).valid is True


def check(model: Model, history, **kw) -> dict:
    return analysis(model, history, **kw).to_dict()
