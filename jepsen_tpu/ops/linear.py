"""Just-in-time linearization over configurations — the second
linearizability algorithm (parity target: knossos.linear/analysis,
invoked from the reference's checker.clj:126; SURVEY.md §2.2).

This is Lowe's "configurations" algorithm and is genuinely different
from the WGL depth-first search in ops/wgl_host.py / ops/wgl_tpu.py: it
sweeps the history's call/return events IN ORDER ONCE, carrying the set
of all distinguishable configurations — (model state, set of pending
ops linearized early) pairs — and only linearizes operations when a
return forces it ("just in time"). A history that defeats WGL's search
order (deep backtracking) often falls to the configuration sweep, and
vice versa; racing the two is what makes the competition checker real
(knossos.competition parity, checker.clj:125).

Semantics match the WGL engines: failed ops are excluded before the
sweep, crashed (:info) ops stay pending forever — available, never
required. A history is linearizable iff a configuration survives every
return event.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Any

from ..history import Entries, Op, entries as make_entries
from ..models import Model, inconsistent

#: truncation for result artifacts (checker.clj:138-141)
MAX_CONFIGS_REPORTED = 10

DEFAULT_MAX_CONFIGS = 2_000_000


@dataclass
class LinearResult:
    valid: Any  # True | False | "unknown"
    op: Op | None = None  # the op at whose return every config died
    configs: list = field(default_factory=list)  # surviving/last configs
    cache_size: int = 0  # peak live configuration count
    steps: int = 0  # model.step invocations
    best_linearization: list | None = None  # kept None: not a DFS path

    def to_dict(self) -> dict:
        d = {"valid": self.valid}
        if self.op is not None:
            d["op"] = self.op.to_dict()
        if self.configs:
            d["configs"] = self.configs
        d["cache_size"] = self.cache_size
        d["steps"] = self.steps
        return d


def _config_dicts(configs, es: Entries) -> list:
    """Human-readable configurations, truncated (checker.clj:138-141)."""
    out = []
    for m, linset in list(configs)[:MAX_CONFIGS_REPORTED]:
        out.append({
            "model": str(m),
            "linearized_pending": [es.invokes[i].to_dict()
                                   for i in sorted(linset)],
        })
    return out


def analysis(
    model: Model,
    history,
    time_limit: float | None = None,
    max_configs: int = DEFAULT_MAX_CONFIGS,
) -> LinearResult:
    """Sweep the history once, maintaining all reachable configurations.

    Returns LinearResult with valid in {True, False, "unknown"} —
    "unknown" when the live configuration set exceeds max_configs or the
    time budget runs out (knossos's :unknown analog)."""
    es = history if isinstance(history, Entries) else make_entries(history)
    n = len(es)
    if es.n_completed == 0:
        return LinearResult(valid=True, configs=[{"model": str(model),
                                                  "linearized_pending": []}])

    # Events in real-time order. Crashed entries' returns are at
    # +infinity (positions past every real event) — skip them: a crashed
    # op simply never forces linearization.
    events: list[tuple[int, bool, int]] = []  # (pos, is_call, entry)
    for e in range(n):
        events.append((int(es.call_pos[e]), True, e))
        if not es.crashed[e]:
            events.append((int(es.ret_pos[e]), False, e))
    events.sort()

    fs = es.f
    vals = es.value_out

    deadline = None if time_limit is None else _time.monotonic() + time_limit
    steps = 0
    peak = 1

    # A configuration is (model, frozenset of open ops linearized early).
    configs: set = {(model, frozenset())}
    open_ops: set = set()

    for pos, is_call, e in events:
        if is_call:
            open_ops.add(e)
            continue

        # Return of e: every surviving configuration must have e
        # linearized. Expand just-in-time: from each config, linearize
        # any valid sequence of pending ops ending with e. Iterative
        # worklist (crash-heavy histories can have thousands of pending
        # ops — recursion would blow the stack) with budget checks in
        # the loop (a single expansion can be exponential on its own).
        open_ops.discard(e)
        new_configs: set = set()
        work: list = list(configs)
        seen: set = set(work)  # dedupe expansion states
        iters = 0
        while work:
            iters += 1
            if len(seen) + len(new_configs) > max_configs:
                return LinearResult(valid="unknown", cache_size=peak,
                                    steps=steps)
            if (deadline is not None and iters % 512 == 0
                    and _time.monotonic() > deadline):
                return LinearResult(valid="unknown", cache_size=peak,
                                    steps=steps)
            m, linset = work.pop()
            if e in linset:
                new_configs.add((m, linset - {e}))
                continue
            # linearize e now...
            steps += 1
            m2 = m.step(fs[e], vals[e])
            if not inconsistent(m2):
                new_configs.add((m2, linset))
            # ...or linearize some other pending op first, then retry.
            for o in open_ops:
                if o in linset:
                    continue
                steps += 1
                m3 = m.step(fs[o], vals[o])
                if inconsistent(m3):
                    continue
                key = (m3, linset | {o})
                if key in seen:
                    continue
                seen.add(key)
                work.append(key)
        if deadline is not None and _time.monotonic() > deadline:
            return LinearResult(valid="unknown", cache_size=peak, steps=steps)

        if not new_configs:
            return LinearResult(
                valid=False,
                op=es.invokes[e],
                configs=_config_dicts(configs, es),
                cache_size=peak,
                steps=steps,
            )
        configs = new_configs
        peak = max(peak, len(configs))

    return LinearResult(
        valid=True,
        configs=_config_dicts(configs, es),
        cache_size=peak,
        steps=steps,
    )


def check(model: Model, history, **kw) -> dict:
    return analysis(model, history, **kw).to_dict()
