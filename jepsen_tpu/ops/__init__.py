"""Analysis kernels.

wgl_host — Wing-Gong-Lowe linearizability search on host (semantics
          oracle + fallback for models without int32 encodings).
wgl_tpu  — the same search as a jitted bitmask-DFS over int32 tensors,
          vmapped over independent keys and sharded over a device mesh.

Importing a KERNEL module (wgl_tpu / wgl_pallas / wgl_pallas_vec)
configures JAX's persistent compilation cache before any kernel
compiles: search-kernel variants cost seconds to tens of seconds of
XLA/Mosaic compile each, and a fresh process pays all of them again
without a disk cache. The package import itself stays jax-free so
pure-host consumers (wgl_host, the control plane) don't pay a jax
import. Override the location with JEPSEN_TPU_COMPILE_CACHE (set to
"off" to disable)."""

import os as _os


def _configure_compilation_cache() -> None:
    ours = _os.environ.get("JEPSEN_TPU_COMPILE_CACHE")
    # precedence: our env var > the standard JAX env var (this jax
    # version does not read it itself, so apply the user's value for
    # them) > a dir the application configured before import > default
    path = ours or _os.environ.get("JAX_COMPILATION_CACHE_DIR") \
        or _os.path.join(
            _os.path.expanduser("~"), ".cache", "jepsen-tpu", "xla-cache")
    if path.lower() in ("", "0", "off", "none"):
        return
    try:
        import jax

        if (ours is None
                and _os.environ.get("JAX_COMPILATION_CACHE_DIR") is None
                and jax.config.jax_compilation_cache_dir):
            return  # application already configured a cache dir
        jax.config.update("jax_compilation_cache_dir", path)
        # search kernels recompile per shape bucket; even small entries
        # are worth keeping, and ~0.5s is well under a kernel compile
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception:  # noqa: BLE001 — older jax or read-only home
        pass
