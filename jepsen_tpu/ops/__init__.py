"""Analysis kernels.

wgl_host — Wing-Gong-Lowe linearizability search on host (semantics
          oracle + fallback for models without int32 encodings).
wgl_tpu  — the same search as a jitted bitmask-DFS over int32 tensors,
          vmapped over independent keys and sharded over a device mesh.
"""
