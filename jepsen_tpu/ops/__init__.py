"""Analysis kernels.

wgl_host — Wing-Gong-Lowe linearizability search on host (semantics
          oracle + fallback for models without int32 encodings).
wgl_tpu  — the same search as a jitted bitmask-DFS over int32 tensors,
          vmapped over independent keys and sharded over a device mesh.

Importing a KERNEL module (wgl_tpu / wgl_pallas / wgl_pallas_vec)
configures JAX's persistent compilation cache before any kernel
compiles: search-kernel variants cost seconds to tens of seconds of
XLA/Mosaic compile each, and a fresh process pays all of them again
without a disk cache. The package import itself stays jax-free so
pure-host consumers (wgl_host, the control plane) don't pay a jax
import. Override the location with JEPSEN_TPU_COMPILE_CACHE (set to
"off" to disable)."""

import os as _os

#: the smallest shape bucket every kernel pads to — one uint32 word of
#: packed columns for the closure engines, and the minimum history pad
#: the search kernels compile for
MIN_PAD = 32


def next_pow2(x: int) -> int:
    """Smallest power of two >= x (minimum 2)."""
    return 1 << max(1, int(max(2, x) - 1).bit_length())


def pad_size(n: int, min_pad: int = MIN_PAD) -> int:
    """The shared shape-bucketing rule: pad to a power of two, floor
    `min_pad`. Both the WGL search kernels (history length) and the
    closure engines (adjacency side) bucket by this so variable-size
    work maps onto a handful of compiled shapes."""
    return max(min_pad, next_pow2(n))


def configure_compilation_cache(path=None, force=False):
    """Point JAX's persistent compilation cache somewhere useful.

    With no arguments this is the import-time default wiring: our env
    var > the standard JAX env var (this jax version does not read it
    itself, so apply the user's value for them) > a dir the
    application configured before import > the per-user default.  An
    explicit ``path`` (the AOT engine bundle pins the cache inside the
    bundle directory so warm starts hit exactly the compiles the
    bundle stamped) takes precedence over everything when ``force`` is
    set, and over everything but an operator env var otherwise.
    Returns the directory in effect, or None when caching is off or
    jax is unavailable."""
    ours = _os.environ.get("JEPSEN_TPU_COMPILE_CACHE")
    if force and path:
        chosen = path
    else:
        chosen = ours or path \
            or _os.environ.get("JAX_COMPILATION_CACHE_DIR") \
            or _os.path.join(
                _os.path.expanduser("~"), ".cache", "jepsen-tpu",
                "xla-cache")
    if str(chosen).lower() in ("", "0", "off", "none"):
        return None
    try:
        import jax

        if (not force and path is None and ours is None
                and _os.environ.get("JAX_COMPILATION_CACHE_DIR") is None
                and jax.config.jax_compilation_cache_dir):
            # application already configured a cache dir
            return jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", str(chosen))
        # search kernels recompile per shape bucket; even small entries
        # are worth keeping, and ~0.5s is well under a kernel compile
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
        return str(chosen)
    except Exception:  # noqa: BLE001 — older jax or read-only home
        return None


def _configure_compilation_cache() -> None:
    """Import-time hook the kernel modules call (kept under the
    historical private name so their import sites stay unchanged)."""
    configure_compilation_cache()
