"""A hermetic Aerospike lookalike: speaks the v2/type-3 message wire
(aerospike_proto's subset) — reads return (generation, bins), writes
bump generation, GENERATION_EQUAL writes fail with result code 3 on a
mismatch. Records keyed by digest hex in the shared flock store."""

from __future__ import annotations

import argparse
import random
import socketserver
import struct
import sys
import time

from . import aerospike_proto as ap
from .simbase import Store, build_sim_archive


class Handler(socketserver.BaseRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client went away")
            buf += chunk
        return buf

    def handle(self):
        self.request.settimeout(120.0)
        try:
            while True:
                header = self._read_exact(8)
                length = int.from_bytes(header[2:8], "big")
                payload = self._read_exact(length)
                if self.mean_latency > 0:
                    time.sleep(random.expovariate(1.0 / self.mean_latency))
                reply = self._dispatch(payload)
                self.request.sendall(
                    struct.pack(">BB", 2, 3)
                    + len(reply).to_bytes(6, "big") + reply)
        except (ConnectionError, TimeoutError, OSError, struct.error):
            return

    @staticmethod
    def _parse(payload: bytes) -> tuple:
        (hdr_sz, info1, info2, _i3, _unused, _res, generation, _ttl,
         _txn, n_fields, n_ops) = struct.unpack(">BBBBBBIIIHH",
                                                payload[:22])
        pos = hdr_sz
        digest = b""
        for _ in range(n_fields):
            (size,) = struct.unpack_from(">I", payload, pos)
            ftype = payload[pos + 4]
            data = payload[pos + 5:pos + 4 + size]
            if ftype == ap.FIELD_DIGEST:
                digest = data
            pos += 4 + size
        ops = []
        for _ in range(n_ops):
            (size,) = struct.unpack_from(">I", payload, pos)
            op_type, btype, _ver, name_len = struct.unpack_from(
                ">BBBB", payload, pos + 4)
            name = payload[pos + 8:pos + 8 + name_len].decode()
            value = payload[pos + 8 + name_len:pos + 4 + size]
            ops.append((op_type, btype, name, value))
            pos += 4 + size
        return info1, info2, generation, digest.hex(), ops

    @staticmethod
    def _reply(result: int, generation: int = 0,
               bins: dict | None = None) -> bytes:
        op_blobs = []
        for name, (btype, data) in (bins or {}).items():
            nb = name.encode()
            body = struct.pack(">BBBB", ap.OP_READ, btype, 0,
                               len(nb)) + nb + data
            op_blobs.append(struct.pack(">I", len(body)) + body)
        body = struct.pack(
            ">BBBBBBIIIHH", 22, 0, 0, 0, 0, result, generation, 0, 0, 0,
            len(op_blobs))
        return body + b"".join(op_blobs)

    def _dispatch(self, payload: bytes) -> bytes:
        info1, info2, generation, digest, ops = self._parse(payload)
        if info1 & ap.INFO1_READ:
            def read(data):
                return (data.get("records") or {}).get(digest), None

            rec = self.store.transact(read)
            if rec is None:
                return self._reply(ap.RESULT_NOT_FOUND)
            bins = {name: (btype, bytes.fromhex(vhex))
                    for name, (btype, vhex) in rec["bins"].items()}
            return self._reply(ap.RESULT_OK, rec["generation"], bins)

        if info2 & ap.INFO2_WRITE:
            def write(data):
                records = dict(data.get("records") or {})
                rec = records.get(digest)
                if info2 & ap.INFO2_GENERATION:
                    cur = rec["generation"] if rec else 0
                    if cur != generation:
                        return ap.RESULT_GENERATION, None
                new_bins = dict(rec["bins"]) if rec else {}
                for op_type, btype, name, value in ops:
                    if op_type == ap.OP_WRITE:
                        new_bins[name] = (btype, value.hex())
                    elif op_type == ap.OP_APPEND:
                        old = new_bins.get(name)
                        prior = bytes.fromhex(old[1]) if old else b""
                        new_bins[name] = (btype, (prior + value).hex())
                records[digest] = {
                    "generation": (rec["generation"] + 1) if rec else 1,
                    "bins": new_bins,
                }
                new = dict(data)
                new["records"] = records
                return ap.RESULT_OK, new

            result = self.store.transact(write)
            return self._reply(result)
        return self._reply(ap.RESULT_OK)


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def parse_args(argv):
    p = argparse.ArgumentParser(description="aerospike wire sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=3000)
    p.add_argument("--name", default="sim")
    p.add_argument("--config-file", default=None)  # asd flag, tolerated
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    srv = Server(("127.0.0.1", args.port), Handler)
    print(f"aerospike-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.aerospike_sim", "asd", "aerospike-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
