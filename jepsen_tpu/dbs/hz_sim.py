"""A hermetic Hazelcast lookalike: an HTTP/JSON server exposing the
distributed data structures the hazelcast suite drives — queue, lock,
atomic long, atomic reference, id-generator, and maps (reference
behavior: /root/reference/hazelcast/src/jepsen/hazelcast.clj:155-346 —
cited for parity, not copied; the reference uses the Hazelcast Java
client against a JVM server, this speaks plain HTTP).

Like etcd_sim/zk_sim, every member process shares one flock-guarded
JSON state file, so the simulated cluster is linearizable by
construction; a --mean-latency knob adds exponential jitter so recorded
histories have real concurrency windows.

Semantics matched to Hazelcast's structures:
  - queue: FIFO put / poll-with-timeout (IQueue.put / IQueue.poll)
  - lock:  tryLock(wait-ms) with session ownership + reentrancy count,
           unlock by non-owner is an IllegalMonitorState error
  - atomic-long: incrementAndGet
  - atomic-ref:  get / compareAndSet
  - id-gen: block-allocated ids — each server process claims blocks of
            BLOCK ids from shared state and hands them out locally
            (unique but non-contiguous, like IdGenerator)
  - map: get / putIfAbsent / replace(key, old, new)
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .simbase import Store, build_sim_archive

ID_BLOCK = 10_000


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    protocol_version = "HTTP/1.1"

    # id-generator block state, local to this server process
    _id_lock = threading.Lock()
    _id_next = 0
    _id_limit = 0

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _jitter(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))

    def _reply(self, status: int, body: dict):
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _error(self, status: int, kind: str, message: str = ""):
        self._reply(status, {"error": kind, "message": message})

    # -- dispatch ---------------------------------------------------------

    def do_POST(self):
        self._jitter()
        length = int(self.headers.get("Content-Length") or 0)
        try:
            req = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            return self._error(400, "bad-json")
        parts = [p for p in self.path.split("/") if p]
        if len(parts) != 2:
            return self._error(404, "no-route", self.path)
        kind, verb = parts
        name = f"op_{kind}_{verb}".replace("-", "_")
        handler = getattr(self, name, None)
        if handler is None:
            return self._error(404, "no-route", self.path)
        handler(req)

    def do_GET(self):
        if self.path == "/health":
            return self._reply(200, {"status": "ok"})
        self._error(404, "no-route", self.path)

    # -- queue ------------------------------------------------------------

    def op_queue_put(self, req):
        name, value = req.get("name", "default"), req["value"]

        def put(data):
            qs = dict(data.get("queues") or {})
            qs[name] = list(qs.get(name) or []) + [value]
            new = dict(data)
            new["queues"] = qs
            return None, new

        self.store.transact(put)
        self._reply(200, {"ok": True})

    def op_queue_poll(self, req):
        name = req.get("name", "default")
        timeout_ms = req.get("timeout_ms", 0)
        deadline = time.monotonic() + timeout_ms / 1000.0

        def poll(data):
            q = list((data.get("queues") or {}).get(name) or [])
            if not q:
                return None, None
            head, rest = q[0], q[1:]
            new = dict(data)
            qs = dict(new.get("queues") or {})
            qs[name] = rest
            new["queues"] = qs
            return head, new

        while True:
            got = self.store.transact(poll)
            if got is not None or time.monotonic() >= deadline:
                return self._reply(200, {"value": got})
            time.sleep(0.001)

    # -- lock -------------------------------------------------------------

    def op_lock_acquire(self, req):
        name = req.get("name", "default")
        session = req["session"]
        timeout_ms = req.get("timeout_ms", 0)
        deadline = time.monotonic() + timeout_ms / 1000.0

        def try_lock(data):
            locks = dict(data.get("locks") or {})
            cur = locks.get(name)
            if cur is None or cur["owner"] == session:
                locks[name] = {"owner": session,
                               "count": (cur["count"] + 1) if cur else 1}
                new = dict(data)
                new["locks"] = locks
                return True, new
            return False, None

        while True:
            if self.store.transact(try_lock):
                return self._reply(200, {"acquired": True})
            if time.monotonic() >= deadline:
                return self._reply(200, {"acquired": False})
            time.sleep(0.005)

    def op_lock_release(self, req):
        name = req.get("name", "default")
        session = req["session"]

        def unlock(data):
            locks = dict(data.get("locks") or {})
            cur = locks.get(name)
            if cur is None or cur["owner"] != session:
                return False, None
            if cur["count"] > 1:
                locks[name] = {"owner": session, "count": cur["count"] - 1}
            else:
                del locks[name]
            new = dict(data)
            new["locks"] = locks
            return True, new

        if self.store.transact(unlock):
            return self._reply(200, {"released": True})
        # Hazelcast throws IllegalMonitorStateException here
        self._error(409, "not-lock-owner",
                    "Current thread is not owner of the lock!")

    # -- atomic long ------------------------------------------------------

    def op_atomic_long_inc(self, req):
        name = req.get("name", "default")

        def inc(data):
            longs = dict(data.get("atomic_longs") or {})
            v = int(longs.get(name) or 0) + 1
            longs[name] = v
            new = dict(data)
            new["atomic_longs"] = longs
            return v, new

        self._reply(200, {"value": self.store.transact(inc)})

    # -- atomic reference -------------------------------------------------

    def op_atomic_ref_get(self, req):
        name = req.get("name", "default")

        def get(data):
            return (data.get("atomic_refs") or {}).get(name), None

        self._reply(200, {"value": self.store.transact(get)})

    def op_atomic_ref_cas(self, req):
        name = req.get("name", "default")
        old, new_v = req.get("old"), req.get("new")

        def cas(data):
            refs = dict(data.get("atomic_refs") or {})
            if refs.get(name) != old:
                return False, None
            refs[name] = new_v
            new = dict(data)
            new["atomic_refs"] = refs
            return True, new

        self._reply(200, {"swapped": self.store.transact(cas)})

    # -- id generator -----------------------------------------------------

    def op_id_gen_new(self, req):
        cls = type(self)
        with cls._id_lock:
            if cls._id_next >= cls._id_limit:
                def claim(data):
                    base = int(data.get("id_gen_block") or 0)
                    new = dict(data)
                    new["id_gen_block"] = base + 1
                    return base * ID_BLOCK, new

                cls._id_next = self.store.transact(claim)
                cls._id_limit = cls._id_next + ID_BLOCK
            v = cls._id_next
            cls._id_next += 1
        self._reply(200, {"value": v})

    # -- map --------------------------------------------------------------

    def op_map_get(self, req):
        name, key = req.get("name", "default"), str(req["key"])

        def get(data):
            return ((data.get("maps") or {}).get(name) or {}).get(key), None

        self._reply(200, {"value": self.store.transact(get)})

    def op_map_put_if_absent(self, req):
        name, key = req.get("name", "default"), str(req["key"])
        value = req["value"]

        def pia(data):
            maps = dict(data.get("maps") or {})
            m = dict(maps.get(name) or {})
            if key in m:
                return m[key], None  # existing value, no write
            m[key] = value
            maps[name] = m
            new = dict(data)
            new["maps"] = maps
            return None, new

        self._reply(200, {"previous": self.store.transact(pia)})

    def op_map_replace(self, req):
        name, key = req.get("name", "default"), str(req["key"])
        old, new_v = req["old"], req["new"]

        def rep(data):
            maps = dict(data.get("maps") or {})
            m = dict(maps.get(name) or {})
            if m.get(key) != old:
                return False, None
            m[key] = new_v
            maps[name] = m
            new = dict(data)
            new["maps"] = maps
            return True, new

        self._reply(200, {"replaced": self.store.transact(rep)})


def parse_args(argv):
    p = argparse.ArgumentParser(description="hazelcast-like sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=5701)
    p.add_argument("--name", default="sim")
    p.add_argument("--members", default=None)  # tolerated, unused
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    httpd = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"hz-sim {args.name} serving on {args.port}, data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    """A hazelcast-server-shaped tar.gz whose binary launches this sim
    (installed through the suite's normal install_archive path)."""
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.hz_sim", "hazelcast-server",
        "hazelcast-sim", data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
