"""Dgraph workloads: bank, delete, sequential, linearizable-register,
and long-fork — the transactional suites of the reference
(/root/reference/dgraph/src/jepsen/dgraph/{bank,delete,sequential,
linearizable_register,long_fork}.clj), driven through the MVCC txn
layer in dgraph.py/dgraph_sim.py.

Shapes mirrored from the reference:

- bank stripes keys/amounts/types across PRED_COUNT predicates
  (bank.clj:14-15) so the tablet-mover nemesis splits accounts across
  groups; zero-balance accounts are deleted and recreated on demand
  (bank.clj:85-99's write-account!).
- delete checks that index reads never surface half-deleted records
  (delete.clj:66-89).
- sequential restricts txns to read-only or write-your-full-read-set,
  then requires per-process monotonic register observations
  (sequential.clj:1-49).
- linearizable-register is the stock per-key CAS register bundle with
  reads-as-fail-on-timeout (linearizable_register.clj:24-31).
- long-fork is the stock incompatible-snapshot-order workload over
  single-key write txns (long_fork.clj via dgraph/long_fork.clj:1-8).
"""

from __future__ import annotations

import itertools
import logging
import random
import socket
import threading
import urllib.error

from .. import checker as checker_mod
from .. import generator as gen, independent, trace
from .. import client as client_mod
from ..checker import Checker
from ..history import Op, ops as _ops
from ..workloads import bank as bank_wl
from ..workloads import linearizable_register as lr_wl
from ..workloads import long_fork as lf_wl
from .. import txn as mop
from .dgraph import (DgraphConn, DgraphError, TxnConflict, node_host,
                     node_port, with_conflict_as_fail, with_txn)

log = logging.getLogger("jepsen_tpu.dbs.dgraph")

NETWORK_ERRORS = (socket.timeout, TimeoutError, urllib.error.URLError,
                  ConnectionError, OSError)

PRED_COUNT = 3  # bank.clj:14-15


def gen_pred(prefix: str, k: int) -> str:
    """Predicate for key k, striped across PRED_COUNT predicates
    (client.clj's gen-pred)."""
    return f"{prefix}_{k % PRED_COUNT}"


def gen_preds(prefix: str) -> list:
    return [f"{prefix}_{i}" for i in range(PRED_COUNT)]


def _open_conn(test, node) -> DgraphConn:
    return DgraphConn(node_host(test, node), node_port(test, node))


def _upsert_directive(test) -> str:
    """' @upsert' when the test runs with the upsert schema (the
    reference's --upsert-schema option, on by default here: without it
    concurrent insert-if-absent races produce duplicate records, e.g.
    bank.clj:111-117, linearizable_register.clj:40-43)."""
    return " @upsert" if test.get("upsert_schema", True) else ""


def _complete(op: Op, body, read_only: bool) -> Op:
    """Shared completion taxonomy for every transactional client:
    conflicts are safe :fail (the txn did not apply,
    client.clj:105-167); other errors :fail for idempotent read-only
    ops and :info (indeterminate) for writes
    (linearizable_register.clj:24-31's read-info->fail)."""

    def run():
        try:
            return body()
        except TxnConflict:
            raise  # with_conflict_as_fail's job (subclass of DgraphError)
        except (DgraphError, *NETWORK_ERRORS) as e:
            crash = "fail" if read_only else "info"
            return op.with_(type=crash, error=str(e))

    return with_conflict_as_fail(op, run)


# ---------------------------------------------------------------------------
# Bank (bank.clj)


def _acct_row_to_key_amount(row: dict) -> tuple:
    """{'key_0': 1, 'amount_2': 5, ...} -> (1, 5)
    (bank.clj:17-34's multi-pred-acct->key+amount)."""
    key = amount = None
    for pred, v in row.items():
        if pred.startswith("key_"):
            assert key is None, f"multiple keys in {row!r}"
            key = v
        elif pred.startswith("amount_"):
            assert amount is None, f"multiple amounts in {row!r}"
            amount = v
    return key, amount


class BankClient(client_mod.Client):
    """Striped-predicate bank accounts (bank.clj:36-180)."""

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        return BankClient(_open_conn(test, node))

    def setup(self, test):
        with trace.with_trace("bank.setup"):
            up = _upsert_directive(test)
            schema = "".join(
                f"{p}: int @index(int){up} .\n" for p in gen_preds("key")
            ) + "".join(
                f"{p}: string @index(exact) .\n" for p in gen_preds("type")
            ) + "".join(
                f"{p}: int .\n" for p in gen_preds("amount"))
            self.conn.alter(schema)
            # Seed the whole total into the first account
            # (bank.clj:130-141); races between clients are benign.
            k = test["accounts"][0]
            try:
                with with_txn(self.conn) as t:
                    if not t.query(self._key_query(k, with_amount=False)):
                        t.mutate(sets=[self._record(
                            k, test["total_amount"])])
            except TxnConflict:
                pass

    @staticmethod
    def _record(k: int, amount: int, uid: str | None = None) -> dict:
        rec = {gen_pred("key", k): k,
               gen_pred("type", k): "account",
               gen_pred("amount", k): amount}
        if uid is not None:
            rec["uid"] = uid
        return rec

    @staticmethod
    def _key_query(k: int, with_amount: bool = True) -> str:
        kp, ap = gen_pred("key", k), gen_pred("amount", k)
        fields = f"uid {kp} {ap}" if with_amount else "uid"
        return f"{{ q(func: eq({kp}, {k})) {{ {fields} }} }}"

    def _find_account(self, t, k: int) -> dict:
        """{'uid'?, 'key', 'amount'} — a fresh zero account when absent
        (bank.clj:60-82)."""
        rows = t.query(self._key_query(k))
        if rows:
            key, amount = _acct_row_to_key_amount(rows[0])
            return {"uid": rows[0]["uid"], "key": key, "amount": amount}
        return {"key": k, "amount": 0}

    def _write_account(self, t, acct: dict) -> None:
        """Zero-balance accounts are deleted; others written back
        (bank.clj:85-99)."""
        if acct["amount"] == 0 and acct.get("uid"):
            t.mutate(dels=[{"uid": acct["uid"]}])
        elif acct["amount"] != 0:
            t.mutate(sets=[self._record(
                acct["key"], acct["amount"], acct.get("uid"))])

    def _read_accounts(self, t) -> dict:
        """All accounts across every type predicate (bank.clj:36-58)."""
        fields = " ".join(["uid"] + gen_preds("key") + gen_preds("amount"))
        out = {}
        for tp in gen_preds("type"):
            rows = t.query(
                f'{{ q(func: eq({tp}, "account")) {{ {fields} }} }}')
            for row in rows:
                key, amount = _acct_row_to_key_amount(row)
                if key is not None:
                    out[key] = amount
        return out

    def invoke(self, test, op: Op) -> Op:
        def body():
            if op.f == "read":
                with with_txn(self.conn) as t:
                    val = self._read_accounts(t)
                return op.with_(type="ok", value=val)
            if op.f == "transfer":
                v = op.value
                t = self.conn.txn()
                try:
                    frm = self._find_account(t, v["from"])
                    to = self._find_account(t, v["to"])
                    frm = {**frm, "amount": frm["amount"] - v["amount"]}
                    to = {**to, "amount": to["amount"] + v["amount"]}
                    if frm["amount"] < 0:
                        # Insufficient funds: abort, nothing applied
                        # (bank.clj:176-180 backs the txn out).
                        return op.with_(type="fail",
                                        error="insufficient-funds")
                    self._write_account(t, frm)
                    self._write_account(t, to)
                    t.commit()
                    return op.with_(type="ok")
                finally:
                    t.discard()
            raise ValueError(f"unknown op {op.f!r}")

        with trace.with_trace("bank.invoke"):
            return _complete(op, body, read_only=op.f == "read")

    def close(self, test):
        pass


def bank_workload(opts: dict) -> dict:
    n = opts.get("accounts", 5)
    total = opts.get("total_amount", 100)
    return {
        "name": "bank",
        "client": BankClient(),
        "during": gen.stagger(opts.get("stagger", 0.05),
                              bank_wl.generator()),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "bank": bank_wl.checker(),
            "plot": bank_wl.plotter(),
        }),
        "test_opts": {"accounts": list(range(n)),
                      "total_amount": total,
                      "max_transfer": opts.get("max_transfer", 5)},
    }


# ---------------------------------------------------------------------------
# Delete (delete.clj)


class DeleteClient(client_mod.Client):
    """Upsert/delete/read of indexed records per key (delete.clj:23-64);
    values are independent (k, v) tuples."""

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        conn = _open_conn(test, node)
        conn.alter(f"key: int @index(int){_upsert_directive(test)} .")
        return DeleteClient(conn)

    def invoke(self, test, op: Op) -> Op:
        k = op.value[0] if isinstance(op.value, tuple) else op.value

        def body():
            if op.f == "read":
                with with_txn(self.conn) as t:
                    rows = t.query(
                        f"{{ q(func: eq(key, {k})) {{ uid key }} }}")
                return op.with_(type="ok",
                                value=independent.tuple_(k, rows))
            if op.f == "upsert":
                with with_txn(self.conn) as t:
                    uids = t.mutate(
                        sets=[{"key": k}],
                        query=f"{{ v(func: eq(key, {k})) {{ uid }} }}",
                        cond="@if(eq(len(v), 0))")
                if not uids:
                    return op.with_(type="fail", error="present")
                return op.with_(type="ok")
            if op.f == "delete":
                with with_txn(self.conn) as t:
                    rows = t.query(
                        f"{{ q(func: eq(key, {k})) {{ uid }} }}")
                    if not rows:
                        return op.with_(type="fail", error="not-found")
                    t.mutate(dels=[{"uid": rows[0]["uid"]}])
                return op.with_(type="ok")
            raise ValueError(f"unknown op {op.f!r}")

        return _complete(op, body, read_only=op.f == "read")

    def close(self, test):
        pass


class DeleteChecker(Checker):
    """Every ok read sees nothing, or exactly one {uid, key} record for
    its key (delete.clj:66-89)."""

    def check(self, test, history, opts=None) -> dict:
        k = (opts or {}).get("history_key")
        bad = []
        for o in _ops(history):
            if not (o.is_ok and o.f == "read"):
                continue
            rows = o.value[1] if isinstance(o.value, tuple) else o.value
            if len(rows) == 0:
                continue
            if (len(rows) == 1 and set(rows[0]) == {"uid", "key"}
                    and (k is None or rows[0]["key"] == k)):
                continue
            bad.append(o.to_dict())
        return {"valid": not bad, "bad_reads": bad}


def _d_r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def _d_u(test, process):
    return {"type": "invoke", "f": "upsert", "value": None}


def _d_d(test, process):
    return {"type": "invoke", "f": "delete", "value": None}


def delete_workload(opts: dict) -> dict:
    n = len(opts["nodes"])
    return {
        "name": "delete",
        "client": DeleteClient(),
        "during": independent.concurrent_generator(
            2 * n, itertools.count(),
            lambda k: gen.limit(
                opts.get("ops_per_key", 1000),
                gen.stagger(0.01, gen.mix([_d_r, _d_u, _d_d])))),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "deletes": independent.checker(checker_mod.compose({
                "deletes": DeleteChecker(),
                "timeline": checker_mod.timeline_html(),
            })),
        }),
    }


# ---------------------------------------------------------------------------
# Sequential (sequential.clj)


class SequentialClient(client_mod.Client):
    """Read-only txns and read-inc-write txns on per-key counters;
    values are (k, observed-count) tuples (sequential.clj:66-105)."""

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        conn = _open_conn(test, node)
        conn.alter(f"key: int @index(int){_upsert_directive(test)} .\n"
                   "value: int @index(int) .\n")
        return SequentialClient(conn)

    def invoke(self, test, op: Op) -> Op:
        k = op.value[0] if isinstance(op.value, tuple) else op.value

        def body():
            with with_txn(self.conn) as t:
                rows = t.query(
                    f"{{ q(func: eq(key, {k})) {{ uid value }} }}")
                if op.f == "inc":
                    value = (rows[0].get("value", 0) if rows else 0) + 1
                    if rows:
                        t.mutate(sets=[{"uid": rows[0]["uid"],
                                        "value": value}])
                    else:
                        t.mutate(sets=[{"key": k, "value": value}])
                    return op.with_(type="ok",
                                    value=independent.tuple_(k, value))
                if op.f == "read":
                    value = rows[0].get("value", 0) if rows else 0
                    return op.with_(type="ok",
                                    value=independent.tuple_(k, value))
            raise ValueError(f"unknown op {op.f!r}")

        return _complete(op, body, read_only=op.f == "read")

    def close(self, test):
        pass


def non_monotonic_pairs(history) -> list:
    """Same-process consecutive ok ops where the observed register
    value decreased (sequential.clj:107-124). Values may be (k, count)
    tuples, or bare counts inside an independent subhistory."""
    last: dict = {}
    bad = []
    for o in _ops(history):
        if not o.is_ok:
            continue
        v = o.value[1] if isinstance(o.value, tuple) else o.value
        if not isinstance(v, int):
            continue
        prev = last.get(o.process)
        if prev is not None and v < prev[0]:
            bad.append([prev[1], o.to_dict()])
        last[o.process] = (v, o.to_dict())
    return bad


class SequentialChecker(Checker):
    """Per-process monotonicity of observed counts
    (sequential.clj:126-141)."""

    def check(self, test, history, opts=None) -> dict:
        bad = non_monotonic_pairs(history)
        return {"valid": not bad, "non_monotonic": bad}


def _s_inc(test, process):
    return {"type": "invoke", "f": "inc", "value": None}


def _s_read(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def sequential_workload(opts: dict) -> dict:
    n = len(opts["nodes"])
    return {
        "name": "sequential",
        "client": SequentialClient(),
        "during": independent.concurrent_generator(
            n, itertools.count(),
            lambda k: gen.limit(
                opts.get("ops_per_key", 500),
                gen.stagger(0.01, gen.mix([_s_inc, _s_read])))),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "sequential": independent.checker(checker_mod.compose({
                "sequential": SequentialChecker(),
                "timeline": checker_mod.timeline_html(),
            })),
        }),
    }


# ---------------------------------------------------------------------------
# Linearizable register (linearizable_register.clj)


class LrClient(client_mod.Client):
    """Single key/value predicates, read/write/cas in a txn
    (linearizable_register.clj:33-67). Read timeouts demote :info to
    :fail — reads are idempotent (linearizable_register.clj:24-31)."""

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        conn = _open_conn(test, node)
        conn.alter(f"key: int @index(int){_upsert_directive(test)} .\n"
                   "value: int .\n")
        return LrClient(conn)

    def _read(self, t, k: int) -> dict | None:
        rows = t.query(f"{{ q(func: eq(key, {k})) {{ uid value }} }}")
        assert len(rows) < 2, f"multiple records for key {k}: {rows!r}"
        return rows[0] if rows else None

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value

        def body():
            with with_txn(self.conn) as t:
                if op.f == "read":
                    rec = self._read(t, k)
                    return op.with_(
                        type="ok",
                        value=independent.tuple_(
                            k, rec.get("value") if rec else None))
                if op.f == "write":
                    rec = self._read(t, k)
                    if rec:
                        t.mutate(sets=[{"uid": rec["uid"], "value": v}])
                    else:
                        t.mutate(sets=[{"key": k, "value": v}])
                    return op.with_(type="ok")
                if op.f == "cas":
                    expect, new = v
                    rec = self._read(t, k)
                    if not rec or rec.get("value") != expect:
                        return op.with_(type="fail",
                                        error="value-mismatch")
                    t.mutate(sets=[{"uid": rec["uid"], "value": new}])
                    return op.with_(type="ok")
            raise ValueError(f"unknown op {op.f!r}")

        return _complete(op, body, read_only=op.f == "read")

    def close(self, test):
        pass


def lr_workload(opts: dict) -> dict:
    wl = lr_wl.test(opts)
    return {
        "name": "linearizable-register",
        "client": LrClient(),
        "during": gen.stagger(0.01, wl["generator"]),
        "model": wl["model"],
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "register": wl["checker"],
        }),
    }


# ---------------------------------------------------------------------------
# Long fork (long_fork.clj via dgraph/long_fork.clj)


class LongForkClient(client_mod.Client):
    """Executes [f k v] micro-op txns: single-key write txns and
    multi-key read txns, all in one dgraph transaction."""

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        conn = _open_conn(test, node)
        conn.alter(f"key: int @index(int){_upsert_directive(test)} .\n"
                   "value: int .\n")
        return LongForkClient(conn)

    def invoke(self, test, op: Op) -> Op:
        def body():
            with with_txn(self.conn) as t:
                out = []
                for m in op.value:
                    if mop.is_write(m):
                        rows = t.query(
                            f"{{ q(func: eq(key, {mop.key(m)}))"
                            " { uid } }")
                        sets = [{"key": mop.key(m), "value": mop.value(m)}]
                        if rows:
                            sets[0]["uid"] = rows[0]["uid"]
                        t.mutate(sets=sets)
                        out.append(m)
                    else:
                        rows = t.query(
                            f"{{ q(func: eq(key, {mop.key(m)}))"
                            " { value } }")
                        val = rows[0].get("value") if rows else None
                        out.append([mop.READ, mop.key(m), val])
            return op.with_(type="ok", value=out)

        return _complete(op, body,
                         read_only=all(mop.is_read(m) for m in op.value))

    def close(self, test):
        pass


def long_fork_workload(opts: dict) -> dict:
    wl = lf_wl.workload(opts.get("long_fork_n", 2))
    return {
        "name": "long-fork",
        "client": LongForkClient(),
        "during": gen.stagger(0.01, wl["generator"]),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "long-fork": wl["checker"],
        }),
    }


# ---------------------------------------------------------------------------
# Types (types.clj): type safety & integer overflow hunting


def type_cases() -> list:
    """[attribute, value] pairs sweeping the integer boundaries where
    type systems break (types.clj:137-165): ranges around byte/short/
    int/long maxima (positive and negative), the largest exactly-
    float/double-representable integers, and values well outside
    signed 64-bit."""
    interesting = [
        0,
        (1 << 7) - 1,        # Byte/MAX_VALUE
        (1 << 15) - 1,       # Short/MAX_VALUE
        (1 << 31) - 1,       # Integer/MAX_VALUE
        (1 << 63) - 1,       # Long/MAX_VALUE
        16777217,            # largest exact-float int + 1
        9007199254740993,    # largest exact-double int + 1
        3 * ((1 << 63) - 1),  # well outside signed longs
    ]
    values: list = []
    for x in interesting:
        values.extend(range(x - 8, x + 8))
        values.extend(range(-x - 8, -x + 8))
    # nsect-style probe between two near-Long.MAX points
    lo, hi = 9223372036854775293, 9223372036854775299
    values.extend(lo + (hi - lo) * i // 15 for i in range(16))
    seen: set = set()
    out = []
    for a in ("foo", "int64"):
        for v in values:
            if (a, v) not in seen:
                seen.add((a, v))
                out.append([a, v])
    return out


class TypesClient(client_mod.Client):
    """Writes entity-attribute-value triples and reads them back by
    uid (types.clj:24-57). Values are [e, a, v] triples; writes create
    fresh entities and complete with the assigned uid."""

    def __init__(self, conn=None, entities=None):
        self.conn = conn
        self.entities = entities if entities is not None else []

    def open(self, test, node):
        conn = _open_conn(test, node)
        # 'foo' is deliberately schemaless; only int64 declares a type
        # (types.clj:29-30)
        conn.alter("int64: int .\n")
        return TypesClient(conn, self.entities)

    def invoke(self, test, op: Op) -> Op:
        e, a, v = op.value

        def body():
            with with_txn(self.conn) as t:
                if op.f == "write":
                    uids = t.mutate(sets=[{a: v}])
                    uid = next(iter(uids.values()))
                    # record the attribute too: the final phase reads
                    # each entity under the one attribute it was
                    # written with, not the full cross product
                    self.entities.append((uid, a))
                    return op.with_(type="ok", value=[uid, a, v])
                if op.f == "read":
                    rows = t.query(
                        f"{{ q(func: uid({e})) {{ {a} }} }}")
                    got = rows[0].get(a) if rows else None
                    return op.with_(type="ok", value=[e, a, got])
            raise ValueError(f"unknown op {op.f!r}")

        return _complete(op, body, read_only=op.f == "read")

    def close(self, test):
        pass


class TypesChecker(Checker):
    """Everything written must read back EXACTLY (types.clj:59-125):
    errs collect (entity, attribute, wrote, read) mismatches — the
    signature of float64 coercion or int64 overflow; writes that were
    never successfully read make the verdict unknown, not valid."""

    def check(self, test, history, opts=None) -> dict:
        state: dict = {}
        dup_writes = []
        for o in _ops(history):
            if o.is_ok and o.f == "write":
                e, a, v = o.value
                if (e, a) in state:
                    # the reference assert+'s here; a checker must
                    # never crash on the anomaly it hunts — report it
                    dup_writes.append({"entity": e, "attribute": a})
                    continue
                state[(e, a)] = v
        read_state: dict = {}
        inconsistent = []
        errs = []
        for o in _ops(history):
            if not (o.is_ok and o.f == "read"):
                continue
            e, a, v = o.value
            prev = read_state.get((e, a), v)
            if prev != v:
                # two ok reads of the same (entity, attribute) that
                # disagree — e.g. a stale replica under a nemesis
                inconsistent.append({"entity": e, "attribute": a,
                                     "reads": sorted({str(prev),
                                                      str(v)})})
            read_state[(e, a)] = v
            if (e, a) in state and v != state[(e, a)]:
                errs.append({"entity": e, "attribute": a,
                             "wrote": state[(e, a)], "read": v})
        unread = sorted(
            (str(k) for k in set(state) - set(read_state)))
        mapping: dict = {}
        for (e, a), wrote in state.items():
            mapping.setdefault(a, {})[str(wrote)] = \
                read_state.get((e, a))
        errs = [dict(t) for t in
                {tuple(sorted(x.items())) for x in errs}]
        return {
            "valid": (False if errs or inconsistent or dup_writes
                      else "unknown" if unread else True),
            "error_count": len(errs),
            "unread_count": len(unread),
            "errors": sorted(errs, key=str)[:32],
            "inconsistent_reads": inconsistent[:32],
            "duplicate_writes": dup_writes[:32],
            "unread": unread[:32],
            "mapping": {a: dict(sorted(m.items())[:16])
                        for a, m in sorted(mapping.items())},
        }


def types_workload(opts: dict) -> dict:
    cases = type_cases()
    if opts.get("type_cases"):
        # stride-sample so a bounded run still sweeps the whole
        # boundary spectrum (small ints AND beyond-double values)
        n = opts["type_cases"]
        stride = max(1, len(cases) // n)
        cases = cases[::stride][:n]
    client = TypesClient()
    entities = client.entities

    final_cache: list = []
    final_lock = threading.Lock()

    def final():
        # derefer calls per op request; build once (delay semantics,
        # types.clj:176-188) — 3 read passes, dgraph "likes to stop
        # taking writes just cuz"
        with final_lock:
            if not final_cache:
                reads = [{"type": "invoke", "f": "read",
                          "value": [e, a, None]}
                         for _ in range(3)
                         for e, a in list(entities)]
                random.shuffle(reads)
                final_cache.append(
                    gen.stagger(0.01, gen.seq(reads)))
            return final_cache[0]

    return {
        "name": "types",
        "client": client,
        "during": gen.stagger(
            0.01,
            gen.seq({"type": "invoke", "f": "write",
                     "value": [None, a, v]} for a, v in cases)),
        "final": gen.derefer(final),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "types": TypesChecker(),
        }),
    }


class UidLrClient(client_mod.Client):
    """The uid-variant register client (linearizable_register.clj:
    80-150): keys map to uids through a client-side shared map instead
    of an @upsert index, avoiding the false linearization points index
    conflicts could introduce. A write that loses the uid-creation
    race completes :fail :lost-uid-race — its value will never be
    read."""

    def __init__(self, conn=None, uids=None, lock=None):
        self.conn = conn
        self.uids = uids if uids is not None else {}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        conn = _open_conn(test, node)
        conn.alter("value: int .\n")
        return UidLrClient(conn, self.uids, self.lock)

    def _uid_read(self, t, k):
        with self.lock:
            u = self.uids.get(k)
        if u is None:
            return None
        rows = t.query(f"{{ q(func: uid({u})) {{ uid value }} }}")
        assert len(rows) < 2, rows
        return rows[0] if rows else None

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value

        def body():
            with with_txn(self.conn) as t:
                if op.f == "read":
                    rec = self._uid_read(t, k)
                    return op.with_(
                        type="ok",
                        value=independent.tuple_(
                            k, rec.get("value") if rec else None))
                if op.f == "write":
                    with self.lock:
                        u = self.uids.get(k)
                    if u is not None:
                        t.mutate(sets=[{"uid": u, "value": v}])
                        return op.with_(type="ok")
                    new_u = next(iter(
                        t.mutate(sets=[{"value": v}]).values()))
                    with self.lock:
                        # record iff nobody else won the race meanwhile
                        won = self.uids.setdefault(k, new_u) == new_u
                    if won:
                        return op.with_(type="ok")
                    return op.with_(type="fail", error="lost-uid-race")
                if op.f == "cas":
                    expect, new = v
                    rec = self._uid_read(t, k)
                    if rec is None:
                        return op.with_(type="fail", error="not-found")
                    if rec.get("value") != expect:
                        return op.with_(type="fail",
                                        error="value-mismatch")
                    t.mutate(sets=[{"uid": rec["uid"], "value": new}])
                    return op.with_(type="ok")
            raise ValueError(f"unknown op {op.f!r}")

        return _complete(op, body, read_only=op.f == "read")

    def close(self, test):
        pass


def uid_lr_workload(opts: dict) -> dict:
    """linearizable_register.clj:152-160's uid-workload: the stock
    per-key register bundle over UidLrClient, with the reference's
    larger per-key budget."""
    wl = lr_wl.test({**opts, "per_key_limit":
                     opts.get("per_key_limit", 1024)})
    return {
        "name": "uid-linearizable-register",
        "client": UidLrClient(),
        "during": gen.stagger(0.05, wl["generator"]),
        "model": wl["model"],
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "register": wl["checker"],
        }),
    }
