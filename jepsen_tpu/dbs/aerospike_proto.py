"""Minimal Aerospike wire protocol — the transport for the aerospike
suite's cas-register and counter workloads (the reference drives the
Java client, aerospike/src/aerospike/support.clj; the semantics that
matter are generation-checked writes: read returns (generation, bins),
write can demand GENERATION_EQUAL and fails with result code 3 on a
lost race).

Message layout (v2 type-3 'message' protos):
  proto header: version(1)=2, type(1)=3, length(6, big-endian)
  msg header:   header_sz(1)=22, info1, info2, info3, unused,
                result_code, generation(u32), record_ttl(u32),
                transaction_ttl(u32), n_fields(u16), n_ops(u16)
  fields:       size(u32 incl. type byte), type(1), data
                (0=namespace, 1=set, 4=ripemd160 key digest)
  ops:          size(u32), op(1) (1=read, 2=write), bin_type(1),
                version(1), name_len(1), name, value

Integers travel as 8-byte big-endian bin type 1; blobs/strings as type
3/4 raw bytes. Key digest = RIPEMD160(set + type_byte + key-bytes).
"""

from __future__ import annotations

import hashlib
import socket
import struct

INFO1_READ = 0x01
INFO1_GET_ALL = 0x02
INFO2_WRITE = 0x01
INFO2_GENERATION = 0x04   # write iff generation matches

FIELD_NAMESPACE = 0
FIELD_SET = 1
FIELD_DIGEST = 4

OP_READ = 1
OP_WRITE = 2
OP_APPEND = 9

BIN_TYPE_INTEGER = 1
BIN_TYPE_STRING = 3

RESULT_OK = 0
RESULT_NOT_FOUND = 2
RESULT_GENERATION = 3


class AerospikeError(Exception):
    def __init__(self, code: int, message: str = ""):
        super().__init__(message or f"result code {code}")
        self.code = code


def key_digest(set_name: str, key) -> bytes:
    """RIPEMD160 over set + key-type byte + key bytes (the client
    contract every aerospike driver implements)."""
    if isinstance(key, int):
        kb = b"\x01" + struct.pack(">q", key)
    else:
        kb = b"\x03" + str(key).encode()
    return hashlib.new("ripemd160", set_name.encode() + kb).digest()


def _field(ftype: int, data: bytes) -> bytes:
    return struct.pack(">IB", len(data) + 1, ftype) + data


def _encode_bin_value(v) -> tuple:
    if isinstance(v, int):
        return BIN_TYPE_INTEGER, struct.pack(">q", v)
    return BIN_TYPE_STRING, str(v).encode()


def _op(op_type: int, name: str, value=None) -> bytes:
    nb = name.encode()
    if value is None:
        body = struct.pack(">BBBB", op_type, 0, 0, len(nb)) + nb
    else:
        btype, vb = _encode_bin_value(value)
        body = struct.pack(">BBBB", op_type, btype, 0, len(nb)) + nb + vb
    return struct.pack(">I", len(body)) + body


def decode_bin(btype: int, data: bytes):
    if btype == BIN_TYPE_INTEGER:
        return struct.unpack(">q", data)[0]
    return data.decode(errors="replace")


def build_message(info1: int, info2: int, generation: int,
                  fields: list, ops: list) -> bytes:
    body = struct.pack(
        ">BBBBBBIIIHH", 22, info1, info2, 0, 0, 0, generation, 0, 1000,
        len(fields), len(ops))
    body += b"".join(fields) + b"".join(ops)
    return struct.pack(">BB", 2, 3) + len(body).to_bytes(6, "big") + body


def parse_message(payload: bytes) -> tuple:
    """(result_code, generation, bins, n_fields_skipped)."""
    (hdr_sz, _i1, _i2, _i3, _unused, result, generation, _ttl, _txn,
     n_fields, n_ops) = struct.unpack(">BBBBBBIIIHH", payload[:22])
    pos = hdr_sz
    for _ in range(n_fields):
        (size,) = struct.unpack_from(">I", payload, pos)
        pos += 4 + size
    bins = {}
    for _ in range(n_ops):
        (size,) = struct.unpack_from(">I", payload, pos)
        op_type, btype, _ver, name_len = struct.unpack_from(
            ">BBBB", payload, pos + 4)
        name = payload[pos + 8:pos + 8 + name_len].decode()
        value = payload[pos + 8 + name_len:pos + 4 + size]
        bins[name] = decode_bin(btype, value) if value else None
        pos += 4 + size
    return result, generation, bins


class AerospikeConn:
    def __init__(self, host: str, port: int, namespace: str = "jepsen",
                 set_name: str = "jepsen", timeout: float = 5.0,
                 connect_timeout: float = 10.0):
        self.namespace = namespace
        self.set_name = set_name
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.settimeout(timeout)

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("aerospike connection closed")
            buf += chunk
        return buf

    def _roundtrip(self, msg: bytes) -> tuple:
        self.sock.sendall(msg)
        header = self._read_exact(8)
        version, mtype = header[0], header[1]
        length = int.from_bytes(header[2:8], "big")
        payload = self._read_exact(length)
        if version != 2 or mtype != 3:
            raise AerospikeError(-1, f"bad proto {version}/{mtype}")
        return parse_message(payload)

    def _key_fields(self, key) -> list:
        return [
            _field(FIELD_NAMESPACE, self.namespace.encode()),
            _field(FIELD_SET, self.set_name.encode()),
            _field(FIELD_DIGEST, key_digest(self.set_name, key)),
        ]

    def get(self, key) -> tuple:
        """(generation, bins) or (None, None) when absent."""
        msg = build_message(INFO1_READ | INFO1_GET_ALL, 0, 0,
                            self._key_fields(key), [])
        result, generation, bins = self._roundtrip(msg)
        if result == RESULT_NOT_FOUND:
            return None, None
        if result != RESULT_OK:
            raise AerospikeError(result)
        return generation, bins

    def put(self, key, bins: dict, expected_generation: int | None = None
            ) -> None:
        """Write bins; with expected_generation, demand
        GENERATION_EQUAL (raises AerospikeError code 3 on mismatch)."""
        info2 = INFO2_WRITE
        generation = 0
        if expected_generation is not None:
            info2 |= INFO2_GENERATION
            generation = expected_generation
        ops = [_op(OP_WRITE, name, v) for name, v in bins.items()]
        msg = build_message(0, info2, generation,
                            self._key_fields(key), ops)
        result, _gen, _bins = self._roundtrip(msg)
        if result != RESULT_OK:
            raise AerospikeError(result)

    def append(self, key, bins: dict) -> None:
        """Append to string bins (the set workload's primitive:
        aerospike/set.clj:35 appends \" v\" to one bin with s/append!)."""
        ops = [_op(OP_APPEND, name, v) for name, v in bins.items()]
        msg = build_message(0, INFO2_WRITE, 0,
                            self._key_fields(key), ops)
        result, _gen, _bins = self._roundtrip(msg)
        if result != RESULT_OK:
            raise AerospikeError(result)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
