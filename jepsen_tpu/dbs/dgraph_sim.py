"""A hermetic Dgraph lookalike: the HTTP API subset the dgraph suite
drives — /alter (schema accepted), /mutate with set-JSON and optional
upsert query+cond, /query with a tiny DQL subset (func: has(pred) |
eq(pred, val), fields uid + predicates), /health. Nodes are uid-keyed
predicate maps in the shared flock store; mutations are atomic under
the store lock, reproducing a serializable Zero."""

from __future__ import annotations

import argparse
import json
import random
import re
import sys
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .simbase import Store, build_sim_archive


def parse_func(query: str) -> tuple:
    """(func_name, pred, value|None, fields) from the one-block DQL
    shape `{ q(func: eq(value, 5)) { uid value } }`."""
    m = re.search(
        r"func:\s*(\w+)\s*\(\s*(\w+)\s*(?:,\s*([^)\s]+))?\s*\)", query)
    if not m:
        raise ValueError(f"can't parse query func: {query!r}")
    fm = re.search(r"\)\s*\)?\s*\{([^}]*)\}", query)
    fields = fm.group(1).split() if fm else ["uid"]
    value = m.group(3)
    if value is not None:
        value = value.strip("\"'")
        try:
            value = int(value)
        except ValueError:
            pass
    return m.group(1), m.group(2), value, fields


def run_query(data: dict, query: str) -> list:
    func, pred, value, fields = parse_func(query)
    nodes = data.get("nodes") or {}
    out = []
    for uid, preds in nodes.items():
        if func == "has" and pred not in preds:
            continue
        if func == "eq" and preds.get(pred) != value:
            continue
        row = {}
        for f in fields:
            if f == "uid":
                row["uid"] = uid
            elif f in preds:
                row[f] = preds[f]
        out.append(row)
    return out


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _reply(self, status: int, body: dict) -> None:
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        if urllib.parse.urlparse(self.path).path == "/health":
            return self._reply(200, {"status": "healthy"})
        self._reply(404, {"errors": [{"message": "no route"}]})

    def do_POST(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))
        path = urllib.parse.urlparse(self.path).path
        length = int(self.headers.get("Content-Length") or 0)
        try:
            body = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            return self._reply(400, {"errors": [{"message": "bad json"}]})
        if path == "/alter":
            return self._reply(200, {"data": {"code": "Success"}})
        if path == "/query":
            def rd(data):
                try:
                    return run_query(data, body["query"]), None
                except ValueError as e:
                    return e, None

            out = self.store.transact(rd)
            if isinstance(out, Exception):
                return self._reply(400, {"errors": [{"message": str(out)}]})
            return self._reply(200, {"data": {"q": out}})
        if path == "/mutate":
            return self._mutate(body)
        self._reply(404, {"errors": [{"message": "no route"}]})

    def _mutate(self, body: dict) -> None:
        sets = body.get("set") or []
        upsert_query = body.get("query")
        cond = body.get("cond")

        def mut(data):
            nodes = dict(data.get("nodes") or {})
            if upsert_query is not None:
                found = run_query(data, upsert_query)
                if cond is not None:
                    m = re.search(r"eq\(len\(\w+\),\s*(\d+)\)", cond)
                    want = int(m.group(1)) if m else 0
                    if len(found) != want:
                        return {"data": {"code": "Success",
                                         "uids": {}}}, None
            uids = {}
            counter = int(data.get("uid_counter") or 0)
            for i, triple in enumerate(sets):
                counter += 1
                uid = f"0x{counter:x}"
                nodes[uid] = {k: v for k, v in triple.items()
                              if k != "uid"}
                uids[f"blank-{i}"] = uid
            new = dict(data)
            new["nodes"], new["uid_counter"] = nodes, counter
            return {"data": {"code": "Success", "uids": uids}}, new

        self._reply(200, self.store.transact(mut))


def parse_args(argv):
    p = argparse.ArgumentParser(description="dgraph HTTP sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--name", default="sim")
    # dgraph alpha flags tolerated:
    p.add_argument("--zero", default=None)
    p.add_argument("--my", default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    httpd = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"dgraph-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.dgraph_sim", "dgraph", "dgraph-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
