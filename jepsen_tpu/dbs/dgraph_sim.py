"""A hermetic Dgraph lookalike: the HTTP API subset the dgraph suite
drives — /alter (schema accepted), /mutate with set/delete JSON and
optional upsert query+cond, /query with a tiny DQL subset (func:
has(pred) | eq(pred, val), fields uid + predicates), /commit, /health,
and /state (zero's group/tablet map, for the tablet-mover nemesis).

Storage is MVCC over the shared flock store, reproducing dgraph's
transaction model (reference client:
/root/reference/dgraph/src/jepsen/dgraph/client.clj:66-103):

- every node is a VERSION CHAIN [[commit_ts, preds-or-None], ...];
- a transaction's first request is assigned a start_ts and reads the
  snapshot as of that ts (snapshot isolation — reads may be stale but
  are internally consistent);
- /mutate?startTs=N&commitNow=false stages writes in the txn record;
- /commit?startTs=N detects write-write conflicts via CONFLICT KEYS —
  one per written uid plus one per written (predicate, value) pair,
  which is how dgraph's @upsert index directive turns concurrent
  insert-if-absent races into aborts — and answers HTTP 409
  "Transaction has been aborted. Please retry." like the real server;
- /mutate without startTs (or with commitNow=true) is a one-shot
  atomic transaction, preserving the non-transactional clients.
"""

from __future__ import annotations

import argparse
import json
import random
import re
import sys
import time
import urllib.parse
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .simbase import Store, build_sim_archive

ABORTED = "Transaction has been aborted. Please retry."


def parse_func(query: str) -> tuple:
    """(func_name, pred, value|None, fields) from the one-block DQL
    shape `{ q(func: eq(value, 5)) { uid value } }`."""
    m = re.search(
        r"func:\s*(\w+)\s*\(\s*(\w+)\s*(?:,\s*([^)\s]+))?\s*\)", query)
    if not m:
        raise ValueError(f"can't parse query func: {query!r}")
    fm = re.search(r"\)\s*\)?\s*\{([^}]*)\}", query)
    fields = fm.group(1).split() if fm else ["uid"]
    value = m.group(3)
    if value is not None:
        value = value.strip("\"'")
        try:
            value = int(value)
        except ValueError:
            pass
    return m.group(1), m.group(2), value, fields


def snapshot(data: dict, ts: int, overlay: dict | None = None) -> dict:
    """Materialize {uid: preds} as of commit-ts <= ts, with a txn's own
    staged writes overlaid (None = staged delete)."""
    view = {}
    for uid, chain in (data.get("nodes") or {}).items():
        preds = None
        for cts, p in chain:
            if cts <= ts:
                preds = p
            else:
                break
        if preds is not None:
            view[uid] = preds
    for uid, preds in (overlay or {}).items():
        if preds is None:
            view.pop(uid, None)
        else:
            view[uid] = preds
    return view


def run_query(view: dict, query: str) -> list:
    func, pred, value, fields = parse_func(query)
    out = []
    for uid, preds in view.items():
        if func == "has" and pred not in preds:
            continue
        if func == "eq" and preds.get(pred) != value:
            continue
        # uid(0x..) is single-argument: the uid lands in the pred slot
        if func == "uid" and uid != pred:
            continue
        row = {}
        for f in fields:
            if f == "uid":
                row["uid"] = uid
            elif f in preds:
                row[f] = preds[f]
        out.append(row)
    return out


INT64_MIN, INT64_MAX = -(1 << 63), (1 << 63) - 1


def json_number(v):
    """Dgraph's HTTP surface decodes JSON numbers the way Go's
    encoding/json does — through float64 — so integers beyond 2^53
    lose precision, and values whose float64 image falls outside int64
    convert the way amd64's cvttsd2si does: to INT64_MIN (the x86
    "integer indefinite"), NOT a clip to the nearest bound. Clipping
    would make exactly 2^63-1 round-trip cleanly (float rounds it up
    to 2^63, the clip brings it back) and hide the anomaly at the one
    boundary the dgraph `types` workload most wants to probe
    (types.clj:1-2)."""
    if isinstance(v, bool) or not isinstance(v, int):
        return v
    if -(1 << 53) <= v <= (1 << 53):
        return v
    as_float = float(v)
    if as_float >= float(1 << 63) or as_float < float(INT64_MIN):
        return INT64_MIN
    return int(as_float)


def conflict_keys(touched: dict, upsert_preds: set) -> list:
    """Conflict keys for a txn's EXPLICITLY-written triples: one per
    touched uid, plus one per (pred, value) pair whose predicate has
    the @upsert index directive — dgraph only materializes index-level
    conflicts for @upsert predicates, which is what turns concurrent
    insert-if-absent races into aborts without making every shared
    value a false conflict."""
    keys = []
    for uid, preds in touched.items():
        keys.append(f"u:{uid}")
        for p, v in (preds or {}).items():
            if p in upsert_preds:
                keys.append(f"pv:{p}={v!r}")
    return keys


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _reply(self, status: int, body: dict) -> None:
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        path = urllib.parse.urlparse(self.path).path
        if path == "/health":
            return self._reply(200, {"status": "healthy"})
        if path == "/state":
            # Zero's state: every predicate seen so far, assigned to
            # one of two groups by hash — enough surface for the
            # tablet-mover nemesis (dgraph/nemesis.clj:50-86).
            def rd(data):
                preds = set()
                for chain in (data.get("nodes") or {}).values():
                    for _, p in chain:
                        preds.update((p or {}).keys())
                moved = data.get("tablet_groups") or {}
                groups: dict = {"1": {"tablets": {}}, "2": {"tablets": {}}}
                for p in sorted(preds):
                    # Stable across processes and runs (hash() is
                    # PYTHONHASHSEED-randomized; the sim must be
                    # deterministic for every node process).
                    g = moved.get(p) or str(
                        1 + (zlib.crc32(p.encode()) % 2))
                    groups.setdefault(g, {"tablets": {}})
                    groups[g]["tablets"][p] = {
                        "predicate": p, "groupId": int(g)}
                return {"groups": groups,
                        "leader": data.get("leader") or "n1"}, None

            return self._reply(200, self.store.transact(rd))
        self._reply(404, {"errors": [{"message": "no route"}]})

    def do_POST(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path
        params = dict(urllib.parse.parse_qsl(parsed.query))
        length = int(self.headers.get("Content-Length") or 0)
        try:
            body = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            return self._reply(400, {"errors": [{"message": "bad json"}]})
        if path == "/alter":
            return self._alter(body)
        if path == "/query":
            return self._query(body, params)
        if path == "/mutate":
            return self._mutate(body, params)
        if path == "/commit":
            return self._commit(params)
        if path == "/moveTablet":
            return self._move_tablet(params)
        self._reply(404, {"errors": [{"message": "no route"}]})

    # -- transactional plumbing --------------------------------------

    @staticmethod
    def _txn(data: dict, start_ts: int) -> dict | None:
        return (data.get("txns") or {}).get(str(start_ts))

    def _alter(self, body: dict) -> None:
        """Record which predicates carry @upsert (used for index-level
        conflict keys); schemas merge like dgraph's alter."""
        schema = body.get("schema") or ""
        ups = [m.group(1)
               for m in re.finditer(r"(\w+)\s*:[^\n.]*@upsert", schema)]

        def al(data):
            if not ups:
                return {"data": {"code": "Success"}}, None
            new = dict(data)
            new["upsert_preds"] = sorted(
                set(new.get("upsert_preds") or []) | set(ups))
            return {"data": {"code": "Success"}}, new

        self._reply(200, self.store.transact(al))

    def _query(self, body: dict, params: dict) -> None:
        start_ts = int(params.get("startTs") or 0)
        transactional = "startTs" in params

        def rd(data):
            new = None
            if start_ts:
                ts = start_ts
            elif transactional:
                # startTs=0 from a txn's first contact: assign its
                # start_ts, like dgraph returns extensions.txn.start_ts.
                # No txn record yet — it's created lazily by the first
                # staged mutate, so read-only txns leave no garbage.
                ts = int(data.get("ts") or 0) + 1
                new = dict(data)
                new["ts"] = ts
            else:
                # Legacy non-transactional read: current snapshot, no
                # state write (the read hot path stays pure).
                ts = int(data.get("ts") or 0)
            # Only a transactional read may overlay staged writes — a
            # legacy read at ts == an open txn's start_ts must not see
            # that txn's uncommitted data.
            txn = (self._txn(data, ts) or {}) if transactional else {}
            view = snapshot(data, ts, txn.get("writes"))
            try:
                return (run_query(view, body["query"]), ts), new
            except ValueError as e:
                return (e, ts), new

        out, ts = self.store.transact(rd)
        if isinstance(out, Exception):
            return self._reply(400, {"errors": [{"message": str(out)}]})
        return self._reply(200, {
            "data": {"q": out},
            "extensions": {"txn": {"start_ts": ts}},
        })

    def _mutate(self, body: dict, params: dict) -> None:
        sets = body.get("set") or []
        dels = body.get("delete") or []
        upsert_query = body.get("query")
        cond = body.get("cond")
        start_ts = int(params.get("startTs") or 0)
        # Auto-commit when asked explicitly, or when the caller isn't
        # transactional at all (no startTs AND no commitNow param — the
        # legacy one-shot clients). startTs=0&commitNow=false is a
        # txn's FIRST staged mutate: assign its start_ts below.
        commit_now = (params.get("commitNow", "").lower() == "true"
                      or ("commitNow" not in params and not start_ts))

        def mut(data):
            new = dict(data)
            ts = start_ts
            if not ts:
                ts = int(data.get("ts") or 0) + 1
                new["ts"] = ts
            txns = dict(new.get("txns") or {})
            txn = dict(txns.get(str(ts)) or {"writes": {}, "touched": {}})
            writes = dict(txn["writes"])
            # touched = only the explicitly-written (pred, value) pairs
            # per uid — the conflict surface (merged old preds in
            # `writes` exist for MVCC visibility, not conflicts).
            touched = {u: dict(p) if p is not None else None
                       for u, p in (txn.get("touched") or {}).items()}
            view = snapshot(data, ts, writes)

            if upsert_query is not None:
                found = run_query(view, upsert_query)
                if cond is not None:
                    m = re.search(r"eq\(len\(\w+\),\s*(\d+)\)", cond)
                    want = int(m.group(1)) if m else 0
                    if len(found) != want:
                        return ({"data": {"code": "Success", "uids": {}},
                                 "extensions": {"txn": {"start_ts": ts}}},
                                new if new != data else None)

            uids = {}
            counter = int(new.get("uid_counter") or 0)
            for i, triple in enumerate(sets):
                uid = triple.get("uid")
                if uid is None:
                    counter += 1
                    uid = f"0x{counter:x}"
                    uids[f"blank-{i}"] = uid
                explicit = {k: json_number(v)
                            for k, v in triple.items() if k != "uid"}
                merged = dict(view.get(uid) or {})
                merged.update(explicit)
                writes[uid] = merged
                t = dict(touched.get(uid) or {})
                t.update(explicit)
                touched[uid] = t
            for triple in dels:
                uid = triple.get("uid")
                if uid is not None and uid in view:
                    writes[uid] = None
                    touched[uid] = None
            new["uid_counter"] = counter

            if commit_now:
                err, new2 = _apply_commit(new, ts, writes, touched)
                if err:
                    return ({"_status": 409,
                             "errors": [{"message": err}]}, None)
                # Commit-on-last-mutate finishes the txn: drop any
                # staged record so a later /commit can't replay it.
                if str(ts) in (new2.get("txns") or {}):
                    txns2 = dict(new2["txns"])
                    txns2.pop(str(ts))
                    new2 = dict(new2)
                    new2["txns"] = txns2
                return ({"data": {"code": "Success", "uids": uids},
                         "extensions": {"txn": {"start_ts": ts}}}, new2)
            txn["writes"] = writes
            txn["touched"] = touched
            txns[str(ts)] = txn
            new["txns"] = txns
            return ({"data": {"code": "Success", "uids": uids},
                     "extensions": {"txn": {"start_ts": ts}}}, new)

        out = self.store.transact(mut)
        status = out.pop("_status", 200)
        self._reply(status, out)

    def _commit(self, params: dict) -> None:
        start_ts = int(params.get("startTs") or 0)
        abort = params.get("abort", "").lower() == "true"

        def com(data):
            txns = dict(data.get("txns") or {})
            txn = txns.pop(str(start_ts), None)
            new = dict(data)
            new["txns"] = txns
            if txn is None or abort:
                # Read-only commit or abort/discard: both succeed (a
                # read-only txn has no record — see _query — and
                # dgraph's discard of a finished txn is a no-op).
                return ({"data": {"code": "Success"}}, new)
            err, new2 = _apply_commit(new, start_ts, txn["writes"],
                                      txn.get("touched") or txn["writes"])
            if err:
                return ({"_status": 409,
                         "errors": [{"message": err}]}, new)
            return ({"data": {"code": "Success"},
                     "extensions": {"txn": {"start_ts": start_ts,
                                            "commit_ts": new2["ts"]}}},
                    new2)

        out = self.store.transact(com)
        status = out.pop("_status", 200)
        self._reply(status, out)

    def _move_tablet(self, params: dict) -> None:
        pred = params.get("tablet")
        group = params.get("group")

        def mv(data):
            new = dict(data)
            moved = dict(new.get("tablet_groups") or {})
            moved[pred] = str(group)
            new["tablet_groups"] = moved
            return {"data": {"code": "Success",
                             "message": f"moved {pred} to {group}"}}, new

        self._reply(200, self.store.transact(mv))


def _apply_commit(data: dict, start_ts: int, writes: dict,
                  touched: dict):
    """Conflict-check the txn's explicit writes against commits after
    start_ts; on success append new versions at a fresh commit_ts.
    Returns (error-message-or-None, new-data)."""
    upsert_preds = set(data.get("upsert_preds") or [])
    ckeys = dict(data.get("ckeys") or {})
    keys = conflict_keys(touched, upsert_preds)
    for key in keys:
        if ckeys.get(key, 0) > start_ts:
            return ABORTED, None
    if not writes:
        return None, data
    commit_ts = int(data.get("ts") or 0) + 1
    new = dict(data)
    new["ts"] = commit_ts
    nodes = dict(new.get("nodes") or {})
    for uid, preds in writes.items():
        chain = list(nodes.get(uid) or [])
        chain.append([commit_ts, preds])
        nodes[uid] = chain
    new["nodes"] = nodes
    for key in keys:
        ckeys[key] = commit_ts
    new["ckeys"] = ckeys
    return None, new


def parse_args(argv):
    p = argparse.ArgumentParser(description="dgraph HTTP sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--name", default="sim")
    # dgraph alpha flags tolerated:
    p.add_argument("--zero", default=None)
    p.add_argument("--my", default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    httpd = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"dgraph-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.dgraph_sim", "dgraph", "dgraph-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
