"""Shared clients and helpers for the MySQL-protocol suites (galera,
percona, mysql-cluster, tidb). The reference repeats these clients per
suite (galera.clj:214-337, percona.clj, mysql_cluster.clj:100-180,
tidb/{bank,sets,register}.clj); here they're written once and
parameterized by each suite's SuiteCfg.

Shared failure taxonomy (galera.clj:120-187's with-error-handling /
with-txn-aborts): deadlock/txn-abort errors (1213) definitely did not
commit → :fail; duplicate keys :fail; timeouts and connection errors on
writes are :info; reads always :fail on error."""

from __future__ import annotations

import logging
import random
import socket
import time

from .. import client, generator as gen, reconnect
from ..checker import Checker
from ..history import Op, ops as _ops
from . import mysql_proto as mp
from .common import once as _once, shared_flag as _shared_flag

log = logging.getLogger("jepsen_tpu.dbs.mysql_common")


def probe_mysql_ready(suite, test, node) -> bool:
    """Shared readiness probe: the SQL port answers a trivial query
    (a server mid-startup can speak garbage; callers keep polling)."""
    try:
        conn = mp.MySqlConn(suite.host(test, node),
                            suite.port(test, node),
                            connect_timeout=2.0, timeout=2.0)
        try:
            conn.query("select 1")
            return True
        finally:
            conn.close()
    except (mp.MySqlError, mp.MySqlProtocolError):
        return False


def conn_wrapper(suite, test, node, user="jepsen", password="",
                 database="jepsen"):
    host, port = suite.host(test, node), suite.port(test, node)
    return reconnect.wrapper(
        open=lambda: mp.MySqlConn(host, port, user=user, password=password,
                                  database=database),
        close=lambda c: c.close(),
        name=f"{suite.name} {node}",
    ).open()


def txn_retry(body, attempts: int = 20, backoff: float = 0.02):
    """Retry deadlock aborts with backoff (galera.clj with-txn-retries)."""
    while True:
        try:
            return body()
        except mp.MySqlError as e:
            if not e.deadlock or attempts <= 0:
                raise
            attempts -= 1
            time.sleep(backoff)
            backoff *= 2


def exception_to_op(op: Op, e) -> Op | None:
    if isinstance(e, mp.MySqlError):
        if e.deadlock:
            return op.with_(type="fail", error=("txn-abort", str(e)))
        if e.code == mp.ER_DUP_ENTRY:
            return op.with_(type="fail", error="duplicate-key")
        crash = "fail" if op.f == "read" else "info"
        return op.with_(type=crash, error=str(e))
    if isinstance(e, (socket.timeout, TimeoutError)):
        return op.with_(type="fail" if op.f == "read" else "info",
                        error="timeout")
    if isinstance(e, (ConnectionError, mp.MySqlProtocolError, OSError)):
        return op.with_(type="fail" if op.f == "read" else "info",
                        error=str(e))
    return None


class _SqlClient(client.Client):
    """Base: reconnect-wrapped conn + exception taxonomy + txn
    bracket."""

    def __init__(self, suite, conn=None, flag=None):
        self.suite = suite
        self.conn = conn
        self.flag = flag or _shared_flag()

    def _clone(self, conn):
        out = type(self)(self.suite)
        out.__dict__.update(self.__dict__)
        out.conn = conn
        return out

    def open(self, test, node):
        return self._clone(conn_wrapper(self.suite, test, node))

    def _txn(self, c, body):
        c.query("begin")
        try:
            out = body()
        except BaseException:
            try:
                c.query("rollback")
            except (OSError, mp.MySqlError, mp.MySqlProtocolError):
                pass
            raise
        c.query("commit")
        return out

    def invoke(self, test, op: Op) -> Op:
        try:
            with self.conn.with_conn() as c:
                return self._invoke(c, test, op)
        except Exception as e:  # noqa: BLE001
            mapped = exception_to_op(op, e)
            if mapped is None:
                raise
            return mapped

    def _invoke(self, c, test, op: Op) -> Op:
        raise NotImplementedError

    def close(self, test):
        if self.conn:
            self.conn.close()


class BankClient(_SqlClient):
    """Account transfers in serializable transactions
    (galera.clj:260-309)."""

    def __init__(self, suite, n: int = 5, starting_balance: int = 10,
                 conn=None, flag=None):
        super().__init__(suite, conn, flag)
        self.n = n
        self.starting_balance = starting_balance

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                txn_retry(lambda: c.query("drop table if exists accounts"))
                txn_retry(lambda: c.query(
                    "create table accounts (id int not null primary key, "
                    "balance bigint not null)"))
                for i in range(self.n):
                    try:
                        txn_retry(lambda i=i: c.query(
                            f"insert into accounts (id, balance) values "
                            f"({i}, {self.starting_balance})"))
                    except mp.MySqlError as e:
                        if e.code != mp.ER_DUP_ENTRY:
                            raise

        _once(self.flag, create)

    def _invoke(self, c, test, op: Op) -> Op:
        def run():
            def body():
                if op.f == "read":
                    rows = c.query("select id, balance from accounts").rows
                    return op.with_(type="ok",
                                    value={int(i): int(b)
                                           for i, b in rows})
                frm, to = op.value["from"], op.value["to"]
                amount = op.value["amount"]
                b1 = int(c.query(
                    f"select balance from accounts where id = {frm}"
                ).scalars()[0]) - amount
                b2 = int(c.query(
                    f"select balance from accounts where id = {to}"
                ).scalars()[0]) + amount
                if b1 < 0:
                    return op.with_(type="fail", error=("negative", frm))
                if b2 < 0:
                    return op.with_(type="fail", error=("negative", to))
                c.query(f"update accounts set balance = {b1} "
                        f"where id = {frm}")
                c.query(f"update accounts set balance = {b2} "
                        f"where id = {to}")
                return op.with_(type="ok")

            return self._txn(c, body)

        return txn_retry(run, attempts=5)


class SetClient(_SqlClient):
    """Unique-int inserts + final whole-table read
    (galera.clj:214-258)."""

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                txn_retry(lambda: c.query("drop table if exists sets"))
                txn_retry(lambda: c.query(
                    "create table sets (val int primary key)"))

        _once(self.flag, create)

    def _invoke(self, c, test, op: Op) -> Op:
        if op.f == "add":
            txn_retry(lambda: c.query(
                f"insert into sets values ({op.value})"))
            return op.with_(type="ok")
        if op.f == "read":
            vals = sorted(int(v) for v in
                          c.query("select val from sets").scalars())
            return op.with_(type="ok", value=vals)
        raise ValueError(f"unknown op {op.f!r}")


class DirtyReadsClient(_SqlClient):
    """Writers set EVERY row to a unique value in one transaction;
    readers read every row. A failed write's value visible to a reader
    is a dirty read (galera/dirty_reads.clj:29-96)."""

    def __init__(self, suite, n: int = 4, conn=None, flag=None):
        super().__init__(suite, conn, flag)
        self.n = n

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                txn_retry(lambda: c.query("drop table if exists dirty"))
                txn_retry(lambda: c.query(
                    "create table dirty (id int not null primary key, "
                    "x bigint not null)"))
                for i in range(self.n):
                    try:
                        txn_retry(lambda i=i: c.query(
                            f"insert into dirty (id, x) values ({i}, -1)"))
                    except mp.MySqlError as e:
                        if e.code != mp.ER_DUP_ENTRY:
                            raise

        _once(self.flag, create)

    def _invoke(self, c, test, op: Op) -> Op:
        def body():
            if op.f == "read":
                xs = [int(x) for x in
                      c.query("select x from dirty").scalars()]
                return op.with_(type="ok", value=xs)
            if op.f == "write":
                order = list(range(self.n))
                random.shuffle(order)
                for i in order:
                    c.query(f"select x from dirty where id = {i}")
                for i in order:
                    c.query(f"update dirty set x = {op.value} "
                            f"where id = {i}")
                return op.with_(type="ok")
            raise ValueError(f"unknown op {op.f!r}")

        return self._txn(c, body)


class DirtyReadsChecker(Checker):
    """No failed write's value may appear in any read; reads must also
    be internally consistent (dirty_reads.clj:72-96)."""

    def check(self, test, history, opts=None) -> dict:
        failed = {o.value for o in _ops(history)
                  if o.is_fail and o.f == "write"}
        reads = [o.value for o in _ops(history)
                 if o.is_ok and o.f == "read"]
        inconsistent = [r for r in reads if len(set(r)) > 1]
        dirty = [r for r in reads if any(x in failed for x in r)]
        return {
            "valid": not dirty,
            "inconsistent_reads": inconsistent[:10],
            "dirty_reads": dirty[:10],
        }


class RegisterClient(_SqlClient):
    """tidb-style single-row CAS register (tidb/register.clj): read =
    select; write = upsert; cas = conditional UPDATE rowcount."""

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                txn_retry(lambda: c.query("drop table if exists test"))
                txn_retry(lambda: c.query(
                    "create table test (id int primary key, val int)"))

        _once(self.flag, create)

    def _invoke(self, c, test, op: Op) -> Op:
        if op.f == "read":
            vals = c.query("select val from test where id = 0").scalars()
            value = int(vals[0]) if vals and vals[0] is not None else None
            return op.with_(type="ok", value=value)
        if op.f == "write":
            def w():
                def body():
                    rows = c.query(
                        "select val from test where id = 0").rows
                    if rows:
                        c.query(f"update test set val = {op.value} "
                                "where id = 0")
                    else:
                        c.query(f"insert into test values (0, {op.value})")
                return self._txn(c, body)
            txn_retry(w)
            return op.with_(type="ok")
        if op.f == "cas":
            old, new = op.value
            n = txn_retry(lambda: c.query(
                f"update test set val = {new} "
                f"where id = 0 and val = {old}").rowcount)
            return op.with_(type="ok" if n else "fail")
        raise ValueError(f"unknown op {op.f!r}")


# ---------------------------------------------------------------------------
# Generators


def bank_read(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def bank_transfer(test, process):
    n = test.get("accounts_n", 5)
    return {"type": "invoke", "f": "transfer",
            "value": {"from": random.randrange(n),
                      "to": random.randrange(n),
                      "amount": 1 + random.randrange(5)}}


def bank_diff_transfer():
    return gen.filter_gen(
        lambda op: op["value"]["from"] != op["value"]["to"], bank_transfer)


# ---------------------------------------------------------------------------
# Suite factory: the four MySQL-protocol suites differ only in name,
# port, daemon launch flags, and workload selection.


def make_sql_suite(name: str, default_port: int, binary: str,
                   daemon_args_fn, workload_names: tuple,
                   display_name: str | None = None,
                   db_cls=None,
                   extra_nemeses=None,
                   extra_nemesis_names: tuple = ()):
    """Build (suite_cfg, DBClass, workloads_fn, test_fn, opt_spec) for a
    MySQL-protocol suite. db_cls overrides the default single-daemon
    ArchiveDB (tidb's triple, mysql-cluster's role split);
    extra_nemeses(db) -> dict merges suite-specific nemesis entries
    (component killers) into the shared registry, and
    extra_nemesis_names exposes them on the --nemesis flag."""
    from .. import checker as checker_mod
    from .. import models, osdist
    from .common import ArchiveDB, SuiteCfg

    suite = SuiteCfg(name, default_port, f"/opt/{name}")

    class DB(ArchiveDB):
        binary_name = binary
        log_name = f"{name}.log"
        pid_name = f"{name}.pid"

        def __init__(self, archive_url=None, ready_timeout=60.0):
            super().__init__(suite, archive_url, ready_timeout)
            self.binary = binary

        def daemon_args(self, test, node):
            return daemon_args_fn(suite, test, node)

        def probe_ready(self, test, node):
            return probe_mysql_ready(suite, test, node)

    DB.__name__ = f"{name.title().replace('-', '')}DB"
    if db_cls is not None:
        # factory form: db_cls(suite) -> class, so multi-daemon DBs
        # close over the suite cfg built here
        DB = db_cls(suite)  # noqa: F811 — deliberate override

    def workloads(opts: dict):
        import itertools

        n_accounts = opts.get("accounts", 5)
        starting = opts.get("starting_balance", 10)
        all_workloads = {
            "bank": {
                "client": BankClient(suite, n_accounts, starting),
                "during": gen.stagger(
                    opts.get("stagger", 0.05),
                    gen.mix([bank_read, bank_diff_transfer()])),
                "final": gen.clients(gen.once(bank_read)),
                "checker_name": "bank",
                "test_opts": {"accounts_n": n_accounts},
            },
            "sets": {
                "client": SetClient(suite),
                "during": gen.stagger(
                    opts.get("stagger", 0.05),
                    gen.seq({"type": "invoke", "f": "add", "value": x}
                            for x in itertools.count())),
                "final": gen.clients(gen.each(
                    lambda: gen.once({"type": "invoke", "f": "read"}))),
                "checker_name": "set",
            },
            "dirty-reads": {
                "client": DirtyReadsClient(suite, opts.get("rows", 4)),
                "during": gen.mix([
                    {"type": "invoke", "f": "read"},
                    gen.seq({"type": "invoke", "f": "write", "value": x}
                            for x in itertools.count()),
                ]),
                "checker_name": "dirty-reads",
            },
            "register": {
                "client": RegisterClient(suite),
                "during": gen.stagger(opts.get("stagger", 0.05), gen.mix([
                    lambda t, p: {"type": "invoke", "f": "read",
                                  "value": None},
                    lambda t, p: {"type": "invoke", "f": "write",
                                  "value": random.randrange(5)},
                    lambda t, p: {"type": "invoke", "f": "cas",
                                  "value": (random.randrange(5),
                                            random.randrange(5))},
                ])),
                "checker_name": "linear",
                "model": models.CASRegister(),
            },
        }
        return {k: all_workloads[k] for k in workload_names}

    def checker_for(wl, n_accounts, starting):
        name_ = wl["checker_name"]
        if name_ == "bank":
            class _BankTotals(Checker):
                def check(self, test, history, opts=None):
                    bad = []
                    total = n_accounts * starting
                    for o in _ops(history):
                        if o.is_ok and o.f == "read" \
                                and sum(o.value.values()) != total:
                            bad.append(o.to_dict())
                    return {"valid": not bad, "bad_reads": bad[:10]}

            return _BankTotals()
        if name_ == "set":
            return checker_mod.set_checker()
        if name_ == "dirty-reads":
            return DirtyReadsChecker()
        return checker_mod.linearizable()

    def test_fn(opts: dict) -> dict:
        from ..testlib import noop_test
        from .common import pick_nemesis

        wl_name = opts.get("workload", workload_names[0])
        wl = workloads(opts)[wl_name]
        db = DB(archive_url=opts.get("archive_url"))
        nem_client = pick_nemesis(
            db, opts,
            extra=extra_nemeses(db) if extra_nemeses else None)
        dt = opts.get("nemesis_interval", 10)
        generator = gen.time_limit(
            opts.get("time_limit", 60),
            gen.nemesis(gen.start_stop(dt, dt), wl["during"]),
        )
        phases = [generator,
                  gen.nemesis(gen.once({"type": "info", "f": "stop"}))]
        if wl.get("final") is not None:
            from .common import ready_gated_final

            phases += [gen.sleep(opts.get("quiesce", 10)),
                       ready_gated_final(db, wl["final"], opts)]
        test = noop_test()
        test.update(opts)
        test.update(
            {
                "name": f"{display_name or name} {wl_name}",
                "os": osdist.debian,
                "db": db,
                "client": wl["client"],
                "nemesis": nem_client,
                "model": wl.get("model"),
                "generator": gen.phases(*phases),
                "checker": checker_mod.compose({
                    "perf": checker_mod.perf_checker(),
                    "workload": checker_for(
                        wl, opts.get("accounts", 5),
                        opts.get("starting_balance", 10)),
                }),
            }
        )
        test.update(wl.get("test_opts") or {})
        return test

    def opt_spec(p) -> None:
        from .common import NEMESIS_NAMES, nemesis_opt

        p.add_argument("--workload", default=workload_names[0],
                       choices=sorted(workload_names))
        nemesis_opt(p, names=NEMESIS_NAMES + tuple(extra_nemesis_names))
        p.add_argument("--archive-url", dest="archive_url", default=None)
        p.add_argument("--accounts", type=int, default=5)
        p.add_argument("--starting-balance", dest="starting_balance",
                       type=int, default=10)

    return suite, DB, workloads, test_fn, opt_spec
