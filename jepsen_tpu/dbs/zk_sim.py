"""A hermetic ZooKeeper lookalike: a socket server speaking the jute
protocol subset in dbs/zk_proto.py (handshake, create/delete/exists/
getData/setData/ping/close) plus the `ruok` four-letter word.

Like dbs/etcd_sim.py, this is the test double that lets the zookeeper
suite exercise its real code paths — archive install, daemon lifecycle,
binary wire protocol, version-CAS — on one machine with no network.
All member processes share one flock-guarded JSON state file, so the
simulated ensemble is linearizable by construction; --mean-latency adds
jitter for real concurrency windows.

Accepts zkServer-ish flags plus the sim's own (--port, --data).
"""

from __future__ import annotations

import argparse
import base64
import random
import socket
import socketserver
import struct
import sys
import time

from .simbase import Store, build_sim_archive
from . import zk_proto as P


def _node(data: bytes, version: int = 0) -> dict:
    return {"data": base64.b64encode(data).decode(), "version": version}


def _data_of(node: dict) -> bytes:
    return base64.b64decode(node["data"])


def _stat_of(node: dict) -> dict:
    d = _data_of(node)
    return {"version": node["version"], "dataLength": len(d)}


class Handler(socketserver.BaseRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0

    def _jitter(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))

    def handle(self):
        sock = self.request
        sock.settimeout(30)
        try:
            head = P._recv_exact(sock, 4)
        except (ConnectionError, OSError):
            return
        if head == b"ruok":  # four-letter word, unframed
            try:
                sock.sendall(b"imok")
            except OSError:
                pass
            return
        try:
            (n,) = struct.unpack(">i", head)
            connect = P.Reader(P._recv_exact(sock, n))
            connect.int32()  # protocolVersion
            connect.int64()  # lastZxidSeen
            session_timeout = connect.int32()
            # ConnectResponse
            resp = (P.Writer().int32(0).int32(session_timeout)
                    .int64(random.getrandbits(62)).buffer(b"\x00" * 16))
            P.write_frame(sock, resp.bytes_())
            while True:
                self._serve_one(sock)
        except (ConnectionError, OSError, P.ZkError):
            return

    def _serve_one(self, sock: socket.socket) -> None:
        r = P.Reader(P.read_frame(sock))
        xid = r.int32()
        opcode = r.int32()
        self._jitter()
        if opcode == P.OP_CLOSE:
            P.write_frame(
                sock, P.Writer().int32(xid).int64(0).int32(P.OK).bytes_()
            )
            raise ConnectionError("closed")
        err, payload = self._dispatch(opcode, r)
        out = P.Writer().int32(xid).int64(0).int32(err).bytes_() + payload
        P.write_frame(sock, out)

    def _dispatch(self, opcode: int, r: P.Reader) -> tuple[int, bytes]:
        if opcode == P.OP_PING:
            return P.OK, b""

        if opcode == P.OP_CREATE:
            path = r.ustring() or ""
            data = r.buffer() or b""

            def create(state):
                if path in state:
                    return (P.ERR_NODE_EXISTS, b""), None
                new = dict(state)
                new[path] = _node(data)
                return (P.OK, P.Writer().ustring(path).bytes_()), new

            return self.store.transact(create)

        if opcode == P.OP_DELETE:
            path = r.ustring() or ""
            version = r.int32()

            def delete(state):
                node = state.get(path)
                if node is None:
                    return (P.ERR_NO_NODE, b""), None
                if version != -1 and node["version"] != version:
                    return (P.ERR_BAD_VERSION, b""), None
                new = dict(state)
                del new[path]
                return (P.OK, b""), new

            return self.store.transact(delete)

        if opcode == P.OP_EXISTS:
            path = r.ustring() or ""

            def exists(state):
                node = state.get(path)
                if node is None:
                    return (P.ERR_NO_NODE, b""), None
                return (P.OK, P.pack_stat(_stat_of(node))), None

            return self.store.transact(exists)

        if opcode == P.OP_GET_DATA:
            path = r.ustring() or ""

            def get_data(state):
                node = state.get(path)
                if node is None:
                    return (P.ERR_NO_NODE, b""), None
                out = (P.Writer().buffer(_data_of(node)).bytes_()
                       + P.pack_stat(_stat_of(node)))
                return (P.OK, out), None

            return self.store.transact(get_data)

        if opcode == P.OP_SET_DATA:
            path = r.ustring() or ""
            data = r.buffer() or b""
            version = r.int32()

            def set_data(state):
                node = state.get(path)
                if node is None:
                    return (P.ERR_NO_NODE, b""), None
                if version != -1 and node["version"] != version:
                    return (P.ERR_BAD_VERSION, b""), None
                new = dict(state)
                new[path] = _node(data, node["version"] + 1)
                return (P.OK, P.pack_stat(_stat_of(new[path]))), new

            return self.store.transact(set_data)

        return P.ERR_UNIMPLEMENTED, b""


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def parse_args(argv):
    p = argparse.ArgumentParser(description="ZooKeeper jute-subset simulator",
                                allow_abbrev=False)
    p.add_argument("--data", required=True, help="shared JSON state file")
    p.add_argument("--port", type=int, default=2181)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--name", default="zk-sim")
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    srv = Server((args.host, args.port), Handler)
    print(f"zk-sim {args.name} serving on {args.host}:{args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    """An archive whose `zkserver` binary launches this simulator
    (installed through the suite's normal install_archive path)."""
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.zk_sim", "zkserver", "zookeeper-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
