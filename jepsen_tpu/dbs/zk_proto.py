"""Minimal ZooKeeper wire protocol (jute) — client side.

The zookeeper suite needs exactly what the reference's avout zk-atom
uses (/root/reference/zookeeper/src/jepsen/zookeeper.clj:78-104): a
session, create, getData, setData-with-version (optimistic CAS), and
ping. This implements that subset of the ZooKeeper 3.4 protocol from the
jute IDL: length-framed packets, a ConnectRequest handshake, then
xid/opcode request frames. No external ZK library exists in this
environment, so the framework carries its own client.

All multi-byte integers are big-endian. Strings and buffers are
length-prefixed (-1 = null).
"""

from __future__ import annotations

import socket
import struct
import threading

# Opcodes (zookeeper.h)
OP_CREATE = 1
OP_DELETE = 2
OP_EXISTS = 3
OP_GET_DATA = 4
OP_SET_DATA = 5
OP_PING = 11
OP_CLOSE = -11

XID_PING = -2

# Error codes
OK = 0
ERR_UNIMPLEMENTED = -6
ERR_NO_NODE = -101
ERR_NODE_EXISTS = -110
ERR_BAD_VERSION = -103

#: world:anyone ACL with all perms (0x1f)
OPEN_ACL_UNSAFE = [(0x1F, "world", "anyone")]

STAT_STRUCT = struct.Struct(">qqqqiiiqiiq")  # 68 bytes


class ZkError(Exception):
    def __init__(self, code: int, message: str = ""):
        super().__init__(message or f"zookeeper error {code}")
        self.code = code


class NoNode(ZkError):
    def __init__(self):
        super().__init__(ERR_NO_NODE, "no node")


class NodeExists(ZkError):
    def __init__(self):
        super().__init__(ERR_NODE_EXISTS, "node exists")


class BadVersion(ZkError):
    def __init__(self):
        super().__init__(ERR_BAD_VERSION, "bad version")


_ERRS = {ERR_NO_NODE: NoNode, ERR_NODE_EXISTS: NodeExists,
         ERR_BAD_VERSION: BadVersion}


def err_for(code: int) -> ZkError:
    cls = _ERRS.get(code)
    return cls() if cls else ZkError(code)


# ---------------------------------------------------------------------------
# jute primitives

class Writer:
    def __init__(self):
        self.parts: list[bytes] = []

    def int32(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">i", v))
        return self

    def int64(self, v: int) -> "Writer":
        self.parts.append(struct.pack(">q", v))
        return self

    def bool_(self, v: bool) -> "Writer":
        self.parts.append(b"\x01" if v else b"\x00")
        return self

    def buffer(self, b: bytes | None) -> "Writer":
        if b is None:
            return self.int32(-1)
        self.int32(len(b))
        self.parts.append(b)
        return self

    def ustring(self, s: str | None) -> "Writer":
        return self.buffer(None if s is None else s.encode())

    def acls(self, acls) -> "Writer":
        self.int32(len(acls))
        for perms, scheme, ident in acls:
            self.int32(perms).ustring(scheme).ustring(ident)
        return self

    def bytes_(self) -> bytes:
        return b"".join(self.parts)


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def _take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise ZkError(0, "short packet")
        b = self.data[self.off:self.off + n]
        self.off += n
        return b

    def int32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def int64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def bool_(self) -> bool:
        return self._take(1) != b"\x00"

    def buffer(self) -> bytes | None:
        n = self.int32()
        return None if n < 0 else self._take(n)

    def ustring(self) -> str | None:
        b = self.buffer()
        return None if b is None else b.decode()

    def stat(self) -> dict:
        (czxid, mzxid, ctime, mtime, version, cversion, aversion,
         ephemeral_owner, data_length, num_children, pzxid) = (
            STAT_STRUCT.unpack(self._take(STAT_STRUCT.size)))
        return {
            "czxid": czxid, "mzxid": mzxid, "ctime": ctime, "mtime": mtime,
            "version": version, "cversion": cversion, "aversion": aversion,
            "ephemeralOwner": ephemeral_owner, "dataLength": data_length,
            "numChildren": num_children, "pzxid": pzxid,
        }


def pack_stat(stat: dict) -> bytes:
    return STAT_STRUCT.pack(
        stat.get("czxid", 0), stat.get("mzxid", 0), stat.get("ctime", 0),
        stat.get("mtime", 0), stat.get("version", 0),
        stat.get("cversion", 0), stat.get("aversion", 0),
        stat.get("ephemeralOwner", 0), stat.get("dataLength", 0),
        stat.get("numChildren", 0), stat.get("pzxid", 0),
    )


def read_frame(sock: socket.socket) -> bytes:
    head = _recv_exact(sock, 4)
    (n,) = struct.unpack(">i", head)
    if n < 0 or n > 64 * 1024 * 1024:
        raise ZkError(0, f"bad frame length {n}")
    return _recv_exact(sock, n)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">i", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(n)
        if not b:
            raise ConnectionError("connection closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


# ---------------------------------------------------------------------------
# Client connection

class ZkConn:
    """One ZooKeeper session over one socket. Synchronous, lock-guarded:
    requests are matched to responses by xid in order."""

    def __init__(self, host: str, port: int = 2181,
                 timeout: float = 5.0, session_timeout_ms: int = 10_000):
        self.timeout = timeout
        self._lock = threading.Lock()
        self._xid = 0
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        # ConnectRequest: protocolVersion, lastZxidSeen, timeOut,
        # sessionId, passwd
        req = (Writer().int32(0).int64(0).int32(session_timeout_ms)
               .int64(0).buffer(b"\x00" * 16).bytes_())
        write_frame(self.sock, req)
        resp = Reader(read_frame(self.sock))
        resp.int32()  # protocolVersion
        self.negotiated_timeout = resp.int32()
        self.session_id = resp.int64()
        resp.buffer()  # passwd

    def _call(self, opcode: int, payload: bytes, xid: int | None = None
              ) -> Reader:
        with self._lock:
            if xid is None:
                self._xid += 1
                xid = self._xid
            write_frame(
                self.sock,
                Writer().int32(xid).int32(opcode).bytes_() + payload,
            )
            r = Reader(read_frame(self.sock))
        got_xid = r.int32()
        r.int64()  # zxid
        err = r.int32()
        if got_xid != xid:
            raise ZkError(0, f"xid mismatch: sent {xid}, got {got_xid}")
        if err != OK:
            raise err_for(err)
        return r

    def create(self, path: str, data: bytes = b"",
               acls=OPEN_ACL_UNSAFE, flags: int = 0) -> str:
        payload = (Writer().ustring(path).buffer(data).acls(acls)
                   .int32(flags).bytes_())
        return self._call(OP_CREATE, payload).ustring() or path

    def exists(self, path: str) -> dict | None:
        try:
            r = self._call(OP_EXISTS,
                           Writer().ustring(path).bool_(False).bytes_())
            return r.stat()
        except NoNode:
            return None

    def get_data(self, path: str) -> tuple[bytes, dict]:
        r = self._call(OP_GET_DATA,
                       Writer().ustring(path).bool_(False).bytes_())
        data = r.buffer() or b""
        return data, r.stat()

    def set_data(self, path: str, data: bytes, version: int = -1) -> dict:
        payload = (Writer().ustring(path).buffer(data)
                   .int32(version).bytes_())
        return self._call(OP_SET_DATA, payload).stat()

    def delete(self, path: str, version: int = -1) -> None:
        self._call(OP_DELETE, Writer().ustring(path).int32(version).bytes_())

    def ping(self) -> None:
        self._call(OP_PING, b"", xid=XID_PING)

    def close(self) -> None:
        try:
            with self._lock:
                write_frame(
                    self.sock, Writer().int32(self._xid + 1)
                    .int32(OP_CLOSE).bytes_()
                )
        except OSError:
            pass
        finally:
            try:
                self.sock.close()
            except OSError:
                pass
