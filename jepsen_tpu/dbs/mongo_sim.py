"""A hermetic MongoDB lookalike: an OP_MSG server handling the command
subset the mongodb suites drive — ping, hello/isMaster, insert, update
(exact-match filters, upsert, n-matched reporting), find, and
replSetInitiate/replSetGetStatus as accepted no-ops (membership is
implicit in the shared state). Collections live in the flock-guarded
JSON store as {db.coll: [docs]}."""

from __future__ import annotations

import argparse
import random
import socketserver
import struct
import sys
import time

from . import bson, mongo_proto
from .simbase import Store, build_sim_archive


def _matches(doc: dict, q: dict) -> bool:
    return all(doc.get(k) == v for k, v in q.items())


class Handler(socketserver.BaseRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client went away")
            buf += chunk
        return buf

    def handle(self):
        self.request.settimeout(120.0)
        try:
            while True:
                (length,) = struct.unpack("<i", self._read_exact(4))
                rest = self._read_exact(length - 4)
                req_id, _reply_to, opcode = struct.unpack_from("<iii",
                                                               rest, 0)
                if opcode != mongo_proto.OP_MSG:
                    return
                cmd, _ = bson.decode(rest, 12 + 4 + 1)
                if self.mean_latency > 0:
                    time.sleep(random.expovariate(1.0 / self.mean_latency))
                reply = self._dispatch(cmd)
                payload = b"\x00\x00\x00\x00\x00" + bson.encode(reply)
                header = struct.pack("<iiii", 16 + len(payload), 0,
                                     req_id, mongo_proto.OP_MSG)
                self.request.sendall(header + payload)
        except (ConnectionError, TimeoutError, OSError, ValueError):
            return

    def _dispatch(self, cmd: dict) -> dict:
        db = cmd.get("$db", "admin")
        name = next(iter(cmd))
        if name in ("ping", "hello", "isMaster", "ismaster"):
            return {"ok": 1, "isWritablePrimary": True}
        if name in ("replSetInitiate", "replSetGetStatus"):
            return {"ok": 1, "members": []}
        if name == "insert":
            return self._insert(db, cmd)
        if name == "update":
            return self._update(db, cmd)
        if name == "find":
            return self._find(db, cmd)
        if name == "findAndModify":
            return self._find_and_modify(db, cmd)
        return {"ok": 0, "errmsg": f"no such command: '{name}'",
                "code": 59}

    def _find_and_modify(self, db: str, cmd: dict) -> dict:
        """Only the remove-oldest shape the logger workload uses
        (mongodb_rocks.clj:113-121: sort + remove=true)."""
        key = f"{db}.{cmd['findAndModify']}"
        q = cmd.get("query") or {}
        sort = cmd.get("sort") or {}
        if not cmd.get("remove"):
            return {"ok": 0, "errmsg": "only remove supported"}

        def fam(data):
            colls = dict(data.get("colls") or {})
            coll = list(colls.get(key) or [])
            hits = [d for d in coll if _matches(d, q)]
            if sort:
                field, direction = next(iter(sort.items()))
                # docs missing the sort field order last regardless
                # of direction (so they are never the victim while a
                # sortable doc exists)
                present = [d for d in hits if d.get(field) is not None]
                absent = [d for d in hits if d.get(field) is None]
                present.sort(key=lambda d: d[field],
                             reverse=direction < 0)
                hits = present + absent
            if not hits:
                return {"ok": 1, "value": None}, None
            victim = hits[0]
            coll.remove(victim)
            colls[key] = coll
            new = dict(data)
            new["colls"] = colls
            return {"ok": 1, "value": victim}, new

        return self.store.transact(fam)

    def _insert(self, db: str, cmd: dict) -> dict:
        key = f"{db}.{cmd['insert']}"
        docs = cmd["documents"]

        def ins(data):
            colls = dict(data.get("colls") or {})
            coll = list(colls.get(key) or [])
            for d in docs:
                if "_id" in d and any(
                        x.get("_id") == d["_id"] for x in coll):
                    return {"ok": 1, "n": 0, "writeErrors": [
                        {"code": 11000,
                         "errmsg": "E11000 duplicate key error"}]}, None
                coll.append(d)
            colls[key] = coll
            new = dict(data)
            new["colls"] = colls
            return {"ok": 1, "n": len(docs)}, new

        return self.store.transact(ins)

    def _update(self, db: str, cmd: dict) -> dict:
        key = f"{db}.{cmd['update']}"
        spec = cmd["updates"][0]
        q, u, upsert = spec["q"], spec["u"], spec.get("upsert", False)

        def upd(data):
            colls = dict(data.get("colls") or {})
            coll = list(colls.get(key) or [])
            n = 0
            for i, doc in enumerate(coll):
                if _matches(doc, q):
                    replacement = dict(u)
                    if "_id" in doc and "_id" not in replacement:
                        replacement["_id"] = doc["_id"]
                    coll[i] = replacement
                    n += 1
                    break  # multi:false semantics
            upserted = 0
            if n == 0 and upsert:
                coll.append(dict(u))
                upserted = 1
            colls[key] = coll
            new = dict(data)
            new["colls"] = colls
            return ({"ok": 1, "n": n + upserted,
                     "nModified": n}, new if (n or upserted) else None)

        return self.store.transact(upd)

    def _find(self, db: str, cmd: dict) -> dict:
        key = f"{db}.{cmd['find']}"
        q = cmd.get("filter") or {}
        limit = cmd.get("limit") or 0

        def rd(data):
            coll = (data.get("colls") or {}).get(key) or []
            out = [d for d in coll if _matches(d, q)]
            if limit:
                out = out[:limit]
            return out, None

        batch = self.store.transact(rd)
        return {"ok": 1, "cursor": {"id": 0, "ns": key,
                                    "firstBatch": batch}}


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def parse_args(argv):
    p = argparse.ArgumentParser(description="mongodb OP_MSG sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=27017)
    p.add_argument("--name", default="sim")
    # mongod flags tolerated:
    p.add_argument("--replSet", default=None)
    p.add_argument("--dbpath", default=None)
    p.add_argument("--storageEngine", default=None)
    p.add_argument("--bind_ip", default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    srv = Server(("127.0.0.1", args.port), Handler)
    print(f"mongo-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.mongo_sim", "mongod", "mongod-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
