"""RobustIRC test suite: TOPIC messages as a grow-only set over the
raft-replicated IRC network (reference:
/root/reference/robustirc/src/jepsen/robustirc.clj:1-217).

Each client opens a RobustSession, registers (NICK/USER/JOIN), adds
integers by setting the channel topic ("TOPIC #jepsen :<n>",
robustirc.clj:163-176), and the final read extracts every topic value
seen in the message log; the set checker demands every acknowledged add
appear (robustirc.clj:195-211)."""

from __future__ import annotations

import itertools
import json
import logging
import socket
import urllib.error
import urllib.request

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, generator as gen, osdist
from ..history import Op
from .common import ArchiveDB, SuiteCfg, ready_gated_final

log = logging.getLogger("jepsen_tpu.dbs.robustirc")

PORT = 13001
CHANNEL = "#jepsen"


_suite = SuiteCfg("robustirc", PORT, "/opt/robustirc")
node_host = _suite.host
node_port = _suite.port


class RobustIrcDB(ArchiveDB):
    binary = "robustirc"
    log_name = "robustirc.log"
    pid_name = "robustirc.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        primary = test["nodes"][0]
        args = ["--port", str(node_port(test, node)),
                "-network_name", "jepsen"]
        if node != primary:
            args += ["-peer_addr",
                     f"{node_host(test, primary)}:"
                     f"{node_port(test, primary)}"]
        return args

    def probe_ready(self, test, node) -> bool:
        # a session create answering at all means raft is up
        try:
            RobustSession(test, node, timeout=2.0)
            return True
        except (urllib.error.URLError, OSError):
            return False


class RobustSession:
    """One RobustSession (robustirc.clj:102-135)."""

    def __init__(self, test, node, timeout: float = 5.0):
        self.base = (f"http://{node_host(test, node)}:"
                     f"{node_port(test, node)}/robustirc/v1")
        self.timeout = timeout
        self._msg_ids = itertools.count(1)
        body = self._request("POST", "/session")
        self.session_id = body["Sessionid"]
        self.session_auth = body["Sessionauth"]

    def _request(self, method: str, path: str, body=None,
                 auth: bool = False):
        data = json.dumps(body).encode() if body is not None else b""
        req = urllib.request.Request(self.base + path, data=data,
                                     method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        if auth:
            req.add_header("X-Session-Auth", self.session_auth)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            payload = resp.read()
            return json.loads(payload) if payload else {}

    def post_message(self, irc_line: str) -> None:
        self._request("POST", f"/{self.session_id}/message",
                      body={"Data": irc_line,
                            "ClientMessageId": next(self._msg_ids)},
                      auth=True)

    def read_all(self) -> list:
        return self._request("GET", f"/{self.session_id}/messages",
                             auth=True)


def filter_topic(msg: dict) -> bool:
    """Raw client lines start with TOPIC; server-echoed lines carry a
    :prefix first (robustirc.clj:138-143's 'use a proper IRC parser'
    caveat applies here too)."""
    parts = (msg.get("Data") or "").split(" ")
    return bool(parts) and (
        parts[0] == "TOPIC"
        or (len(parts) > 1 and parts[1] == "TOPIC"))


def extract_topic(msg: dict) -> int | None:
    try:
        return int((msg.get("Data") or "").rsplit(":", 1)[-1])
    except ValueError:
        return None


class SetClient(client.Client):
    """TOPIC-set client (robustirc.clj:150-182): adds are
    acknowledged-or-failed topic changes; the read collects every topic
    value in the log. An add whose POST errors is :info — the message
    may have been committed by raft anyway."""

    def __init__(self, session: RobustSession | None = None):
        self.session = session

    def open(self, test, node):
        session = RobustSession(test, node)
        session.post_message(f"NICK {node}")
        session.post_message("USER j j j j")
        session.post_message(f"JOIN {CHANNEL}")
        return SetClient(session)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                self.session.post_message(
                    f"TOPIC {CHANNEL} :{op.value}")
                return op.with_(type="ok")
            if op.f == "read":
                msgs = self.session.read_all()
                values = sorted({
                    v for v in (extract_topic(m) for m in msgs
                                if filter_topic(m))
                    if v is not None
                })
                return op.with_(type="ok", value=values)
            raise ValueError(f"unknown op {op.f!r}")
        except (socket.timeout, TimeoutError):
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error="timeout")
        except (urllib.error.URLError, OSError) as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))


def robustirc_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = RobustIrcDB(archive_url=opts.get("archive_url"))
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": "robustirc set",
            "os": osdist.debian,
            "db": db_,
            "client": SetClient(),
            "nemesis": cmn.pick_nemesis(db_, opts),
            "generator": gen.phases(
                gen.time_limit(
                    opts.get("time_limit", 60),
                    gen.nemesis(
                        gen.start_stop(10, 10),
                        gen.stagger(
                            opts.get("stagger", 0.1),
                            gen.seq({"type": "invoke", "f": "add",
                                     "value": x}
                                    for x in itertools.count())),
                    ),
                ),
                gen.nemesis(gen.once({"type": "info", "f": "stop"})),
                gen.sleep(opts.get("quiesce", 10)),
                ready_gated_final(
                    db_,
                    gen.clients(gen.each(
                        lambda: gen.once(
                            {"type": "invoke", "f": "read"}))),
                    opts),
            ),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "set": checker_mod.set_checker(),
            }),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--archive-url", dest="archive_url", default=None)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(robustirc_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
