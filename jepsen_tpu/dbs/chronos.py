"""Chronos test suite: schedule repeating jobs, record their actual
runs, and verify every promised execution happened within its window
(reference: /root/reference/chronos/src/jepsen/chronos.clj:1-270 and
chronos/checker.clj:1-321).

Jobs are shell commands that log their own invocation times to
tempfiles on the node (chronos.clj:109-117); the final :read collects
those run files from every node via the control plane
(chronos.clj:161-172). The checker derives each job's target windows
[t, t+epsilon+forgiveness] from its schedule and greedily matches runs
to targets — a target with no run is a missed execution
(checker.clj:30-90's model, with a greedy matcher in place of the
reference's constraint solver)."""

from __future__ import annotations

import datetime
import itertools
import json
import logging
import random
import socket
import time
import urllib.error
import urllib.request

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, generator as gen, osdist
from ..checker import Checker
from ..history import Op, ops as _ops
from ..util import real_pmap
from .common import SuiteCfg, ready_gated_final

log = logging.getLogger("jepsen_tpu.dbs.chronos")

PORT = 4400
EPSILON_FORGIVENESS = 5  # let chronos miss deadlines by a few seconds


_suite = SuiteCfg("chronos", PORT, "/opt/chronos")
node_host = _suite.host
node_port = _suite.port


def job_dir(test) -> str:
    return _suite.cfg(test).get("job_dir", "/tmp/chronos-test")


MASTER_COUNT = 3          # mesosphere.clj:17
ZK_PORT = 2181
MESOS_PORT = 5050


class ChronosDB(cmn.MultiDaemonDB):
    """The real mesosphere stack per the reference: zookeeper on every
    node, mesos-master on the first MASTER_COUNT sorted nodes and
    mesos-slave on the rest (mesosphere.clj:57-119's role split),
    chronos on every node (chronos.clj:56-83 layers it over
    mesosphere/db). Bring-up is readiness-gated in dependency order
    zk -> mesos -> chronos; teardown reverses it (chronos.clj:73-78
    stops chronos first, then the mesosphere teardown). The chronos
    sim gates its scheduler API on the node's zookeeper, so the
    kill-zk nemesis is client-observable."""

    binary = "chronos"
    log_name = "chronos.log"
    pid_name = "chronos.pid"

    ROLES = ("zk", "mesos-master", "mesos-slave", "chronos")
    ROLE_TAG = {"zk": "zookeeper", "mesos-master": "mesos-master",
                "mesos-slave": "mesos-slave", "chronos": "chronos"}
    ROLE_BIN = {"zk": "zookeeper-server",
                "mesos-master": "mesos-master",
                "mesos-slave": "mesos-slave", "chronos": "chronos"}
    STOP_ORDER = ("chronos", "mesos-slave", "mesos-master", "zk")

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    # ---- role placement (mesosphere.clj:60-71,93-100) ----

    def masters(self, test) -> list:
        return sorted(test["nodes"])[:MASTER_COUNT]

    def role_nodes(self, test, role) -> list:
        if role == "mesos-master":
            return self.masters(test)
        if role == "mesos-slave":
            return [n for n in sorted(test["nodes"])
                    if n not in self.masters(test)]
        return list(test["nodes"])

    def role_port(self, test, node, role) -> int:
        if role == "chronos":
            return node_port(test, node)
        if role == "zk":
            ports = _suite.cfg(test).get("zk_ports")
            return ports[node] if ports else ZK_PORT
        ports = _suite.cfg(test).get("mesos_ports")
        return ports[node] if ports else MESOS_PORT

    def zk_uri(self, test) -> str:
        """zk://host:port,.../mesos (mesosphere.clj:38-46)."""
        return "zk://" + ",".join(
            f"{node_host(test, n)}:{self.role_port(test, n, 'zk')}"
            for n in test["nodes"]) + "/mesos"

    def role_args(self, test, node, role) -> list:
        port = self.role_port(test, node, role)
        if role == "zk":
            return ["--port", str(port)]
        if role == "mesos-master":
            quorum = len(self.masters(test)) // 2 + 1
            return ["--port", str(port), "--role", "master",
                    "--zk", self.zk_uri(test), "--quorum", str(quorum)]
        if role == "mesos-slave":
            return ["--port", str(port), "--role", "slave",
                    "--master", self.zk_uri(test)]
        return ["--port", str(port),
                "--zk-port", str(self.role_port(test, node, "zk")),
                "--master", self.zk_uri(test)]

    # the base-class single-daemon surface (shared start-kill /
    # hammer-time nemeses) targets the chronos scheduler itself
    def daemon_args(self, test, node) -> list:
        return self.role_args(test, node, "chronos")

    def probe_ready(self, test, node) -> bool:
        url = (f"http://{node_host(test, node)}:{node_port(test, node)}"
               "/scheduler/jobs")
        with urllib.request.urlopen(url, timeout=2) as resp:
            return resp.status == 200

    def setup(self, test, node) -> None:
        remote = test["remote"]
        remote.exec(node, ["mkdir", "-p", job_dir(test)], check=False)
        self.install(test, node)
        self.start_component(test, node, "zk")
        self._await_ports(test, "zk", self.ready_timeout)
        for mesos_role in ("mesos-master", "mesos-slave"):
            if node in self.role_nodes(test, mesos_role):
                self.start_component(test, node, mesos_role)
        self._await_ports(test, "mesos-master", self.ready_timeout)
        self.start_component(test, node, "chronos")
        self.await_ready(test, node)

    def teardown(self, test, node) -> None:
        super().teardown(test, node)
        test["remote"].exec(node, ["rm", "-rf", job_dir(test)],
                            check=False)


def interval_str(job: dict) -> str:
    """R<count>/<ISO start>/PT<interval>S (chronos.clj:102-107)."""
    start = datetime.datetime.fromtimestamp(
        job["start"], tz=datetime.timezone.utc)
    return (f"R{job['count']}/{start.isoformat()}"
            f"/PT{job['interval']}S")


def command(job: dict, test) -> str:
    """Shell command logging name + invocation + completion times
    (chronos.clj:109-117)."""
    d = job_dir(test)
    return (f"MEW=$(mktemp -p {d}); "
            f"echo \"{job['name']}\" >> $MEW; "
            "date -u +%s.%N >> $MEW; "
            f"sleep {job['duration']}; "
            "date -u +%s.%N >> $MEW;")


def job_to_json(job: dict, test) -> dict:
    return {
        "name": str(job["name"]),
        "command": command(job, test),
        "schedule": interval_str(job),
        "scheduleTimeZone": "UTC",
        "owner": "jepsen@jepsen.io",
        "epsilon": f"PT{job['epsilon']}S",
        "mem": 1, "disk": 1, "cpus": 0.001, "async": False,
    }


def read_runs(test) -> list:
    """Collect every run record from every node's job files
    (chronos.clj:143-172). Files are parsed INDIVIDUALLY — a job still
    mid-sleep has only [name, start] in its file, and concatenating
    everything would shift later records out of alignment."""
    remote = test["remote"]
    d = job_dir(test)
    sep = "==JEPSEN-FILE=="

    def read_node(node):
        out = remote.exec(
            node,
            f'for f in {d}/*; do echo "{sep}"; cat "$f"; echo; done '
            "2>/dev/null || true",
            check=False).out
        runs = []
        for block in out.split(sep):
            lines = [ln for ln in block.splitlines() if ln.strip()]
            if len(lines) < 2:
                continue
            try:
                runs.append({
                    "node": str(node),
                    "name": int(lines[0]),
                    "start": float(lines[1]),
                    "end": (float(lines[2])
                            if len(lines) > 2 else None),
                })
            except ValueError:
                continue
        return runs

    out = []
    for runs in real_pmap(read_node, test["nodes"]):
        out.extend(runs)
    return out


class ChronosClient(client.Client):
    """add-job POSTs the schedule; read collects run files
    (chronos.clj:174-196)."""

    def __init__(self, node=None):
        self.node = node

    def open(self, test, node):
        return ChronosClient(node)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add-job":
                body = json.dumps(job_to_json(op.value, test)).encode()
                req = urllib.request.Request(
                    f"http://{node_host(test, self.node)}:"
                    f"{node_port(test, self.node)}/scheduler/iso8601",
                    data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(req, timeout=20):
                    pass
                return op.with_(type="ok")
            if op.f == "read":
                # runs carry EPOCH times, so the read moment must be
                # epoch too (Op.time is relative to test start)
                return op.with_(type="ok", value={
                    "time": time.time(),
                    "runs": read_runs(test),
                })
            raise ValueError(f"unknown op {op.f!r}")
        except (ConnectionError, socket.timeout, TimeoutError) as e:
            return op.with_(type="fail", error=str(e))
        except (urllib.error.URLError, OSError) as e:
            return op.with_(type="fail", error=str(e))


class ChronosChecker(Checker):
    """Match runs to each job's target windows (checker.clj:30-199,
    greedy instead of loco). A job's targets are every scheduled start
    before the final read (minus epsilon+duration slack); each needs a
    run beginning within [target, target+epsilon+forgiveness]."""

    def check(self, test, history, opts=None) -> dict:
        jobs = [o.value for o in _ops(history)
                if o.is_ok and o.f == "add-job"]
        read_time = None
        runs = None
        for o in _ops(history):
            if o.is_ok and o.f == "read":
                if not isinstance(o.value, dict):
                    # a pre-dict-format store: no epoch read time was
                    # recorded, so targets can't be derived honestly
                    return {"valid": "unknown",
                            "error": "read lacks epoch timestamp"}
                runs = o.value["runs"]
                read_time = o.value["time"]
        if runs is None:
            return {"valid": "unknown", "error": "no run read"}

        runs_by_job: dict = {}
        for run in runs:
            runs_by_job.setdefault(run["name"], []).append(run)

        job_results = {}
        all_valid = True
        for job in jobs:
            targets = []
            finish = read_time - job["epsilon"] - job["duration"]
            for i in range(job["count"]):
                t = job["start"] + i * job["interval"]
                if t > finish:
                    break
                targets.append(t)
            available = sorted(
                r["start"] for r in runs_by_job.get(job["name"], []))
            used = [False] * len(available)
            solo = []
            for t in targets:
                hit = None
                for i, s in enumerate(available):
                    if used[i]:
                        continue
                    if t <= s <= t + job["epsilon"] + EPSILON_FORGIVENESS:
                        hit = i
                        break
                if hit is None:
                    solo.append(t)
                else:
                    used[hit] = True
            extra = used.count(False)
            ok = not solo
            all_valid = all_valid and ok
            job_results[job["name"]] = {
                "valid": ok,
                "targets": len(targets),
                "runs": len(available),
                "missed_targets": solo[:10],
                "extra_runs": extra,
            }
        return {"valid": all_valid, "jobs": job_results}


def add_job_gen():
    """Non-overlapping repeating jobs a few seconds out
    (chronos.clj:194-217)."""
    ids = itertools.count(1)

    def g(test, process):
        head_start = test.get("chronos_head_start", 10)
        duration = random.randrange(test.get("chronos_max_duration", 10))
        epsilon = 10 + random.randrange(20)
        interval = (1 + duration + epsilon + EPSILON_FORGIVENESS
                    + random.randrange(30))
        return {
            "type": "invoke",
            "f": "add-job",
            "value": {
                "name": next(ids),
                "start": time.time() + head_start,
                "count": 1 + random.randrange(
                    test.get("chronos_max_count", 99)),
                "duration": duration,
                "epsilon": epsilon,
                "interval": interval,
            },
        }

    return g


def chronos_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = ChronosDB(archive_url=opts.get("archive_url"))
    test = noop_test()
    test.update(opts)
    # component killers per stack role (the tidb/NDB surface): kill
    # one node's zookeeper/mesos daemon while the rest keep serving
    extra = {
        f"kill-{role}": (lambda role=role: cmn.ComponentKiller(
            db_, role))
        for role in ("zk", "mesos-master", "mesos-slave", "chronos")
    }
    test.update(
        {
            "name": "chronos",
            "os": osdist.debian,
            "db": db_,
            "client": ChronosClient(),
            "nemesis": cmn.pick_nemesis(db_, opts, extra=extra),
            "generator": gen.phases(
                gen.time_limit(
                    opts.get("time_limit", 120),
                    gen.nemesis(
                        gen.start_stop(20, 20),
                        gen.stagger(opts.get("stagger", 5),
                                    add_job_gen()),
                    ),
                ),
                gen.nemesis(gen.once({"type": "info", "f": "stop"})),
                gen.sleep(opts.get("quiesce", 15)),
                ready_gated_final(
                    db_,
                    gen.clients(gen.once(
                        {"type": "invoke", "f": "read"})),
                    opts),
            ),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "chronos": ChronosChecker(),
            }),
        }
    )
    return test


COMPONENT_NEMESES = ("kill-zk", "kill-mesos-master",
                     "kill-mesos-slave", "kill-chronos")


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p, names=cmn.NEMESIS_NAMES + COMPONENT_NEMESES)
    p.add_argument("--archive-url", dest="archive_url", default=None)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(chronos_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
