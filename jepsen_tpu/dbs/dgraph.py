"""Dgraph test suite: set and upsert workloads over the HTTP API
(reference: /root/reference/dgraph/src/jepsen/dgraph/{core,client,set,
upsert}.clj — the reference drives dgraph4j over gRPC; this speaks the
HTTP mutate/query API, dgraph's other first-class surface).

Workloads:
  - set: integers as nodes with a value predicate; final read queries
    has(value) — every acknowledged add must appear (set.clj:20-53)
  - upsert: concurrent insert-if-absent of the same key via an upsert
    block (query + cond); under snapshot isolation at most ONE insert
    per key may win (upsert.clj:20-68)
"""

from __future__ import annotations

import contextlib
import itertools
import json
import logging
import socket
import urllib.error
import urllib.parse
import urllib.request

from .. import checker as checker_mod
from .. import cli, client, generator as gen, independent, nemesis, osdist
from .. import trace
from ..checker import Checker
from ..history import Op, ops as _ops
from .common import ArchiveDB, SuiteCfg

log = logging.getLogger("jepsen_tpu.dbs.dgraph")

PORT = 8080


_suite = SuiteCfg("dgraph", PORT, "/opt/dgraph")
node_host = _suite.host
node_port = _suite.port


class DgraphDB(ArchiveDB):
    """dgraph alpha per node, pointed at the first node's zero
    (dgraph/support.clj's cluster bring-up)."""

    binary = "dgraph"
    log_name = "dgraph.log"
    pid_name = "dgraph.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        primary = test["nodes"][0]
        return ["--port", str(node_port(test, node)),
                "--zero", f"{node_host(test, primary)}:5080",
                "--my", f"{node_host(test, node)}:7080"]

    def probe_ready(self, test, node) -> bool:
        url = (f"http://{node_host(test, node)}:{node_port(test, node)}"
               "/health")
        with urllib.request.urlopen(url, timeout=2) as resp:
            return resp.status == 200


class DgraphConn:
    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.base = f"http://{host}:{port}"
        self.timeout = timeout

    def _post(self, path: str, body: dict, params: dict | None = None) -> dict:
        # Spans around every wire call, like the reference's client
        # wraps each query/mutation (dgraph/trace.clj:43-53).
        with trace.with_trace(f"dgraph.client{path}"):
            url = self.base + path
            if params:
                url += "?" + urllib.parse.urlencode(params)
            req = urllib.request.Request(
                url, data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    out = json.load(resp)
            except urllib.error.HTTPError as e:
                try:
                    out = json.load(e)
                except json.JSONDecodeError:
                    raise DgraphError(f"HTTP {e.code}") from e
                msg = (out.get("errors") or [{}])[0].get("message", "")
                if "aborted" in msg.lower():
                    # client.clj:105-167 maps this to :fail :conflict
                    raise TxnConflict(msg) from e
                raise DgraphError(msg or f"HTTP {e.code}") from e
            if out.get("errors"):
                raise DgraphError(out["errors"][0].get("message", "error"))
            return out

    def alter(self, schema: str) -> None:
        self._post("/alter", {"schema": schema})

    def mutate(self, sets: list, query: str | None = None,
               cond: str | None = None) -> dict:
        """One-shot (auto-commit) mutation."""
        body: dict = {"set": sets}
        if query is not None:
            body["query"] = query
        if cond is not None:
            body["cond"] = cond
        return self._post("/mutate", body)["data"]["uids"]

    def query(self, q: str) -> list:
        return self._post("/query", {"query": q})["data"]["q"]

    def txn(self) -> "DgraphTxn":
        return DgraphTxn(self)


class DgraphTxn:
    """A dgraph transaction: start_ts assigned by the server on first
    contact, reads from that snapshot, mutations staged server-side,
    commit detects write-write conflicts (client.clj:66-103's
    Transaction object over the HTTP API)."""

    def __init__(self, conn: DgraphConn):
        self.conn = conn
        self.start_ts = 0
        self.finished = False

    def _ts(self, out: dict) -> None:
        ts = ((out.get("extensions") or {}).get("txn") or {}).get("start_ts")
        if ts and not self.start_ts:
            self.start_ts = int(ts)

    def query(self, q: str) -> list:
        out = self.conn._post("/query", {"query": q},
                              params={"startTs": self.start_ts})
        self._ts(out)
        return out["data"]["q"]

    def mutate(self, sets: list | None = None, dels: list | None = None,
               query: str | None = None, cond: str | None = None) -> dict:
        body: dict = {}
        if sets:
            body["set"] = sets
        if dels:
            body["delete"] = dels
        if query is not None:
            body["query"] = query
        if cond is not None:
            body["cond"] = cond
        out = self.conn._post(
            "/mutate", body,
            params={"startTs": self.start_ts, "commitNow": "false"})
        self._ts(out)
        return out["data"]["uids"]

    def commit(self) -> None:
        """Commit; raises TxnConflict on a write-write conflict."""
        if self.finished or not self.start_ts:
            self.finished = True
            return
        self.finished = True
        self.conn._post("/commit", {}, params={"startTs": self.start_ts})

    def discard(self) -> None:
        """Abort (client.clj:55-64's abort-txn!); idempotent."""
        if self.finished or not self.start_ts:
            self.finished = True
            return
        self.finished = True
        try:
            self.conn._post("/commit", {},
                            params={"startTs": self.start_ts,
                                    "abort": "true"})
        except (DgraphError, urllib.error.URLError, OSError,
                socket.timeout):
            # Abort must never mask the body's exception — a dead or
            # partitioned node makes the discard a best-effort no-op
            # (client.clj:55-64 tolerates ABORTED the same way).
            pass


@contextlib.contextmanager
def with_txn(conn: DgraphConn):
    """Open a transaction, commit at the end of the body, discard on
    exception (client.clj:66-89's with-txn macro)."""
    t = conn.txn()
    try:
        yield t
        t.commit()
    finally:
        t.discard()


def with_conflict_as_fail(op: Op, fn):
    """Run fn(); a transaction conflict completes `op` as :fail
    :conflict instead of raising (client.clj:105-167). Other errors
    follow the read-fail / write-indeterminate taxonomy at the call
    site."""
    try:
        return fn()
    except TxnConflict:
        return op.with_(type="fail", error="conflict")


class DgraphError(Exception):
    pass


class TxnConflict(DgraphError):
    """The server aborted the transaction at commit (write-write
    conflict) — always safe to call :fail, the txn did not apply."""


class SetClient(client.Client):
    """Adds as fresh nodes (set.clj:20-53)."""

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        conn = DgraphConn(node_host(test, node), node_port(test, node))
        conn.alter("value: int @index(int) .")
        return SetClient(conn)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                self.conn.mutate([{"type": "element",
                                   "value": op.value}])
                return op.with_(type="ok")
            if op.f == "read":
                rows = self.conn.query(
                    "{ q(func: has(value)) { uid value } }")
                return op.with_(
                    type="ok",
                    value=sorted(r["value"] for r in rows
                                 if "value" in r))
            raise ValueError(f"unknown op {op.f!r}")
        except (DgraphError, socket.timeout, TimeoutError,
                urllib.error.URLError, OSError) as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))

    def close(self, test):
        pass


class UpsertClient(client.Client):
    """Insert-if-absent races via an upsert block (upsert.clj:20-50):
    each txn queries for the key and inserts only when absent."""

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        conn = DgraphConn(node_host(test, node), node_port(test, node))
        conn.alter("key: int @index(int) @upsert .")
        return UpsertClient(conn)

    def invoke(self, test, op: Op) -> Op:
        k = op.value
        try:
            if op.f == "upsert":
                uids = self.conn.mutate(
                    [{"key": k}],
                    query=f"{{ v(func: eq(key, {k})) {{ uid }} }}",
                    cond="@if(eq(len(v), 0))",
                )
                # no uids assigned => the cond failed => lost the race
                return op.with_(type="ok" if uids else "fail",
                                error=None if uids else "already-exists")
            if op.f == "read":
                rows = self.conn.query(
                    f"{{ q(func: eq(key, {k})) {{ uid }} }}")
                return op.with_(type="ok",
                                value=(k, [r["uid"] for r in rows]))
            raise ValueError(f"unknown op {op.f!r}")
        except (DgraphError, socket.timeout, TimeoutError,
                urllib.error.URLError, OSError) as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))

    def close(self, test):
        pass


class UpsertChecker(Checker):
    """At most one upsert per key may succeed, AND the final read must
    show at most one uid per key (upsert.clj:53-68) — the read catches
    double-commits whose second ack was lost to a partition (:info)."""

    def check(self, test, history, opts=None) -> dict:
        ok_upserts: dict = {}
        multi_uids: dict = {}
        for o in _ops(history):
            if o.f == "upsert" and o.is_ok:
                ok_upserts[o.value] = ok_upserts.get(o.value, 0) + 1
            if o.f == "read" and o.is_ok:
                k, uids = o.value
                if len(uids) > 1:
                    multi_uids[k] = uids
        multi = {k: n for k, n in ok_upserts.items() if n > 1}
        return {"valid": not multi and not multi_uids,
                "multiple_upserts": multi,
                "multiple_uids": multi_uids}


def workloads(opts: dict) -> dict:
    # Imported here: dgraph_workloads imports this module's txn layer.
    from . import dgraph_workloads as dw

    return {
        "bank": dw.bank_workload(opts),
        "delete": dw.delete_workload(opts),
        "sequential": dw.sequential_workload(opts),
        "linearizable-register": dw.lr_workload(opts),
        "uid-linearizable-register": dw.uid_lr_workload(opts),
        "long-fork": dw.long_fork_workload(opts),
        "types": dw.types_workload(opts),
        "set": {
            "client": SetClient(),
            "during": gen.stagger(
                opts.get("stagger", 0.05),
                gen.seq({"type": "invoke", "f": "add", "value": x}
                        for x in itertools.count())),
            "final": gen.clients(gen.each(
                lambda: gen.once({"type": "invoke", "f": "read"}))),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "set": checker_mod.set_checker(),
            }),
        },
        "upsert": {
            "client": UpsertClient(),
            # every process races to upsert the same keys
            "during": gen.seq(
                gen.each(lambda k=k: gen.once(
                    {"type": "invoke", "f": "upsert", "value": k}))
                for k in range(opts.get("keys", 20))),
            # final read of every key catches double-commits whose
            # second ack went :info
            "final": gen.clients(gen.seq(
                {"type": "invoke", "f": "read", "value": k}
                for k in range(opts.get("keys", 20)))),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "upsert": UpsertChecker(),
            }),
        },
    }


def dgraph_test(opts: dict) -> dict:
    from ..testlib import noop_test

    from . import dgraph_nemesis

    # Configure span tracing (dgraph/core.clj wires trace/tracing from
    # the CLI's --tracing endpoint; here the endpoint is a JSONL path).
    trace.tracing(opts.get("tracing"))
    wl = workloads(opts)[opts.get("workload", "set")]
    db = DgraphDB(archive_url=opts.get("archive_url"))
    # Failure-mode flags select the full composed nemesis
    # (dgraph/nemesis.clj:122-180); default is partition halves.
    pkg = dgraph_nemesis.package(db, opts)
    if pkg is None:
        pkg = {"nemesis": nemesis.partition_random_halves(),
               "generator": gen.start_stop(10, 10),
               "final_generator": gen.once(
                   {"type": "info", "f": "stop"})}
    generator = gen.time_limit(
        opts.get("time_limit", 60),
        gen.nemesis(pkg["generator"], wl["during"]),
    )
    if wl.get("final") is not None:
        heal = ([gen.nemesis(pkg["final_generator"])]
                if pkg.get("final_generator") is not None else [])
        from .common import ready_gated_final

        generator = gen.phases(
            generator,
            *heal,
            gen.sleep(opts.get("quiesce", 10)),
            # health-gate the final reads: the heal's restart returns
            # before the daemon binds (common.AwaitReadyGen)
            ready_gated_final(db, wl["final"], opts),
        )
    test = noop_test()
    test.update(opts)
    test.update(wl.get("test_opts", {}))
    test.update(
        {
            "name": f"dgraph {opts.get('workload', 'set')}",
            "os": osdist.debian,
            "db": db,
            "client": wl["client"],
            "nemesis": pkg["nemesis"],
            "generator": generator,
            "checker": wl["checker"],
        }
    )
    if wl.get("model") is not None:
        test["model"] = wl["model"]
    return test


def _opt_spec(p) -> None:
    p.add_argument("--workload", default="set",
                   choices=["set", "upsert", "bank", "delete",
                            "sequential", "linearizable-register",
                            "uid-linearizable-register",
                            "long-fork", "types"])
    p.add_argument("--archive-url", dest="archive_url", default=None)
    p.add_argument("--tracing", default=None, metavar="SPANS_JSONL",
                   help="export client/nemesis spans to this JSONL file")
    # Failure-mode flags (dgraph/core.clj's nemesis options)
    for flag in ("kill-alpha", "kill-zero", "fix-alpha",
                 "partition-halves", "partition-ring", "skew-clock",
                 "move-tablet"):
        p.add_argument(f"--{flag}", dest=flag.replace("-", "_"),
                       action="store_true")
    p.add_argument("--skew", default=None,
                   choices=["tiny", "small", "big", "huge"])
    p.add_argument("--interval", type=float, default=10.0,
                   help="seconds between nemesis operations")


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(dgraph_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
