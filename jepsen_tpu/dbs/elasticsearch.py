"""Elasticsearch test suite: version-CAS register and set workloads
over the REST API (reference:
/root/reference/elasticsearch/src/jepsen/elasticsearch/{core,sets}.clj
— the reference drives the Java TransportClient; this speaks REST,
which covers the same index/get/search/versioning semantics).

Workloads:
  - register: a document whose _version drives CAS (core.clj's
    cas-set-client shape) — read = GET, write = unconditional index,
    cas = GET then index with ?version
  - set: op_type=create documents, final read = _refresh + _search
    (sets.clj:50-87) — catches ES's near-real-time search losing
    acknowledged writes
"""

from __future__ import annotations

import itertools
import json
import logging
import random
import socket
import time
import urllib.error
import urllib.parse
import urllib.request

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, generator as gen, models, osdist
from ..history import Op
from .common import ArchiveDB, SuiteCfg, ready_gated_final

log = logging.getLogger("jepsen_tpu.dbs.elasticsearch")

PORT = 9200
INDEX = "jepsen"
DOC_TYPE = "register"
REG_ID = "0"


_suite = SuiteCfg("elasticsearch", PORT, "/opt/elasticsearch")
node_host = _suite.host
node_port = _suite.port


class EsDB(ArchiveDB):
    """Tarball install + daemon (core.clj:212-296). Daemon args use
    real Elasticsearch's -E settings syntax (the sim accepts them
    too)."""

    binary = "elasticsearch"
    log_name = "es.log"
    pid_name = "es.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        return ["-E", f"http.port={node_port(test, node)}",
                "-E", f"node.name={node}"]

    def probe_ready(self, test, node) -> bool:
        url = (f"http://{node_host(test, node)}:{node_port(test, node)}"
               "/_cluster/health")
        with urllib.request.urlopen(url, timeout=2) as resp:
            return resp.status == 200


class EsConn:
    """One node's REST endpoint."""

    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.base = f"http://{host}:{port}"
        self.timeout = timeout

    def request(self, method: str, path: str, body=None, query=None):
        url = self.base + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        if data:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.load(resp)

    def get_doc(self, doc_id: str):
        """(source, version) or (None, 0)."""
        try:
            body = self.request("GET",
                                f"/{INDEX}/{DOC_TYPE}/{doc_id}")
            return body["_source"], int(body["_version"])
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None, 0
            raise

    def index_doc(self, doc_id: str, source: dict, version=None,
                  create=False) -> bool:
        """True on success, False on version conflict (409)."""
        query = {}
        if version is not None:
            query["version"] = version
        if create:
            query["op_type"] = "create"
        try:
            self.request("PUT", f"/{INDEX}/{DOC_TYPE}/{doc_id}",
                         body=source, query=query or None)
            return True
        except urllib.error.HTTPError as e:
            if e.code == 409:
                return False
            raise

    def refresh(self) -> None:
        self.request("POST", f"/{INDEX}/_refresh")

    def search_all(self, page_size: int = 10000,
                   sort_field: str | None = None) -> list:
        """Every document. With sort_field (an INDEXED source field —
        real Elasticsearch rejects sorting on _id), results paginate
        via search_after so >10k-doc indexes aren't silently truncated;
        without one, a single size-capped request is issued (the set
        workload's scale)."""
        if sort_field is None:
            resp = self.request("POST", f"/{INDEX}/_search",
                                body={"query": {"match_all": {}},
                                      "size": page_size})
            return [h["_source"] for h in resp["hits"]["hits"]]
        out = []
        after = None
        while True:
            body = {"query": {"match_all": {}}, "size": page_size,
                    "sort": [{sort_field: "asc"}]}
            if after is not None:
                body["search_after"] = [after]
            resp = self.request("POST", f"/{INDEX}/_search", body=body)
            hits = resp["hits"]["hits"]
            out.extend(h["_source"] for h in hits)
            if len(hits) < page_size:
                return out
            last = hits[-1]["_source"].get(sort_field)
            if last is None or last == after:
                return out  # server ignored the cursor: stop honestly
            after = last


class RegisterClient(client.Client):
    """Version-CAS register in one document. Reads :fail on error;
    writes/cas crash to :info; a 409 conflict is a definite :fail."""

    def __init__(self, conn: EsConn | None = None, timeout: float = 5.0):
        self.conn = conn
        self.timeout = timeout

    def open(self, test, node):
        return RegisterClient(
            EsConn(node_host(test, node), node_port(test, node),
                   timeout=self.timeout), timeout=self.timeout)

    def invoke(self, test, op: Op) -> Op:
        crash = "fail" if op.f == "read" else "info"
        try:
            if op.f == "read":
                source, _ = self.conn.get_doc(REG_ID)
                value = source["value"] if source else None
                return op.with_(type="ok", value=value)
            if op.f == "write":
                self.conn.index_doc(REG_ID, {"value": op.value})
                return op.with_(type="ok")
            if op.f == "cas":
                old, new = op.value
                source, version = self.conn.get_doc(REG_ID)
                if source is None or source["value"] != old:
                    return op.with_(type="fail")
                ok = self.conn.index_doc(REG_ID, {"value": new},
                                         version=version)
                return op.with_(type="ok" if ok else "fail")
            raise ValueError(f"unknown op {op.f!r}")
        except (socket.timeout, TimeoutError):
            return op.with_(type=crash, error="timeout")
        except (urllib.error.URLError, OSError) as e:
            return op.with_(type=crash, error=str(e))


class SetClient(client.Client):
    """op_type=create documents; final read refreshes then searches
    (sets.clj:50-87). An indeterminate add is :info — the document may
    have been indexed."""

    def __init__(self, conn: EsConn | None = None, timeout: float = 5.0):
        self.conn = conn
        self.timeout = timeout

    def open(self, test, node):
        return SetClient(
            EsConn(node_host(test, node), node_port(test, node),
                   timeout=self.timeout), timeout=self.timeout)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                ok = self.conn.index_doc(str(op.value),
                                         {"num": op.value}, create=True)
                return op.with_(type="ok" if ok else "fail")
            if op.f == "read":
                self.conn.refresh()
                values = sorted(
                    d["num"] for d in
                    self.conn.search_all(sort_field="num")
                    if "num" in d)
                return op.with_(type="ok", value=values)
            raise ValueError(f"unknown op {op.f!r}")
        except (socket.timeout, TimeoutError):
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error="timeout")
        except (urllib.error.URLError, OSError) as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))


class DirtyReadClient(client.Client):
    """dirty_read.clj:32-104: writers index docs by id; readers GET
    in-flight ids (:ok when found); the final phase refreshes and does
    one strong read (search-all) per client. A read that shows a value
    absent from EVERY strong read observed a write that never
    committed."""

    def __init__(self, conn: EsConn | None = None, timeout: float = 5.0):
        self.conn = conn
        self.timeout = timeout

    def open(self, test, node):
        return DirtyReadClient(
            EsConn(node_host(test, node), node_port(test, node),
                   timeout=self.timeout), timeout=self.timeout)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "write":
                self.conn.index_doc(str(op.value), {"id": op.value})
                return op.with_(type="ok")
            if op.f == "read":
                source, _ = self.conn.get_doc(str(op.value))
                return op.with_(type="ok" if source else "fail")
            if op.f == "refresh":
                self.conn.refresh()
                return op.with_(type="ok")
            if op.f == "strong-read":
                ids = sorted(d["id"] for d in
                             self.conn.search_all(sort_field="id")
                             if "id" in d)
                return op.with_(type="ok", value=ids)
            raise ValueError(f"unknown op {op.f!r}")
        except (socket.timeout, TimeoutError):
            # reads/refresh/strong-read have no side effects: definite
            # :fail (the module-wide read convention); only writes are
            # indeterminate
            crash = "info" if op.f == "write" else "fail"
            return op.with_(type=crash, error="timeout")
        except (urllib.error.URLError, OSError) as e:
            crash = "info" if op.f == "write" else "fail"
            return op.with_(type=crash, error=str(e))


class DirtyReadChecker(checker_mod.Checker):
    """dirty_read.clj:106-156: dirty = ok reads absent from every
    strong read (saw an uncommitted write); lost = ok writes absent
    from every strong read; nodes agree when all strong reads match."""

    def check(self, test, history, opts=None) -> dict:
        from ..history import ops as _ops

        writes, reads, strong = set(), set(), []
        strong_attempted = 0
        for o in _ops(history):
            if o.f == "strong-read" and o.is_invoke:
                strong_attempted += 1
            if not o.is_ok:
                continue
            if o.f == "write":
                writes.add(o.value)
            elif o.f == "read":
                reads.add(o.value)
            elif o.f == "strong-read":
                strong.append(set(o.value))
        if not strong or len(strong) < strong_attempted:
            # a node whose strong read never completed is exactly the
            # suspect node — partial coverage can't prove anything
            return {"valid": "unknown",
                    "error": f"only {len(strong)}/{strong_attempted} "
                             "strong reads completed"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        dirty = reads - on_some
        lost = writes - on_some
        return {
            "valid": not dirty and not lost and on_all == on_some,
            "nodes_agree": on_all == on_some,
            "read_count": len(reads),
            "on_all_count": len(on_all),
            "on_some_count": len(on_some),
            "not_on_all": sorted(on_some - on_all)[:10],
            "dirty": sorted(dirty)[:10],
            "lost": sorted(lost)[:10],
            "some_lost": sorted(writes - on_all)[:10],
        }


def dirty_rw_gen():
    """Writers emit sequential ids; readers probe recently in-flight
    ids (dirty_read.clj:160-189)."""
    import collections
    import threading

    counter = itertools.count()
    recent: collections.deque = collections.deque(maxlen=32)
    lock = threading.Lock()

    def w(test, process):
        v = next(counter)
        with lock:
            recent.append(v)
        return {"type": "invoke", "f": "write", "value": v}

    def rd(test, process):
        with lock:
            v = random.choice(list(recent)) if recent else 0
        return {"type": "invoke", "f": "read", "value": v}

    return gen.mix([w, rd, rd])


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


def workloads() -> dict:
    return {
        "register": {
            "client": RegisterClient(),
            "during": gen.stagger(0.1, gen.mix([r, w, cas])),
            "model": models.CASRegister(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "linear": checker_mod.linearizable(),
            }),
        },
        "dirty-read": {
            "client": DirtyReadClient(),
            "during": gen.stagger(0.02, dirty_rw_gen()),
            # es_test wraps finals in gen.clients (set-workload
            # convention)
            "final": gen.each(lambda: gen.seq([
                gen.once({"type": "invoke", "f": "refresh"}),
                gen.once({"type": "invoke", "f": "strong-read"}),
            ])),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "dirty-read": DirtyReadChecker(),
            }),
        },
        "set": {
            "client": SetClient(),
            "during": gen.stagger(
                0.05,
                gen.seq({"type": "invoke", "f": "add", "value": x}
                        for x in itertools.count()),
            ),
            "final": gen.each(
                lambda: gen.once({"type": "invoke", "f": "read"})),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "set": checker_mod.set_checker(),
            }),
        },
    }


def es_test(opts: dict) -> dict:
    from ..testlib import noop_test

    wl = workloads()[opts.get("workload", "register")]
    db_ = EsDB(archive_url=opts.get("archive_url"))
    generator = gen.time_limit(
        opts.get("time_limit", 60),
        gen.nemesis(gen.start_stop(10, 10), wl["during"]),
    )
    if wl.get("final") is not None:
        generator = gen.phases(
            generator,
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.sleep(opts.get("quiesce", 10)),
            ready_gated_final(db_, gen.clients(wl["final"]), opts),
        )
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": f"elasticsearch {opts.get('workload', 'register')}",
            "os": osdist.debian,
            "db": db_,
            "client": wl["client"],
            "nemesis": cmn.pick_nemesis(db_, opts),
            "model": wl.get("model"),
            "generator": generator,
            "checker": wl["checker"],
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--workload", default="register",
                   choices=sorted(workloads().keys()))
    p.add_argument("--archive-url", dest="archive_url", default=None)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(es_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
