"""A hermetic Chronos lookalike: the scheduler API subset the chronos
suite drives — POST /scheduler/iso8601 with an R<count>/<start>/PT<n>S
repeating schedule, GET /scheduler/jobs — and, crucially, it actually
RUNS each job's shell command at the scheduled times (with bash, like
real Chronos executes on Mesos agents), so the suite's read-runs path
(parsing the run files jobs write) works identically against the sim
and a real cluster."""

from __future__ import annotations

import argparse
import datetime
import json
import random
import re
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .simbase import Store, build_sim_archive


def parse_iso8601_interval(s: str) -> tuple:
    """R<count>/<start>/PT<interval>S -> (count, start_epoch,
    interval_s)."""
    m = re.fullmatch(r"R(\d*)/([^/]+)/PT(\d+(?:\.\d+)?)S", s)
    if not m:
        raise ValueError(f"bad schedule {s!r}")
    count = int(m.group(1)) if m.group(1) else 1 << 30
    start = datetime.datetime.fromisoformat(
        m.group(2).replace("Z", "+00:00")).timestamp()
    return count, start, float(m.group(3))


class Runner(threading.Thread):
    """Executes one job's command at each scheduled time."""

    def __init__(self, job: dict):
        super().__init__(daemon=True)
        self.job = job

    def run(self):
        count, start, interval = parse_iso8601_interval(
            self.job["schedule"])
        for i in range(count):
            target = start + i * interval
            delay = target - time.time()
            if delay > 0:
                time.sleep(delay)
            try:
                subprocess.run(["bash", "-c", self.job["command"]],
                               timeout=300)
            except (OSError, subprocess.TimeoutExpired):
                pass


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _reply(self, status: int, body) -> None:
        payload = (body if isinstance(body, bytes)
                   else json.dumps(body).encode())
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_POST(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))
        if not self.path.startswith("/scheduler/iso8601"):
            return self._reply(404, {"error": "no route"})
        length = int(self.headers.get("Content-Length") or 0)
        try:
            job = json.loads(self.rfile.read(length))
            parse_iso8601_interval(job["schedule"])  # validate
        except (json.JSONDecodeError, KeyError, ValueError) as e:
            return self._reply(400, {"error": str(e)})

        def record(data):
            jobs = dict(data.get("jobs") or {})
            jobs[str(job["name"])] = job
            new = dict(data)
            new["jobs"] = jobs
            return None, new

        self.store.transact(record)
        Runner(job).start()
        self._reply(204, b"")

    def do_GET(self):
        if not self.path.startswith("/scheduler/jobs"):
            return self._reply(404, {"error": "no route"})

        def read(data):
            return list((data.get("jobs") or {}).values()), None

        self._reply(200, self.store.transact(read))


def parse_args(argv):
    p = argparse.ArgumentParser(description="chronos scheduler sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=4400)
    p.add_argument("--name", default="sim")
    p.add_argument("--master", default=None)  # mesos flag, tolerated
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    httpd = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"chronos-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.chronos_sim", "chronos", "chronos-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
