"""A hermetic Chronos lookalike: the scheduler API subset the chronos
suite drives — POST /scheduler/iso8601 with an R<count>/<start>/PT<n>S
repeating schedule, GET /scheduler/jobs — and, crucially, it actually
RUNS each job's shell command at the scheduled times (with bash, like
real Chronos executes on Mesos agents), so the suite's read-runs path
(parsing the run files jobs write) works identically against the sim
and a real cluster.

With --zk-port, the scheduler API is GATED on the local zookeeper
being reachable: real Chronos keeps its state and leader election in
zk (mesosphere.clj:38-46's zk:// URI), so a node that loses zk
answers 500 until it returns — which makes the suite's kill-zk
component nemesis observable at the client, not just in the process
table."""

from __future__ import annotations

import argparse
import datetime
import json
import random
import re
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .simbase import Store


def parse_iso8601_interval(s: str) -> tuple:
    """R<count>/<start>/PT<interval>S -> (count, start_epoch,
    interval_s)."""
    m = re.fullmatch(r"R(\d*)/([^/]+)/PT(\d+(?:\.\d+)?)S", s)
    if not m:
        raise ValueError(f"bad schedule {s!r}")
    count = int(m.group(1)) if m.group(1) else 1 << 30
    start = datetime.datetime.fromisoformat(
        m.group(2).replace("Z", "+00:00")).timestamp()
    return count, start, float(m.group(3))


class Runner(threading.Thread):
    """Executes one job's command at each scheduled time."""

    def __init__(self, job: dict):
        super().__init__(daemon=True)
        self.job = job

    def run(self):
        count, start, interval = parse_iso8601_interval(
            self.job["schedule"])
        for i in range(count):
            target = start + i * interval
            delay = target - time.time()
            if delay > 0:
                time.sleep(delay)
            try:
                subprocess.run(["bash", "-c", self.job["command"]],
                               timeout=300)
            except (OSError, subprocess.TimeoutExpired):
                pass


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    zk_port: int | None = None
    _zk_cache: tuple = (0.0, True)  # (checked_at, ok) — shared, racy-ok
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _zk_ok(self) -> bool:
        """TCP probe of the node's zookeeper, cached ~0.5s."""
        if self.zk_port is None:
            return True
        import socket

        checked, ok = Handler._zk_cache
        now = time.monotonic()
        if now - checked < 0.5:
            return ok
        try:
            with socket.create_connection(("127.0.0.1", self.zk_port),
                                          timeout=0.5):
                ok = True
        except OSError:
            ok = False
        Handler._zk_cache = (now, ok)
        return ok

    def _reply(self, status: int, body) -> None:
        payload = (body if isinstance(body, bytes)
                   else json.dumps(body).encode())
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_POST(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))
        if not self.path.startswith("/scheduler/iso8601"):
            return self._reply(404, {"error": "no route"})
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length)  # always drain: HTTP/1.1
        # keep-alive would parse an unread body as the next request
        if not self._zk_ok():
            return self._reply(500, {"error": "lost zookeeper"})
        try:
            job = json.loads(body)
            parse_iso8601_interval(job["schedule"])  # validate
        except (json.JSONDecodeError, KeyError, ValueError) as e:
            return self._reply(400, {"error": str(e)})

        def record(data):
            jobs = dict(data.get("jobs") or {})
            jobs[str(job["name"])] = job
            new = dict(data)
            new["jobs"] = jobs
            return None, new

        self.store.transact(record)
        Runner(job).start()
        self._reply(204, b"")

    def do_GET(self):
        if not self.path.startswith("/scheduler/jobs"):
            return self._reply(404, {"error": "no route"})
        if not self._zk_ok():
            return self._reply(500, {"error": "lost zookeeper"})

        def read(data):
            return list((data.get("jobs") or {}).values()), None

        self._reply(200, self.store.transact(read))


def parse_args(argv):
    p = argparse.ArgumentParser(description="chronos scheduler sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=4400)
    p.add_argument("--name", default="sim")
    p.add_argument("--master", default=None)  # mesos flag, tolerated
    p.add_argument("--zk-port", dest="zk_port", type=int, default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    Handler.zk_port = args.zk_port
    httpd = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"chronos-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    """The mesosphere-stack archive (mesosphere.clj + chronos.clj):
    zookeeper, mesos-master, mesos-slave, and chronos launchers —
    every role the real topology runs, sharing one state file."""
    from .simbase import build_multi_sim_archive

    return build_multi_sim_archive(
        dest, "chronos-sim",
        {
            "chronos": "jepsen_tpu.dbs.chronos_sim",
            "zookeeper-server": "jepsen_tpu.dbs.zk_sim",
            "mesos-master": "jepsen_tpu.dbs.mesos_sim",
            "mesos-slave": "jepsen_tpu.dbs.mesos_sim",
        },
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
