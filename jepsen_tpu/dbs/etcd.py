"""etcd test suite: CAS-register linearizability over independent keys
(reference: /root/reference/etcd/src/jepsen/etcd.clj:1-188).

Pieces, mirroring the reference:
  - EtcdDB          — archive install + daemon lifecycle (etcd.clj:51-86)
  - EtcdClient      — HTTP v2-API client with the exception-determinacy
                      taxonomy: reads may :fail on timeout, writes/cas
                      must :info (etcd.clj:103,120-136)
  - r/w/cas         — op generators (etcd.clj:145-147)
  - etcd_test(opts) — the test-map constructor (etcd.clj:149-181)
  - main()          — CLI entry (etcd.clj:183-188)

Cluster addressing is configurable through an "etcd" sub-map in the test
map (dir, ports, addr_fn, archive url, sudo) so the same code paths run
against a real 5-node cluster over SSH or against the in-repo simulator
on one machine (dbs/etcd_sim.py).
"""

from __future__ import annotations

import itertools
import json
import logging
import random
import socket
import time
import urllib.error
import urllib.parse
import urllib.request

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, db, generator as gen, independent, models
from ..control import util as cu
from ..history import Op
from .. import osdist

log = logging.getLogger("jepsen_tpu.dbs.etcd")

DIR = "/opt/etcd"
BINARY = "etcd"
CLIENT_PORT = 2379
PEER_PORT = 2380
VERSION = "v3.1.5"


# ---------------------------------------------------------------------------
# Addressing (etcd.clj:27-48)

def _cfg(test) -> dict:
    return test.get("etcd") or {}


def node_host(test, node) -> str:
    fn = _cfg(test).get("addr_fn")
    return fn(node) if fn else str(node)


def client_port(test, node) -> int:
    ports = _cfg(test).get("client_ports")
    return ports[node] if ports else CLIENT_PORT


def peer_port(test, node) -> int:
    ports = _cfg(test).get("peer_ports")
    return ports[node] if ports else PEER_PORT


def client_url(test, node) -> str:
    return f"http://{node_host(test, node)}:{client_port(test, node)}"


def peer_url(test, node) -> str:
    return f"http://{node_host(test, node)}:{peer_port(test, node)}"


def initial_cluster(test) -> str:
    """\"n1=http://n1:2380,n2=...\" (etcd.clj:42-48)."""
    return ",".join(
        f"{node}={peer_url(test, node)}" for node in test["nodes"]
    )


def node_dir(test, node) -> str:
    d = _cfg(test).get("dir", DIR)
    return d(node) if callable(d) else d


# ---------------------------------------------------------------------------
# DB (etcd.clj:51-86)

class EtcdDB(db.DB, db.Kill, db.Pause, db.LogFiles):
    """Installs and runs one etcd member per node. Implements the
    Kill/Pause process protocols over the daemon pidfile, so the
    kill/pause nemesis packages work against both real clusters and
    the in-repo simulator (which runs as a genuine subprocess)."""

    def __init__(self, version: str = VERSION, url: str | None = None,
                 ready_timeout: float = 30.0):
        self.version = version
        self.url = url
        self.ready_timeout = ready_timeout

    def archive_url(self) -> str:
        return self.url or (
            "https://storage.googleapis.com/etcd/" + self.version
            + "/etcd-" + self.version + "-linux-amd64.tar.gz"
        )

    def setup(self, test, node) -> None:
        self.install(test, node)
        self.start(test, node)

    def install(self, test, node) -> None:
        """Fetch + unpack only — split from start so interposers (the
        faultfs FUSE layer) can mount over the data dir BETWEEN
        install's tree wipe and the daemon opening its files."""
        remote = test["remote"]
        d = node_dir(test, node)
        sudo = _cfg(test).get("sudo", True)
        log.info("%s installing etcd %s", node, self.version)
        cu.install_archive(remote, node, self.archive_url(), d, sudo=sudo)

    def start(self, test, node) -> None:
        remote = test["remote"]
        d = node_dir(test, node)
        cu.start_daemon(
            remote, node, f"{d}/{BINARY}",
            "--name", str(node),
            "--listen-peer-urls", peer_url(test, node),
            "--listen-client-urls", client_url(test, node),
            "--advertise-client-urls", client_url(test, node),
            "--initial-cluster-state", "new",
            "--initial-advertise-peer-urls", peer_url(test, node),
            "--initial-cluster", initial_cluster(test),
            "--log-output", "stdout",
            logfile=f"{d}/etcd.log",
            pidfile=f"{d}/etcd.pid",
            chdir=d,
        )
        self.await_ready(test, node)

    def await_ready(self, test, node) -> None:
        """Poll /version until the member answers (replaces the
        reference's blind 5 s sleep, etcd.clj:76)."""
        deadline = time.monotonic() + self.ready_timeout
        url = client_url(test, node) + "/version"
        while True:
            try:
                with urllib.request.urlopen(url, timeout=2) as resp:
                    if resp.status == 200:
                        return
            except OSError:
                pass
            if time.monotonic() > deadline:
                raise db.SetupFailed(f"etcd on {node} never became ready")
            time.sleep(0.2)

    # -- db.Kill / db.Pause (start(test, node) above doubles as the
    #    Kill revival path; it re-runs start_daemon, which is a no-op
    #    when the pidfile still points at a live process)

    def _pidfile(self, test, node) -> str:
        return f"{node_dir(test, node)}/etcd.pid"

    def kill(self, test, node) -> None:
        cu.stop_daemon(test["remote"], node, self._pidfile(test, node))

    def _signal(self, test, node, sig: str) -> None:
        r = test["remote"].exec(node, ["cat", self._pidfile(test, node)],
                                check=False)
        pid = (r.out or "").strip()
        if pid:
            test["remote"].exec(node, ["kill", f"-{sig}", pid], check=False)

    def pause(self, test, node) -> None:
        self._signal(test, node, "STOP")

    def resume(self, test, node) -> None:
        self._signal(test, node, "CONT")

    def alive(self, test, node):
        return cu.daemon_running(test["remote"], node,
                                 self._pidfile(test, node))

    def teardown(self, test, node) -> None:
        remote = test["remote"]
        d = node_dir(test, node)
        log.info("%s tearing down etcd", node)
        cu.stop_daemon(remote, node, f"{d}/etcd.pid")
        remote.exec(node, ["rm", "-rf", d],
                    sudo=_cfg(test).get("sudo", True), check=False)

    def log_files(self, test, node) -> list:
        return [f"{node_dir(test, node)}/etcd.log"]


# ---------------------------------------------------------------------------
# Client (etcd.clj:96-143)

class EtcdError(Exception):
    def __init__(self, code: int | None, message: str):
        super().__init__(message)
        self.code = code


class EtcdHTTP:
    """Minimal etcd v2 keys-API connection (one base URL, per-request
    sockets — like verschlimmbesserung, no persistent state)."""

    def __init__(self, base_url: str, timeout: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _request(self, method: str, key, form: dict | None = None,
                 query: dict | None = None) -> dict:
        url = f"{self.base_url}/v2/keys/{urllib.parse.quote(str(key))}"
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = urllib.parse.urlencode(form).encode() if form else None
        req = urllib.request.Request(url, data=data, method=method)
        if data:
            req.add_header("Content-Type", "application/x-www-form-urlencoded")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.load(resp)
        except urllib.error.HTTPError as e:
            try:
                body = json.load(e)
            except (json.JSONDecodeError, ValueError):
                raise EtcdError(None, f"HTTP {e.code}") from e
            raise EtcdError(body.get("errorCode"),
                            body.get("message", "")) from e

    def get(self, key, quorum: bool = False):
        """Value string, or None if absent (v/get semantics).
        quorum=True requests a linearizable read."""
        try:
            q = {"quorum": "true"} if quorum else None
            return self._request("GET", key, query=q)["node"]["value"]
        except EtcdError as e:
            if e.code == 100:
                return None
            raise

    def put(self, key, value) -> None:
        self._request("PUT", key, {"value": str(value)})

    def cas(self, key, old, new) -> bool:
        """Compare-and-swap with prevExist; False on compare failure
        (v/cas! {:prev-exist? true}, etcd.clj:114-118)."""
        try:
            self._request("PUT", key, {"value": str(new),
                                       "prevValue": str(old),
                                       "prevExist": "true"})
            return True
        except EtcdError as e:
            if e.code == 101:
                return False
            raise


def parse_long(s):
    """Parses a string to an int; passes through None (etcd.clj:88-92)."""
    return None if s is None else int(s)


class EtcdClient(client.Client):
    """CAS-register client over independent-tuple values, with the
    reference's determinacy taxonomy (etcd.clj:96-136): reads may
    :fail on anything (they don't change state); writes and cas must
    :info on indeterminate errors. errorCode 100 (not-found) is always
    a definite :fail."""

    def __init__(self, conn: EtcdHTTP | None = None, timeout: float = 5.0):
        self.conn = conn
        self.timeout = timeout

    def open(self, test, node):
        return EtcdClient(
            EtcdHTTP(client_url(test, node), timeout=self.timeout),
            timeout=self.timeout,
        )

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        crash = "fail" if op.f == "read" else "info"
        try:
            if op.f == "read":
                value = parse_long(self.conn.get(k, quorum=False))
                return op.with_(type="ok",
                                value=independent.tuple_(k, value))
            if op.f == "write":
                self.conn.put(k, v)
                return op.with_(type="ok")
            if op.f == "cas":
                old, new = v
                ok = self.conn.cas(k, old, new)
                return op.with_(type="ok" if ok else "fail")
            raise ValueError(f"unknown op {op.f!r}")
        except (socket.timeout, TimeoutError):
            return op.with_(type=crash, error="timeout")
        except EtcdError as e:
            if e.code == 100:
                return op.with_(type="fail", error="not-found")
            return op.with_(type=crash, error=str(e))
        except urllib.error.URLError as e:
            if isinstance(e.reason, (socket.timeout, TimeoutError)):
                return op.with_(type=crash, error="timeout")
            return op.with_(type=crash, error=str(e))
        except OSError as e:
            return op.with_(type=crash, error=str(e))


# ---------------------------------------------------------------------------
# Generators (etcd.clj:145-147)

def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


# ---------------------------------------------------------------------------
# Test map (etcd.clj:149-181)

def data_dir(test, node) -> str:
    """etcd's default data dir: <name>.etcd under its cwd (we start
    the daemon with chdir=node_dir and no --data-dir flag)."""
    return f"{node_dir(test, node)}/{node}.etcd"


def client_generator(opts: dict, start_key: int = 0):
    """The independent-keys CAS workload (etcd.clj:166-176). start_key
    offsets the key space so a second instance (the post-heal stability
    window) never collides with the main body's keys."""
    per_key = opts.get("ops_per_key", 300)
    threads_per_key = opts.get("threads_per_key", 10)
    return independent.concurrent_generator(
        threads_per_key,
        itertools.count(start_key),
        lambda k: gen.limit(
            per_key,
            gen.stagger(1 / 30, gen.mix([r, w, cas])),
        ),
    )


def etcd_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = EtcdDB(opts.get("version", VERSION),
                 url=opts.get("archive_url"))
    # fs-break* modes interpose the FUSE fault layer around the data
    # dir: the DB wrapper owns the mount (it must precede the daemon),
    # the nemesis only flips the fault switch — etcd is statically
    # linked Go, so the LD_PRELOAD backend can't touch it
    db_, nemesis_ = cmn.fsfault_wiring(db_, opts, data_dir)
    test = noop_test()
    test.update(
        {
            "name": "etcd",
            "os": osdist.debian,
            "db": db_,
            "client": EtcdClient(),
            "model": models.CASRegister(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "indep": independent.checker(checker_mod.compose({
                    "timeline": checker_mod.timeline_html(),
                    "linear": checker_mod.linearizable(),
                })),
            }),
            "generator": client_generator(opts),
        }
    )
    if nemesis_ is None and cmn.fault_package_wiring(
            test, db_, opts,
            stability_generator=client_generator(opts, start_key=1_000_000),
            corrupt_paths=opts.get("corrupt_paths")
            or [lambda t, n: f"{node_dir(t, n)}/etcd.log"]):
        # composed package: generator/nemesis/checker installed in place
        pass
    else:
        if nemesis_ is None:
            nemesis_ = cmn.pick_nemesis(db_, opts)
        test.update({
            "nemesis": nemesis_,
            "generator": gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(
                    gen.seq(itertools.cycle([
                        gen.sleep(5),
                        {"type": "info", "f": "start"},
                        gen.sleep(5),
                        {"type": "info", "f": "stop"},
                    ])),
                    test["generator"],
                ),
            ),
        })
    # The reference merges opts last (etcd.clj:152,181) so CLI options
    # like nodes/ssh/concurrency override suite defaults. "nemesis" is
    # consumed above (resolved into a nemesis OBJECT) — merging the raw
    # string back over it would hand core.run a str.
    consumed = {"version", "archive_url", "ops_per_key", "threads_per_key",
                "time_limit", "nemesis", "fsfault_opt_dir",
                "nemesis_interval", "seed", "stability_period",
                "fault_ops", "corrupt_paths", "recovery_min_ok", "targets"}
    test.update({k: v for k, v in opts.items() if k not in consumed})
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p, names=cmn.PARTITION_NEMESIS_NAMES
                    + cmn.FSFAULT_NEMESIS_NAMES)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(etcd_test, opt_spec=_opt_spec),
         **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
