"""A hermetic Mesos master/slave lookalike — liveness scenery for the
chronos suite's real topology (reference:
/root/reference/chronos/src/jepsen/mesosphere.clj:57-119 starts
mesos-master on the first 3 sorted nodes and mesos-slave on the rest).

The chronos SIM executes job commands itself (standing in for the
Mesos agents), so this daemon's observable surface is its process
lifecycle: the suite's readiness gating probes it, the kill-mesos-*
nemeses stop/restart it, and log snarfing collects its log. It serves
the two endpoints real tooling pokes: GET /state (role + leader
metadata, master's state.json shape) and GET /health (204)."""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class Handler(BaseHTTPRequestHandler):
    role: str = "master"
    name: str = "sim"
    mean_latency: float = 0.0
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(),
                                        fmt % args))
        sys.stdout.flush()

    def _reply(self, status: int, body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))
        if self.path.startswith("/health"):
            return self._reply(204, b"")
        if self.path.startswith("/state"):
            return self._reply(200, json.dumps({
                "version": "0.23.0",
                "hostname": self.name,
                "role": self.role,
                "activated_slaves": 1,
            }).encode())
        return self._reply(404, b'{"error": "no route"}')


def parse_args(argv):
    p = argparse.ArgumentParser(description="mesos master/slave sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)  # uniform sim surface
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=5050)
    p.add_argument("--name", default="sim")
    p.add_argument("--role", default="master",
                   choices=["master", "slave"])
    # real mesos flags, tolerated (mesosphere.clj:77-119)
    p.add_argument("--zk", default=None)
    p.add_argument("--master", default=None)
    p.add_argument("--quorum", default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.role = args.role
    Handler.name = args.name
    Handler.mean_latency = args.mean_latency
    httpd = ThreadingHTTPServer(("127.0.0.1", args.port), Handler)
    print(f"mesos-{args.role} sim {args.name} serving on {args.port}")
    sys.stdout.flush()
    httpd.serve_forever()


if __name__ == "__main__":
    serve()
