"""Minimal BSON encoder/decoder — just the types the mongodb suites
exchange: documents, arrays, strings, booleans, null, int32/int64,
doubles. (The reference rides the monger/Java driver's codecs; there is
no Python BSON library baked into this environment.)"""

from __future__ import annotations

import struct


def encode(doc: dict) -> bytes:
    body = b"".join(_encode_element(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _encode_element(key: str, v) -> bytes:
    k = key.encode() + b"\x00"
    if isinstance(v, bool):  # before int: bool is an int subclass
        return b"\x08" + k + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + k + struct.pack("<i", v)
        return b"\x12" + k + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + k + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return b"\x02" + k + struct.pack("<i", len(b) + 1) + b + b"\x00"
    if v is None:
        return b"\x0a" + k
    if isinstance(v, dict):
        return b"\x03" + k + encode(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + k + encode(
            {str(i): x for i, x in enumerate(v)})
    raise TypeError(f"can't BSON-encode {type(v)}")


def decode(data: bytes, pos: int = 0) -> tuple:
    """(doc, next_pos)."""
    (length,) = struct.unpack_from("<i", data, pos)
    end = pos + length - 1  # excl. trailing NUL
    pos += 4
    doc: dict = {}
    while pos < end:
        t = data[pos]
        pos += 1
        key_end = data.index(b"\x00", pos)
        key = data[pos:key_end].decode()
        pos = key_end + 1
        if t == 0x01:
            (v,) = struct.unpack_from("<d", data, pos)
            pos += 8
        elif t == 0x02:
            (slen,) = struct.unpack_from("<i", data, pos)
            v = data[pos + 4:pos + 4 + slen - 1].decode()
            pos += 4 + slen
        elif t in (0x03, 0x04):
            v, pos = decode(data, pos)
            if t == 0x04:
                v = [v[str(i)] for i in range(len(v))]
        elif t == 0x08:
            v = data[pos] == 1
            pos += 1
        elif t == 0x0A:
            v = None
        elif t == 0x10:
            (v,) = struct.unpack_from("<i", data, pos)
            pos += 4
        elif t == 0x12:
            (v,) = struct.unpack_from("<q", data, pos)
            pos += 8
        else:
            raise ValueError(f"unsupported BSON type 0x{t:02x}")
        doc[key] = v
    return doc, end + 1
