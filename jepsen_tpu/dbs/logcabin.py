"""LogCabin test suite: a CAS register in the replicated tree, driven
through the ON-NODE `treeops` client binary over the control plane —
the reference's exact access path (reference:
/root/reference/logcabin/src/jepsen/logcabin.clj:163-244: every op is
`c/exec treeops -c <servers> ...` over SSH; LogCabin's RPC has no
standalone wire spec to speak directly).

Ops (logcabin.clj:212-241): read = `treeops read <path>` parsed as
JSON; write = value piped to `treeops write`; cas = conditional write
with `-p <path>:<old>` — the CLI's "CAS failed" error is a definite
:fail, its timeout message a :fail :timed-out."""

from __future__ import annotations

import itertools
import json
import logging
import random

from .. import checker as checker_mod
from .. import cli, client, generator as gen, models, osdist
from ..control import RemoteError
from ..history import Op
from .common import ArchiveDB, SuiteCfg, once, shared_flag
from . import common as cmn

log = logging.getLogger("jepsen_tpu.dbs.logcabin")

PORT = 5254
PATH = "/jepsen"
OP_TIMEOUT = 5


_suite = SuiteCfg("logcabin", PORT, "/opt/logcabin")
node_host = _suite.host
node_port = _suite.port


def server_addrs(test) -> str:
    """host:port,host:port,... (logcabin.clj:52-63)."""
    return ",".join(
        f"{node_host(test, n)}:{node_port(test, n)}"
        for n in test["nodes"]
    )


class LogCabinDB(ArchiveDB):
    """logcabind per node; the first node bootstraps the cluster
    (logcabin.clj:78-100)."""

    binary = "logcabind"
    log_name = "logcabin.log"
    pid_name = "logcabin.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 30.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        args = ["--port", str(node_port(test, node))]
        if node == test["nodes"][0]:
            args.append("--bootstrap")
        return args

    def probe_ready(self, test, node) -> bool:
        import socket

        with socket.create_connection(
            (node_host(test, node), node_port(test, node)), timeout=2
        ):
            return True


def treeops(test, node, *args, stdin=None):
    """Run the on-node treeops client (logcabin.clj:163-210)."""
    d = _suite.dir(test, node)
    return test["remote"].exec(
        node,
        [f"{d}/treeops", "-c", server_addrs(test), "-q",
         "-t", str(OP_TIMEOUT), *args],
        stdin=stdin,
        timeout=OP_TIMEOUT * 4,
    )


class CASClient(client.Client):
    """JSON-encoded register at PATH (logcabin.clj:212-244)."""

    def __init__(self, node=None, flag=None):
        self.node = node
        self.flag = flag or shared_flag()

    def open(self, test, node):
        me = CASClient(node, self.flag)
        once(self.flag, lambda: treeops(
            test, node, "write", PATH, stdin=json.dumps(None)))
        return me

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                out = treeops(test, self.node, "read", PATH).out
                return op.with_(type="ok", value=json.loads(out))
            if op.f == "write":
                treeops(test, self.node, "write", PATH,
                        stdin=json.dumps(op.value))
                return op.with_(type="ok")
            if op.f == "cas":
                old, new = op.value
                r = test["remote"].exec(
                    self.node,
                    [f"{_suite.dir(test, self.node)}/treeops",
                     "-c", server_addrs(test), "-q",
                     "-t", str(OP_TIMEOUT),
                     "-p", f"{PATH}:{json.dumps(old)}",
                     "write", PATH],
                    stdin=json.dumps(new),
                    timeout=OP_TIMEOUT * 4,
                    check=False,
                )
                if r.ok:
                    return op.with_(type="ok")
                if "CAS failed" in (r.err or r.out):
                    return op.with_(type="fail")
                return op.with_(type="info", error=r.err or r.out)
            raise ValueError(f"unknown op {op.f!r}")
        except RemoteError as e:
            msg = str(e)
            if "timed out" in msg.lower() or "timeout" in msg.lower():
                return op.with_(
                    type="fail" if op.f == "read" else "info",
                    error="timed-out")
            return op.with_(
                type="fail" if op.f == "read" else "info", error=msg)
        except (json.JSONDecodeError, ValueError) as e:
            return op.with_(type="fail", error=str(e))


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


def logcabin_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = LogCabinDB(archive_url=opts.get("archive_url"))
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": "logcabin",
            "os": osdist.debian,
            "db": db_,
            "client": CASClient(),
            "nemesis": cmn.pick_nemesis(db_, opts),
            "model": models.CASRegister(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "linear": checker_mod.linearizable(),
            }),
            "generator": gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(
                    gen.start_stop(10, 10),
                    gen.stagger(opts.get("stagger", 0.2),
                                gen.mix([r, w, cas])),
                ),
            ),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--archive-url", dest="archive_url", default=None)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(logcabin_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
