"""Consul test suite: a CAS register in one /v1/kv key (reference:
/root/reference/consul/src/jepsen/consul.clj:1-146).

Pieces, mirroring the reference:
  - ConsulDB     — agent lifecycle: primary bootstraps, the rest join it
                   (consul.clj:22-57); archive mode runs the in-repo sim
                   through the same daemon machinery
  - ConsulKV     — HTTP /v1/kv connection: base64 values,
                   X-Consul-Index, ?cas=<ModifyIndex> check-and-set
                   (consul.clj:66-109)
  - CASClient    — JSON-encoded register with the reference's
                   determinacy taxonomy: reads always :fail on error
                   ("we can always pretend they didn't happen",
                   consul.clj:121-125); writes/cas crash to :info
  - consul_test  — test map; main() — CLI entry
"""

from __future__ import annotations

import base64
import itertools
import json
import logging
import random
import socket
import time
import urllib.error
import urllib.parse
import urllib.request

from .. import checker as checker_mod
from .. import cli, client, generator as gen, models, osdist
from ..history import Op
from .common import ArchiveDB, SuiteCfg
from . import common as cmn

log = logging.getLogger("jepsen_tpu.dbs.consul")

PORT = 8500
KEY = "jepsen"


_suite = SuiteCfg("consul", PORT, "/opt/consul")
node_host = _suite.host
node_port = _suite.port


class ConsulDB(ArchiveDB):
    """Consul agent per node (consul.clj:22-57): the first node runs
    -bootstrap, the rest -join it."""

    binary = "consul"
    log_name = "consul.log"
    pid_name = "consul.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 30.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        primary = test["nodes"][0]
        extra = (["-bootstrap"] if node == primary
                 else ["-join", node_host(test, primary)])
        # data_dir() is the single source of truth — the faultfs FUSE
        # layer mounts over exactly this path
        return ["agent", "-server", "-node", str(node),
                "-data-dir", data_dir(test, node), "-client", "0.0.0.0",
                "-http-port", str(node_port(test, node)), *extra]

    def probe_ready(self, test, node) -> bool:
        url = (f"http://{node_host(test, node)}:{node_port(test, node)}"
               "/v1/status/leader")
        with urllib.request.urlopen(url, timeout=2) as resp:
            return resp.status == 200 and resp.read().strip() != b'""' 


class ConsulKV:
    """One node's /v1/kv endpoint (consul.clj:94-109)."""

    def __init__(self, host: str, port: int, key: str = KEY,
                 timeout: float = 5.0):
        self.base = f"http://{host}:{port}/v1/kv/{key}"
        self.timeout = timeout

    def _request(self, method: str, url: str, data: bytes | None = None):
        req = urllib.request.Request(url, data=data, method=method)
        return urllib.request.urlopen(req, timeout=self.timeout)

    def get(self):
        """(value-bytes | None, modify-index)."""
        try:
            with self._request("GET", self.base) as resp:
                body = json.load(resp)[0]
                return (base64.b64decode(body["Value"]),
                        int(body["ModifyIndex"]))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None, 0
            raise

    def put(self, value: bytes) -> bool:
        with self._request("PUT", self.base, data=value) as resp:
            return resp.read().strip() == b"true"

    def cas(self, value: bytes, new_value: bytes) -> bool:
        """Index-based CAS: read, compare the payload, then PUT with
        ?cas=<ModifyIndex> (consul.clj:100-109)."""
        cur, index = self.get()
        if cur != value:
            return False
        url = f"{self.base}?cas={index}"
        with self._request("PUT", url, data=new_value) as resp:
            return resp.read().strip() == b"true"


class CASClient(client.Client):
    """JSON-encoded CAS register (consul.clj:111-141). Reads :fail on
    any error; writes and cas crash to :info on indeterminate errors."""

    def __init__(self, conn: ConsulKV | None = None, timeout: float = 5.0):
        self.conn = conn
        self.timeout = timeout

    def open(self, test, node):
        return CASClient(
            ConsulKV(node_host(test, node), node_port(test, node),
                     timeout=self.timeout),
            timeout=self.timeout,
        )

    def setup(self, test):
        try:
            self.conn.put(json.dumps(None).encode())
        except OSError:
            pass  # another client may already have seeded the key

    def invoke(self, test, op: Op) -> Op:
        crash = "fail" if op.f == "read" else "info"
        try:
            if op.f == "read":
                cur, _ = self.conn.get()
                value = json.loads(cur) if cur else None
                return op.with_(type="ok", value=value)
            if op.f == "write":
                self.conn.put(json.dumps(op.value).encode())
                return op.with_(type="ok")
            if op.f == "cas":
                old, new = op.value
                ok = self.conn.cas(json.dumps(old).encode(),
                                   json.dumps(new).encode())
                return op.with_(type="ok" if ok else "fail")
            raise ValueError(f"unknown op {op.f!r}")
        except (socket.timeout, TimeoutError):
            return op.with_(type=crash, error="timeout")
        except (urllib.error.URLError, OSError) as e:
            return op.with_(type=crash, error=str(e))


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


def data_dir(test, node) -> str:
    """The agent's -data-dir (daemon_args passes {dir}/data)."""
    return f"{_suite.dir(test, node)}/data"


def consul_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = ConsulDB(archive_url=opts.get("archive_url"))
    # consul is a statically linked Go binary: the charybdefs-analog
    # fault modes need the FUSE backend (cmn.fsfault_wiring)
    db_, nemesis_ = cmn.fsfault_wiring(db_, opts, data_dir)
    if nemesis_ is None:
        nemesis_ = cmn.pick_nemesis(db_, opts)
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": "consul",
            "os": osdist.debian,
            "db": db_,
            "client": CASClient(),
            "nemesis": nemesis_,
            "model": models.CASRegister(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "linear": checker_mod.linearizable(),
            }),
            "generator": gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(
                    gen.seq(itertools.cycle([
                        gen.sleep(5),
                        {"type": "info", "f": "start"},
                        gen.sleep(5),
                        {"type": "info", "f": "stop"},
                    ])),
                    gen.stagger(1 / 10, gen.mix([r, w, cas])),
                ),
            ),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p, names=cmn.NEMESIS_NAMES
                    + cmn.FSFAULT_NEMESIS_NAMES)
    p.add_argument("--archive-url", dest="archive_url", default=None,
                   help="consul release archive (or the in-repo sim "
                        "archive for hermetic runs).")


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(consul_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
