"""Aerospike test suite: generation-CAS register and counter workloads
(reference: /root/reference/aerospike/src/aerospike/{core,support,
cas_register,counter}.clj — the reference rides the Java client; this
speaks the wire subset in aerospike_proto).

Workloads:
  - cas-register: read returns (generation, value); cas re-writes with
    GENERATION_EQUAL — result code 3 is a definite :fail (someone else
    won the race); writes are unconditional.
  - counter: unconditional add-like writes of a running total plus
    reads; the counter checker bounds the final value by acknowledged
    increments.
"""

from __future__ import annotations

import itertools
import logging
import random
import socket
import time

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, generator as gen, independent, models, \
    nemesis, osdist
from ..control import util as cu
from ..history import Op
from . import aerospike_proto as ap
from .common import (ArchiveDB, ArchiveKillNemesis, SuiteCfg,
                     ready_gated_final)

log = logging.getLogger("jepsen_tpu.dbs.aerospike")

PORT = 3000
KEY = "jepsen"


_suite = SuiteCfg("aerospike", PORT, "/opt/aerospike")
node_host = _suite.host
node_port = _suite.port


class AerospikeDB(ArchiveDB):
    """asd per node (support.clj's install/configure/start)."""

    binary = "asd"
    log_name = "aerospike.log"
    pid_name = "aerospike.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        return ["--port", str(node_port(test, node))]

    def log_files(self, test, node) -> list:
        # netem.log is PauseNemesis(:net)'s chain output — the only
        # post-mortem evidence when a backgrounded `tc qdisc add` fails
        # (we can't surface errors live; see PauseNemesis._pause).
        return super().log_files(test, node) + [
            f"{_suite.dir(test, node)}/netem.log"]

    def probe_ready(self, test, node) -> bool:
        conn = ap.AerospikeConn(node_host(test, node),
                                node_port(test, node),
                                timeout=2.0, connect_timeout=2.0)
        try:
            conn.get("__probe__")
            return True
        except ap.AerospikeError:
            return True  # server answered: protocol is up
        finally:
            conn.close()


class CasRegisterClient(client.Client):
    """Register via generation CAS (aerospike's cas-register
    workload): read = get(gen, value); cas = read then put with
    GENERATION_EQUAL; generation mismatch (code 3) is a definite
    :fail."""

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        return CasRegisterClient(
            ap.AerospikeConn(node_host(test, node),
                             node_port(test, node)))

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                _gen, bins = self.conn.get(KEY)
                return op.with_(
                    type="ok",
                    value=bins.get("value") if bins else None)
            if op.f == "write":
                self.conn.put(KEY, {"value": op.value})
                return op.with_(type="ok")
            if op.f == "cas":
                old, new = op.value
                generation, bins = self.conn.get(KEY)
                if bins is None or bins.get("value") != old:
                    return op.with_(type="fail")
                try:
                    self.conn.put(KEY, {"value": new},
                                  expected_generation=generation)
                    return op.with_(type="ok")
                except ap.AerospikeError as e:
                    if e.code == ap.RESULT_GENERATION:
                        return op.with_(type="fail",
                                        error="generation-mismatch")
                    raise
            raise ValueError(f"unknown op {op.f!r}")
        except ap.AerospikeError as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=f"code-{e.code}")
        except (socket.timeout, TimeoutError):
            return op.with_(
                type="fail" if op.f == "read" else "info",
                error="timeout")
        except (ConnectionError, OSError) as e:
            return op.with_(
                type="fail" if op.f == "read" else "info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


class CounterClient(client.Client):
    """Counter via read-increment-write with generation CAS retried
    until it lands (aerospike's counter workload shape); emits :add ops
    with the delta and :read ops with the observed total, for the
    framework counter checker."""

    RETRIES = 16

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        return CounterClient(
            ap.AerospikeConn(node_host(test, node),
                             node_port(test, node)))

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                _gen, bins = self.conn.get(KEY)
                return op.with_(
                    type="ok",
                    value=bins.get("count", 0) if bins else 0)
            if op.f == "add":
                for _ in range(self.RETRIES):
                    generation, bins = self.conn.get(KEY)
                    current = bins.get("count", 0) if bins else 0
                    try:
                        self.conn.put(
                            KEY, {"count": current + op.value},
                            expected_generation=generation or 0)
                        return op.with_(type="ok")
                    except ap.AerospikeError as e:
                        if e.code != ap.RESULT_GENERATION:
                            raise
                return op.with_(type="fail", error="retries-exhausted")
            raise ValueError(f"unknown op {op.f!r}")
        except ap.AerospikeError as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=f"code-{e.code}")
        except (socket.timeout, TimeoutError, ConnectionError, OSError) as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


class SetClient(client.Client):
    """A set as CAS-free string appends on a single bin per key
    (aerospike/set.clj:11-45): add appends " v", read splits the bin
    into a sorted set of ints. Values are independent (k, v) tuples."""

    def __init__(self, conn=None):
        self.conn = conn

    def open(self, test, node):
        return SetClient(
            ap.AerospikeConn(node_host(test, node),
                             node_port(test, node)))

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "read":
                _gen, bins = self.conn.get(("set", k))
                raw = (bins or {}).get("value") or ""
                vals = sorted(int(x) for x in raw.split() if x)
                return op.with_(type="ok",
                                value=independent.tuple_(k, vals))
            if op.f == "add":
                self.conn.append(("set", k), {"value": f" {v}"})
                return op.with_(type="ok")
            raise ValueError(f"unknown op {op.f!r}")
        except ap.AerospikeError as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=f"code-{e.code}")
        except (socket.timeout, TimeoutError):
            return op.with_(
                type="fail" if op.f == "read" else "info",
                error="timeout")
        except (ConnectionError, OSError) as e:
            return op.with_(
                type="fail" if op.f == "read" else "info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


class KillNemesis(ArchiveKillNemesis):
    """The reference's bounded-dead-set killer
    (aerospike/src/aerospike/nemesis.clj:17-58): the generic ArchiveDB
    kill/restart plus aerospike's :revive/:recluster maintenance
    commands (issued best-effort via asinfo at the node's address)."""

    def extra_op(self, test, node, op):
        if op.f in ("revive", "recluster"):
            r = test["remote"].exec(
                node,
                ["asinfo", "-h", node_host(test, node),
                 "-p", str(node_port(test, node)), "-v", op.f],
                check=False)
            return "ok" if getattr(r, "ok", False) else "not-running"
        return super().extra_op(test, node, op)


def kill_nemesis(db: "AerospikeDB", max_dead: int = 2) -> KillNemesis:
    return KillNemesis(db, max_dead)


class PauseNemesis(nemesis.Nemesis):
    """The reference's pause nemesis (aerospike/src/aerospike/
    pause.clj:17-85): pause a bounded set of "master" nodes so
    in-flight writes get trapped in their memory, then revive. Two
    modes:

      process — SIGSTOP the asd process; resume SIGCONTs it
                (pause.clj:44-45,75). Targeted by pidfile rather than
                killall so hermetic multi-node-on-one-host clusters
                only freeze the victim's daemon.
      net     — inject a large netem delay AND spawn a self-restoring
                mini-daemon (`sleep N; tc qdisc del`) in the
                background, because the delay also severs our own
                control connection — the reference's "terrible hack"
                (pause.clj:46-56). Resume is a no-op: the node heals
                itself (pause.clj:76).

    masters_limit bounds CONCURRENTLY paused nodes (pause.clj:25-27).
    Speaks pause/resume and the shared start/stop aliases, so the
    suites' standard nemesis generator drives it unchanged. A paused
    node's client ops time out into :info (indeterminate) — the
    history stays checkable because set/linearizability semantics
    treat those as concurrent forever."""

    def __init__(self, db: "AerospikeDB", mode: str = "process",
                 masters_limit: int = 1, pause_delay: float = 30.0):
        assert mode in ("process", "net"), mode
        self.db = db
        self.mode = mode
        self.masters_limit = masters_limit
        self.pause_delay = pause_delay
        # how long to wait after backgrounding the netem chain for the
        # delay to take effect (tests zero this)
        self.settle_s = 1.0
        self.paused: set = set()

    def _pidfile(self, test, node) -> str:
        d = _suite.dir(test, node)
        return f"{d}/{self.db.pid_name}"

    def _pause(self, test, node) -> str:
        remote = test["remote"]
        if self.mode == "process":
            remote.exec(
                node,
                ["bash", "-c",
                 f"kill -STOP $(cat {self._pidfile(test, node)})"],
                sudo=_suite.sudo(test))
            return "paused"
        delay_ms = int(self.pause_delay * 1000)
        hold_s = int(self.pause_delay) + 1
        # The ENTIRE add/sleep/del chain is the backgrounded
        # self-restoring mini-daemon: the netem delay severs our own
        # control connection the instant `tc qdisc add` lands, so even
        # the add must be in the subshell — a foreground add would trap
        # this exec's own reply behind the delay it just installed
        # (pause.clj:46-56 backgrounds the whole chain the same way).
        # Consequence (inherent to the reference's "terrible hack"): an
        # add failure can't be surfaced — once the delay lands we can't
        # talk to the node until it self-heals, so there is no useful
        # moment to verify. Chain output goes to netem.log in the suite
        # dir (snarfed with the other logs) for post-mortem instead.
        chain_log = f"{_suite.dir(test, node)}/netem.log"
        remote.exec(
            node,
            ["nohup", "bash", "-c",
             f"(tc qdisc add dev eth0 root netem delay {delay_ms}ms 1ms "
             f"distribution normal; "
             f"sleep {hold_s}; tc qdisc del dev eth0 root) "
             f"</dev/null >>{chain_log} 2>&1 &"],
            sudo=_suite.sudo(test))
        return "net-delayed"

    def _resume(self, test, node) -> str:
        if self.mode == "process":
            test["remote"].exec(
                node,
                ["bash", "-c",
                 f"kill -CONT $(cat {self._pidfile(test, node)})"],
                sudo=_suite.sudo(test))
            return "resumed"
        return "self-restoring"  # pause.clj:76 — :net resume is nil

    def invoke(self, test, op: Op) -> Op:
        if op.f in ("pause", "start"):
            budget = max(self.masters_limit - len(self.paused), 0)
            if op.value:
                # explicit targets are still bounded: masters_limit
                # caps CONCURRENT pauses however the op arrives
                targets = [n for n in op.value
                           if n not in self.paused][:budget]
            else:
                candidates = [n for n in test["nodes"]
                              if n not in self.paused]
                targets = random.sample(
                    candidates, min(budget, len(candidates)))
            out = {}
            for node in targets:
                out[node] = self._pause(test, node)
                self.paused.add(node)
            # One settle for the whole batch (not per node — that would
            # stagger the "concurrent" pause window by settle_s per
            # node): give the backgrounded netem adds a beat to land
            # before reporting the nodes paused.
            if self.mode == "net" and out and self.settle_s:
                time.sleep(self.settle_s)
            return op.with_(type="info", value=out or "at-limit")
        if op.f in ("resume", "stop"):
            out = {}
            for node in sorted(self.paused):
                out[node] = self._resume(test, node)
            self.paused.clear()
            return op.with_(type="info", value=out or "none-paused")
        raise ValueError(f"unknown nemesis op {op.f!r}")

    def teardown(self, test):
        for node in sorted(self.paused):
            try:
                self._resume(test, node)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        self.paused.clear()


def pause_nemesis(db: "AerospikeDB", mode: str = "process",
                  masters_limit: int = 1) -> PauseNemesis:
    return PauseNemesis(db, mode, masters_limit)


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


def add(test, process):
    return {"type": "invoke", "f": "add", "value": 1}


def workloads(opts: dict) -> dict:
    return {
        "cas-register": {
            "client": CasRegisterClient(),
            "during": gen.stagger(opts.get("stagger", 0.05),
                                  gen.mix([r, w, cas, cas])),
            "model": models.CASRegister(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "linear": checker_mod.linearizable(),
            }),
        },
        "counter": {
            "client": CounterClient(),
            "during": gen.stagger(opts.get("stagger", 0.05),
                                  gen.mix([add, add, r])),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "counter": checker_mod.counter(),
            }),
        },
        "set": _set_workload(opts),
    }


def _set_workload(opts: dict) -> dict:
    """CAS-free appends across independent keys, 5 clients per key,
    with a deferred final read of every key that was touched
    (aerospike/set.clj:47-71 — the max-key atom + derefer dance)."""
    import itertools
    import threading

    seen_keys: list = []
    lock = threading.Lock()

    def fgen(k):
        with lock:
            seen_keys.append(k)
        ctr = itertools.count()  # per-key, captured by the closure

        def add_op(test, process):
            return {"type": "invoke", "f": "add", "value": next(ctr)}

        return gen.limit(opts.get("ops_per_key", 200),
                         gen.stagger(opts.get("stagger", 0.05), add_op))

    # derefer calls its thunk per op request; the reference wraps the
    # final generator in a delay (set.clj:62-71) so it's built ONCE at
    # first deref — memoize or every request builds a fresh generator
    # and the final phase never exhausts.
    final_cache: list = []

    def final():
        with lock:
            if not final_cache:
                ks = sorted(seen_keys)
                final_cache.append(independent.concurrent_generator(
                    5, ks,
                    lambda k: gen.each(
                        lambda: gen.once(
                            {"type": "invoke", "f": "read"}))))
            return final_cache[0]

    return {
        "client": SetClient(),
        "during": independent.concurrent_generator(
            5, itertools.count(), fgen),
        "final": gen.derefer(final),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "sets": independent.checker(checker_mod.set_checker()),
        }),
    }


def aerospike_test(opts: dict) -> dict:
    from ..testlib import noop_test

    wl = workloads(opts)[opts.get("workload", "cas-register")]
    db_ = AerospikeDB(archive_url=opts.get("archive_url"))
    generator = gen.time_limit(
        opts.get("time_limit", 60),
        gen.nemesis(gen.start_stop(10, 10), wl["during"]),
    )
    if wl.get("final") is not None:
        generator = gen.phases(
            generator,
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.sleep(opts.get("quiesce", 10)),
            ready_gated_final(db_, gen.clients(wl["final"]), opts),
        )
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": f"aerospike {opts.get('workload', 'cas-register')}",
            "os": osdist.debian,
            "db": db_,
            "client": wl["client"],
            "nemesis": cmn.pick_nemesis(db_, opts, extra={
                "pause": lambda: PauseNemesis(db_, "process"),
                "pause-net": lambda: PauseNemesis(db_, "net"),
            }),
            "model": wl.get("model"),
            "generator": generator,
            "checker": wl["checker"],
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p, names=cmn.NEMESIS_NAMES + ("pause", "pause-net"))
    p.add_argument("--workload", default="cas-register",
                   choices=["cas-register", "counter", "set"])
    p.add_argument("--archive-url", dest="archive_url", default=None)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(aerospike_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
