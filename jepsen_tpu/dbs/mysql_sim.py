"""A hermetic MySQL-protocol server over the shared mini SQL engine —
the test double for the galera / percona / mysql-cluster / tidb suites.

Transactions hold the shared flock from BEGIN to COMMIT (bounded wait);
contention surfaces as error 1213 with the exact
"Deadlock found when trying to get lock; try restarting transaction"
message the suites' txn-abort taxonomy matches on (galera.clj /
postgres_rds.clj both key on this string). Duplicate keys are 1062,
parse errors 1064 — the MySQL-side shapes of the engine's SQLSTATEs.

Auth: accepts any user with mysql_native_password (including empty
passwords) — it's a test double, not a fortress.
"""

from __future__ import annotations

import argparse
import os
import random
import socketserver
import struct
import sys
import time

from . import crdb_sim, mysql_proto as mp
from .simbase import Store, StoreTxn, build_sim_archive

TXN_LOCK_TIMEOUT = 2.0
SESSION_IDLE_TIMEOUT = 120.0

_SQLSTATE_TO_MYSQL = {
    "40001": (mp.ER_LOCK_DEADLOCK, mp.DEADLOCK_MSG, "40001"),
    "23505": (mp.ER_DUP_ENTRY, "Duplicate entry for key 'PRIMARY'",
              "23000"),
    "42P01": (mp.ER_NO_SUCH_TABLE, "Table doesn't exist", "42S02"),
}


def _to_mysql_error(e: crdb_sim.SqlError) -> bytes:
    code, msg, state = _SQLSTATE_TO_MYSQL.get(
        e.sqlstate, (mp.ER_PARSE_ERROR, e.message, "42000"))
    return mp.err_packet(code, msg, state)


class Handler(socketserver.BaseRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0

    def handle(self):
        self.request.settimeout(SESSION_IDLE_TIMEOUT)
        io = mp.PacketIO(self.request)
        txn = StoreTxn(self.store)
        try:
            # handshake v10: 8+12-byte nonce, protocol 41 caps
            nonce = os.urandom(20).replace(b"\x00", b"\x01")
            greeting = (
                b"\x0a" + b"jepsen-tpu-mysql-sim\x00"
                + struct.pack("<I", os.getpid() & 0xFFFFFFFF)
                + nonce[:8] + b"\x00"
                + struct.pack("<H", 0xF7FF)      # caps low
                + b"\x21"                        # charset
                + struct.pack("<H", 0x0002)      # status
                + struct.pack("<H", 0x000F)      # caps high (plugin auth)
                + bytes([21]) + b"\x00" * 10
                + nonce[8:20] + b"\x00"
                + b"mysql_native_password\x00"
            )
            io.write_packet(greeting)
            io.read_packet()  # handshake response: accept anyone
            io.write_packet(mp.ok_packet())

            while True:
                io.reset_seq()
                payload = io.read_packet()
                io.seq = 1
                if not payload or payload[0] == 0x01:  # COM_QUIT
                    return
                if payload[0] != 0x03:  # only COM_QUERY
                    io.write_packet(mp.err_packet(
                        1047, f"unsupported command {payload[0]}"))
                    continue
                sql = payload[1:].decode()
                if self.mean_latency > 0:
                    time.sleep(random.expovariate(1.0 / self.mean_latency))
                txn = self._statement(io, sql, txn)
        except (ConnectionError, TimeoutError, OSError,
                mp.MySqlProtocolError):
            pass
        finally:
            txn.rollback()

    def _statement(self, io: mp.PacketIO, sql: str,
                   txn: StoreTxn) -> StoreTxn:
        s = sql.strip().rstrip(";").strip().upper()
        try:
            if s in ("BEGIN", "START TRANSACTION"):
                if not txn.active and not txn.begin(
                        timeout=TXN_LOCK_TIMEOUT):
                    raise crdb_sim.SqlError("40001", mp.DEADLOCK_MSG)
                io.write_packet(mp.ok_packet())
                return txn
            if s == "COMMIT":
                if txn.active:
                    txn.commit()
                io.write_packet(mp.ok_packet())
                return txn
            if s == "ROLLBACK":
                txn.rollback()
                io.write_packet(mp.ok_packet())
                return txn
            if s.startswith("SET "):  # isolation levels etc: accepted
                io.write_packet(mp.ok_packet())
                return txn
            if txn.active:
                cols, rows, tag = crdb_sim.execute(txn.data, sql)
            else:
                one = StoreTxn(self.store)
                if not one.begin(timeout=TXN_LOCK_TIMEOUT):
                    raise crdb_sim.SqlError("40001", mp.DEADLOCK_MSG)
                try:
                    cols, rows, tag = crdb_sim.execute(one.data, sql)
                    if tag.startswith("SELECT"):
                        one.rollback()  # reads don't rewrite the state
                    else:
                        one.commit()
                except BaseException:
                    one.rollback()
                    raise
            self._send_result(io, cols, rows, tag)
        except crdb_sim.SqlError as e:
            io.write_packet(_to_mysql_error(e))
        return txn

    @staticmethod
    def _send_result(io: mp.PacketIO, cols, rows, tag) -> None:
        if not cols:
            affected = 0
            parts = tag.split()
            if parts and parts[-1].isdigit():
                affected = int(parts[-1])
            io.write_packet(mp.ok_packet(affected))
            return
        io.write_packet(mp.lenenc_int(len(cols)))
        for c in cols:
            io.write_packet(mp.column_packet(c))
        io.write_packet(mp.eof_packet())
        for row in rows:
            io.write_packet(mp.row_packet(row))
        io.write_packet(mp.eof_packet())


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def parse_args(argv):
    p = argparse.ArgumentParser(description="mysql-protocol sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=3306)
    p.add_argument("--name", default="sim")
    # flags various launchers pass, tolerated:
    p.add_argument("--wsrep-cluster-address", default=None)
    p.add_argument("--ndb-connectstring", default=None)
    p.add_argument("--store", default=None)
    p.add_argument("--path", default=None)
    # the real mysqld accepts a rich flag surface (--ndb-nodeid,
    # --datadir, ...); unknown flags are ignored, not fatal
    args, _unknown = p.parse_known_args(argv)
    return args


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    srv = Server(("127.0.0.1", args.port), Handler)
    print(f"mysql-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, binary: str = "mysqld",
                  mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.mysql_sim", binary, f"{binary}-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
