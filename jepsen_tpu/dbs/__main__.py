"""Suite discovery: `python -m jepsen_tpu.dbs` lists every per-DB
suite, its runner module, and its --workload choices (pulled from each
suite's argparse surface), so a user can find the right entry point
without reading source."""

from __future__ import annotations

import argparse
import importlib

from . import SUITES


def workload_choices(modname: str) -> list:
    """The --workload choices a suite's opt spec declares ([] when the
    suite has a single fixed workload, or when the module can't load —
    one broken suite must not take down the whole listing)."""
    try:
        mod = importlib.import_module(modname)
        spec = (getattr(mod, "_opt_spec", None)
                or getattr(mod, "opt_spec", None))
        if spec is None:
            return []
        p = argparse.ArgumentParser(allow_abbrev=False)
        spec(p)
    except Exception:
        return []
    for action in p._actions:
        if "--workload" in getattr(action, "option_strings", ()):
            return list(action.choices or [])
    return []


def main() -> None:
    print(f"{len(SUITES)} per-DB suites "
          "(run: python -m <module> test --help)\n")
    width = max(len(n) for n in SUITES)
    for name, modname in sorted(SUITES.items()):
        wls = workload_choices(modname)
        extra = f"  workloads: {', '.join(wls)}" if wls else ""
        print(f"  {name:<{width}}  {modname}{extra}")


if __name__ == "__main__":
    main()
