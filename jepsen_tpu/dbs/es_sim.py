"""A hermetic Elasticsearch lookalike: the REST subset the
elasticsearch suite drives — document PUT with internal version CAS
(?version=N → 409 on mismatch), op_type=create, GET by id, _refresh,
_search (match_all), and _cluster/health (reference behavior:
elasticsearch/src/jepsen/elasticsearch/{core,sets}.clj — the reference
uses the Java TransportClient; the suite here speaks REST, which is
what a TPU-era deployment would use anyway).

Shared flock-guarded JSON state across member processes. A "refresh
lag" knob (--refresh-lag) makes _search miss recent writes until
_refresh is called, reproducing ES's near-real-time search semantics
(the thing the sets test exists to catch)."""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .simbase import Store, build_sim_archive


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    refresh_lag: bool = True
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _jitter(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))

    def _reply(self, status: int, body: dict):
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _parts(self):
        u = urllib.parse.urlparse(self.path)
        return [p for p in u.path.split("/") if p], \
            urllib.parse.parse_qs(u.query)

    def do_GET(self):
        self._jitter()
        parts, _q = self._parts()
        if parts[:2] == ["_cluster", "health"]:
            return self._reply(200, {"status": "green"})
        if len(parts) == 3:  # /{index}/{type}/{id}
            index, _type, doc_id = parts

            def read(data):
                docs = (data.get("indices") or {}).get(index) or {}
                return docs.get(doc_id), None

            doc = self.store.transact(read)
            if doc is None:
                return self._reply(
                    404, {"found": False, "_id": doc_id})
            return self._reply(200, {
                "found": True, "_id": doc_id,
                "_version": doc["version"], "_source": doc["source"],
            })
        self._reply(404, {"error": "no route"})

    def do_POST(self):
        self._jitter()
        parts, q = self._parts()
        if parts and parts[-1] == "_refresh":

            def refresh(data):
                new = dict(data)
                new["refreshed_at"] = int(data.get("seq") or 0)
                return None, new

            self.store.transact(refresh)
            return self._reply(200, {"_shards": {"failed": 0}})
        if parts and parts[-1] == "_search":
            index = parts[0] if len(parts) > 1 else None
            length = int(self.headers.get("Content-Length") or 0)
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError:
                body = {}
            if not isinstance(body, dict):
                body = {}

            def search(data):
                docs = (data.get("indices") or {}).get(index) or {}
                horizon = (int(data.get("refreshed_at") or 0)
                           if self.refresh_lag else float("inf"))
                hits = [
                    {"_id": i, "_source": d["source"],
                     "_version": d["version"]}
                    for i, d in docs.items()
                    if d["seq"] <= horizon
                ]
                sort = body.get("sort") or []
                field = None
                for entry in sort:
                    if isinstance(entry, dict) and entry:
                        field = next(iter(entry))
                        break
                if field:
                    def sort_key(h, field=field):
                        return (h["_source"].get(field)
                                if field != "_id" else str(h["_id"]))

                    hits.sort(key=lambda h: (sort_key(h) is None,
                                             sort_key(h)))
                    after = body.get("search_after")
                    if after:
                        hits = [h for h in hits
                                if sort_key(h) is not None
                                and sort_key(h) > after[0]]
                size = body.get("size")
                if isinstance(size, int) and size >= 0:
                    hits = hits[:size]
                return hits, None

            hits = self.store.transact(search)
            return self._reply(200, {
                "hits": {"total": len(hits), "hits": hits}})
        # POST /{index}/{type}/{id} is index-like too
        self._index_doc(parts, q)

    def do_PUT(self):
        self._jitter()
        parts, q = self._parts()
        self._index_doc(parts, q)

    def _index_doc(self, parts, q):
        if len(parts) != 3:
            return self._reply(400, {"error": "bad doc path"})
        index, _type, doc_id = parts
        length = int(self.headers.get("Content-Length") or 0)
        try:
            source = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            return self._reply(400, {"error": "bad json"})
        want_version = q.get("version")
        create_only = q.get("op_type", [""])[0] == "create"

        def write(data):
            indices = dict(data.get("indices") or {})
            docs = dict(indices.get(index) or {})
            cur = docs.get(doc_id)
            if create_only and cur is not None:
                return (409, {"error": "version_conflict_engine_exception",
                              "reason": "document already exists"}), None
            if want_version is not None:
                want = int(want_version[0])
                if cur is None or cur["version"] != want:
                    return (409, {
                        "error": "version_conflict_engine_exception",
                        "reason": f"current version "
                                  f"[{cur['version'] if cur else 0}] is "
                                  f"different than the one provided "
                                  f"[{want}]"}), None
            seq = int(data.get("seq") or 0) + 1
            docs[doc_id] = {
                "source": source,
                "version": (cur["version"] + 1) if cur else 1,
                "seq": seq,
            }
            indices[index] = docs
            new = dict(data)
            new["indices"] = indices
            new["seq"] = seq
            return (200 if cur else 201, {
                "_id": doc_id, "_version": docs[doc_id]["version"],
                "result": "updated" if cur else "created"}), new

        status, body = self.store.transact(write)
        self._reply(status, body)


def parse_args(argv):
    p = argparse.ArgumentParser(description="elasticsearch REST sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=9200)
    p.add_argument("--name", default="sim")
    p.add_argument("--no-refresh-lag", action="store_true")
    # real elasticsearch's settings syntax: -E key=value (repeatable)
    p.add_argument("-E", action="append", default=[], dest="settings")
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    settings = dict(s.split("=", 1) for s in args.settings if "=" in s)
    port = int(settings.get("http.port", args.port))
    name = settings.get("node.name", args.name)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    Handler.refresh_lag = not args.no_refresh_lag
    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    print(f"es-sim {name} serving on {port}, data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.es_sim", "elasticsearch", "es-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
