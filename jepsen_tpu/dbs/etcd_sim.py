"""A hermetic etcd lookalike: an HTTP server speaking the subset of the
etcd v2 keys API that the etcd suite's client uses (GET/PUT/DELETE on
/v2/keys, prevValue/prevExist compare-and-swap, errorCodes 100/101/105),
plus /version.

This is NOT part of the framework proper — it is the test double that
lets the etcd suite run its real code paths (archive install, daemon
start/stop, HTTP client taxonomy) on one machine with no network access
(SURVEY.md §4.2's "in-process fake backend" idea, lifted to a real
process behind a real socket). It accepts etcd's own command-line flags
(--name, --listen-client-urls, --initial-cluster, ...) so the DB layer
can launch it exactly as it would launch etcd
(/root/reference/etcd/src/jepsen/etcd.clj:62-74 — cited for parity, not
copied).

"Cluster consistency" is modeled by all member processes sharing one
flock-guarded JSON state file: every op takes an exclusive lock, so the
simulated cluster is linearizable by construction. A latency knob
(--mean-latency) adds jitter so histories have real concurrency windows.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .simbase import Store, build_sim_archive

KEYS_PREFIX = "/v2/keys/"


def _etcd_error(code: int, message: str, cause: str) -> dict:
    return {"errorCode": code, "message": message, "cause": cause, "index": 0}


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet; daemon log gets stdout
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _jitter(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))

    def _reply(self, status: int, body: dict):
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _key(self) -> str | None:
        path = urllib.parse.urlparse(self.path).path
        if not path.startswith(KEYS_PREFIX):
            return None
        return urllib.parse.unquote(path[len(KEYS_PREFIX):])

    def do_GET(self):
        self._jitter()
        if urllib.parse.urlparse(self.path).path == "/version":
            return self._reply(
                200, {"etcdserver": "jepsen-tpu-sim", "etcdcluster": "2.3.0"}
            )
        k = self._key()
        if k is None:
            return self._reply(404, _etcd_error(100, "Key not found", self.path))

        def read(data):
            if k in data:
                return (200, {"action": "get",
                              "node": {"key": "/" + k, "value": data[k]}}), None
            return (404, _etcd_error(100, "Key not found", "/" + k)), None

        status, body = self.store.transact(read)
        self._reply(status, body)

    def do_PUT(self):
        self._jitter()
        k = self._key()
        if k is None:
            return self._reply(404, _etcd_error(100, "Key not found", self.path))
        length = int(self.headers.get("Content-Length") or 0)
        form = urllib.parse.parse_qs(self.rfile.read(length).decode())
        value = (form.get("value") or [None])[0]
        prev_value = (form.get("prevValue") or [None])[0]
        prev_exist = (form.get("prevExist") or [None])[0]
        if value is None:
            return self._reply(
                400, _etcd_error(200, "Value is Required in POST form", "")
            )

        def write(data):
            node = {"key": "/" + k, "value": value}
            if prev_value is not None:
                if k not in data:
                    return (404, _etcd_error(100, "Key not found", "/" + k)), None
                if data[k] != prev_value:
                    return (
                        412,
                        _etcd_error(
                            101,
                            "Compare failed",
                            f"[{prev_value} != {data[k]}]",
                        ),
                    ), None
                new = dict(data)
                new[k] = value
                return (200, {"action": "compareAndSwap", "node": node}), new
            if prev_exist == "false" and k in data:
                return (412, _etcd_error(105, "Key already exists", "/" + k)), None
            if prev_exist == "true" and k not in data:
                return (404, _etcd_error(100, "Key not found", "/" + k)), None
            new = dict(data)
            new[k] = value
            return (200, {"action": "set", "node": node}), new

        status, body = self.store.transact(write)
        self._reply(status, body)

    def do_DELETE(self):
        self._jitter()
        k = self._key()
        if k is None:
            return self._reply(404, _etcd_error(100, "Key not found", self.path))

        def rm(data):
            if k not in data:
                return (404, _etcd_error(100, "Key not found", "/" + k)), None
            new = dict(data)
            del new[k]
            return (200, {"action": "delete", "node": {"key": "/" + k}}), new

        status, body = self.store.transact(rm)
        self._reply(status, body)


def parse_args(argv):
    p = argparse.ArgumentParser(
        description="etcd v2 keys-API simulator",
        # etcd flags we accept-and-ignore arrive as --flag value pairs
        allow_abbrev=False,
    )
    p.add_argument("--data", required=True, help="shared JSON state file")
    p.add_argument("--mean-latency", type=float, default=0.0,
                   help="mean exponential per-request latency, seconds")
    p.add_argument("--name", default="sim")
    p.add_argument("--listen-client-urls", default="http://127.0.0.1:2379")
    # etcd flags tolerated for command-line compatibility:
    for flag in ("--advertise-client-urls", "--listen-peer-urls",
                 "--initial-advertise-peer-urls", "--initial-cluster",
                 "--initial-cluster-state", "--log-output"):
        p.add_argument(flag, default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    url = urllib.parse.urlparse(args.listen_client_urls.split(",")[0])
    host, port = url.hostname or "127.0.0.1", url.port or 2379
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    httpd = ThreadingHTTPServer((host, port), Handler)
    print(f"etcd-sim {args.name} serving on {host}:{port}, data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    """Build an etcd-shaped tar.gz whose `etcd` binary is a script
    launching this simulator with a shared state file. Installed via the
    suite's normal install_archive path (file:// URL)."""
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.etcd_sim", "etcd", "etcd-sim-linux-amd64",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
