"""ZooKeeper test suite: a single linearizable CAS register stored in a
znode (reference: /root/reference/zookeeper/src/jepsen/zookeeper.clj).

Pieces, mirroring the reference:
  - zk_node_ids / zoo_cfg_servers — ensemble config (zookeeper.clj:19-38)
  - ZookeeperDB   — debian-package install + myid/zoo.cfg + service
                    restart (zookeeper.clj:40-72); an archive mode runs
                    the in-repo jute simulator through the same daemon
                    machinery for hermetic tests
  - ZkAtomClient  — the avout zk-atom analog (zookeeper.clj:78-104):
                    read = getData, write = setData, cas = optimistic
                    version-CAS retry loop; every op is wrapped in a
                    5 s timeout completing as :info :timeout
                    (zookeeper.clj:92)
  - zk_test(opts) — test map (zookeeper.clj:106-131)
  - main()        — CLI entry (zookeeper.clj:133-139)
"""

from __future__ import annotations

import itertools
import logging
import random
import socket
import time

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, db, generator as gen, models, osdist
from ..control import util as cu
from ..history import Op
from . import zk_proto

log = logging.getLogger("jepsen_tpu.dbs.zookeeper")

CLIENT_PORT = 2181
ZNODE = "/jepsen"
VERSION = "3.4.5+dfsg-2"

ZOO_CFG_BASE = """tickTime=2000
initLimit=10
syncLimit=5
dataDir=/var/lib/zookeeper
clientPort=2181
"""


def _cfg(test) -> dict:
    return test.get("zk") or {}


def zk_node_ids(test) -> dict:
    """Node name -> numeric id (zookeeper.clj:19-25)."""
    return {node: i for i, node in enumerate(test["nodes"])}


def zk_node_id(test, node) -> int:
    return zk_node_ids(test)[node]


def zoo_cfg_servers(test) -> str:
    """server.N=host:2888:3888 lines (zookeeper.clj:32-38)."""
    return "\n".join(
        f"server.{i}={node}:2888:3888"
        for node, i in zk_node_ids(test).items()
    )


def node_host(test, node) -> str:
    fn = _cfg(test).get("addr_fn")
    return fn(node) if fn else str(node)


def client_port(test, node) -> int:
    ports = _cfg(test).get("client_ports")
    return ports[node] if ports else CLIENT_PORT


def ruok(test, node, timeout: float = 2.0) -> bool:
    """The `ruok` four-letter health word."""
    try:
        with socket.create_connection(
            (node_host(test, node), client_port(test, node)), timeout=timeout
        ) as s:
            s.sendall(b"ruok")
            s.settimeout(timeout)
            buf = b""
            while len(buf) < 4:  # TCP may fragment even 4 bytes
                chunk = s.recv(4 - len(buf))
                if not chunk:
                    return False
                buf += chunk
            return buf == b"imok"
    except OSError:
        return False


class ZookeeperDB(db.DB, db.LogFiles):
    """Debian-packaged ZooKeeper (zookeeper.clj:40-72). With
    archive_url set, installs an archive and runs its `zkserver` binary
    through start_daemon instead — the hermetic-simulator path."""

    def __init__(self, version: str = VERSION, archive_url: str | None = None,
                 ready_timeout: float = 30.0):
        self.version = version
        self.archive_url = archive_url
        self.ready_timeout = ready_timeout

    # -- packaged mode (reference parity) --------------------------------
    def _setup_packaged(self, test, node) -> None:
        remote = test["remote"]
        log.info("%s installing ZK %s", node, self.version)
        osdist.install(remote, node, {
            "zookeeper": self.version,
            "zookeeper-bin": self.version,
            "zookeeperd": self.version,
        })
        remote.exec(
            node,
            f"echo {zk_node_id(test, node)} > /etc/zookeeper/conf/myid",
            sudo=True,
        )
        cfg = ZOO_CFG_BASE + "\n" + zoo_cfg_servers(test) + "\n"
        remote.exec(node, ["tee", "/etc/zookeeper/conf/zoo.cfg"],
                    stdin=cfg, sudo=True)
        log.info("%s ZK restarting", node)
        remote.exec(node, ["service", "zookeeper", "restart"], sudo=True)

    def _teardown_packaged(self, test, node) -> None:
        remote = test["remote"]
        remote.exec(node, ["service", "zookeeper", "stop"], sudo=True,
                    check=False)
        remote.exec(node, "rm -rf /var/lib/zookeeper/version-* "
                          "/var/log/zookeeper/*", sudo=True, check=False)

    # -- archive/simulator mode ------------------------------------------
    def _dir(self, test, node) -> str:
        d = _cfg(test).get("dir", "/opt/zookeeper")
        return d(node) if callable(d) else d

    def _setup_archive(self, test, node) -> None:
        remote = test["remote"]
        d = self._dir(test, node)
        sudo = _cfg(test).get("sudo", True)
        cu.install_archive(remote, node, self.archive_url, d, sudo=sudo)
        cu.start_daemon(
            remote, node, f"{d}/zkserver",
            "--port", str(client_port(test, node)),
            "--name", str(node),
            logfile=f"{d}/zookeeper.log",
            pidfile=f"{d}/zookeeper.pid",
            chdir=d,
        )

    def _teardown_archive(self, test, node) -> None:
        remote = test["remote"]
        d = self._dir(test, node)
        cu.stop_daemon(remote, node, f"{d}/zookeeper.pid")
        remote.exec(node, ["rm", "-rf", d],
                    sudo=_cfg(test).get("sudo", True), check=False)

    # ---------------------------------------------------------------------
    def setup(self, test, node) -> None:
        if self.archive_url:
            self._setup_archive(test, node)
        else:
            self._setup_packaged(test, node)
        self.await_ready(test, node)

    def await_ready(self, test, node) -> None:
        deadline = time.monotonic() + self.ready_timeout
        while not ruok(test, node):
            if time.monotonic() > deadline:
                raise db.SetupFailed(f"zookeeper on {node} never said imok")
            time.sleep(0.2)
        log.info("%s ZK ready", node)

    def teardown(self, test, node) -> None:
        log.info("%s tearing down ZK", node)
        if self.archive_url:
            self._teardown_archive(test, node)
        else:
            self._teardown_packaged(test, node)

    def log_files(self, test, node) -> list:
        if self.archive_url:
            return [f"{self._dir(test, node)}/zookeeper.log"]
        return ["/var/log/zookeeper/zookeeper.log"]


class ZkAtomClient(client.Client):
    """The avout zk-atom analog: an integer register at ZNODE
    (zookeeper.clj:78-104). Reads getData; writes setData (blind);
    cas does the optimistic read-then-setData(version) loop — a
    BadVersion race retries, value mismatch is a definite :fail.
    Any timeout or connection error completes :info :timeout, exactly
    like the reference's (timeout 5000 (assoc op :type :info ...))."""

    CAS_RETRIES = 16

    def __init__(self, conn: zk_proto.ZkConn | None = None,
                 timeout: float = 5.0):
        self.conn = conn
        self.timeout = timeout

    def open(self, test, node):
        conn = zk_proto.ZkConn(
            node_host(test, node), client_port(test, node),
            timeout=self.timeout,
        )
        return ZkAtomClient(conn, timeout=self.timeout)

    def setup(self, test):
        """Create the register znode with initial value 0 (the
        reference's (avout/zk-atom conn "/jepsen" 0))."""
        try:
            self.conn.create(ZNODE, b"0")
        except zk_proto.NodeExists:
            pass

    def invoke(self, test, op: Op) -> Op:
        # Overall op deadline, like the reference's (timeout 5000 ...)
        # wrapper around the whole invoke (zookeeper.clj:92): a cas
        # retry loop may not keep a worker busy past self.timeout even
        # when each socket call individually stays under its limit.
        deadline = time.monotonic() + self.timeout
        try:
            if op.f == "read":
                data, _ = self.conn.get_data(ZNODE)
                return op.with_(type="ok", value=int(data))
            if op.f == "write":
                self.conn.set_data(ZNODE, str(op.value).encode(), -1)
                return op.with_(type="ok")
            if op.f == "cas":
                old, new = op.value
                for _ in range(self.CAS_RETRIES):
                    if time.monotonic() > deadline:
                        return op.with_(type="info", error="timeout")
                    data, stat = self.conn.get_data(ZNODE)
                    if int(data) != old:
                        return op.with_(type="fail")
                    try:
                        self.conn.set_data(ZNODE, str(new).encode(),
                                           stat["version"])
                        return op.with_(type="ok")
                    except zk_proto.BadVersion:
                        continue  # raced; nothing written, try again
                return op.with_(type="fail", error="cas-retries-exhausted")
            raise ValueError(f"unknown op {op.f!r}")
        except (socket.timeout, TimeoutError):
            return op.with_(type="info", error="timeout")
        except (ConnectionError, OSError) as e:
            return op.with_(type="info", error=str(e))
        except zk_proto.ZkError as e:
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


def zk_test(opts: dict) -> dict:
    """Test map (zookeeper.clj:106-131): mixed r/w/cas staggered 1 s,
    partition nemesis 5 s on / 5 s off, cas-register(0) model, perf +
    linearizable checkers."""
    from ..testlib import noop_test

    db_ = ZookeeperDB(opts.get("version", VERSION),
                      archive_url=opts.get("archive_url"))
    test = noop_test()
    # The reference merges opts BEFORE the suite map (zookeeper.clj:115)
    # so suite settings win; we keep the same precedence.
    test.update(opts)
    test.update(
        {
            "name": "zookeeper",
            "os": osdist.debian,
            "db": db_,
            "client": ZkAtomClient(),
            "nemesis": cmn.pick_nemesis(db_, opts),
            "model": models.CASRegister(0),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "linear": checker_mod.linearizable(),
            }),
            "generator": gen.time_limit(
                opts.get("time_limit", 15),
                gen.nemesis(
                    gen.seq(itertools.cycle([
                        gen.sleep(5),
                        {"type": "info", "f": "start"},
                        gen.sleep(5),
                        {"type": "info", "f": "stop"},
                    ])),
                    gen.stagger(1, gen.mix([r, w, cas])),
                ),
            ),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p, names=cmn.PARTITION_NEMESIS_NAMES)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(zk_test, opt_spec=_opt_spec),
         **cli.serve_cmd()}, argv)


if __name__ == "__main__":
    main()
