"""CockroachDB test suite — DB lifecycle, pgwire client helpers, and
the named-nemesis registry (reference:
/root/reference/cockroachdb/src/jepsen/cockroach.clj,
cockroach/auto.clj, cockroach/client.clj, cockroach/nemesis.clj;
workloads live in cockroach_workloads.py).

Pieces, mirroring the reference:
  - CockroachDB       — tarball install + `cockroach start --insecure
                        --join=...` daemon lifecycle (auto.clj:142-214)
  - conn_wrapper      — reconnect-wrapped PgConn per node
                        (client.clj:76-96)
  - txn()/txn_retry() — transaction context + 40001 retry loop with
                        exponential backoff (client.clj:131-161)
  - exception_to_op   — the exception→op determinacy taxonomy
                        (client.clj:183-226)
  - with_idempotent   — :info→:fail remap for idempotent op classes
                        (client.clj:110-116)
  - nemeses registry + compose / slowing / restarting wrappers
                        (nemesis.clj:26-316)
  - basic_test        — shared test-map scaffold (cockroach.clj:83-164)

The real path installs a cockroach binary tarball; the hermetic path
installs dbs/crdb_sim.py (a pgwire server with serializable
transactions) through the identical archive + daemon code. Either way
the client speaks the same wire protocol via dbs/pg_proto.py.
"""

from __future__ import annotations

import logging
import random
import socket
import threading
import time
from contextlib import contextmanager

from .. import db, generator as gen, nemesis, osdist, reconnect
from .. import net as net_mod
from ..control import util as cu
from ..nemesis import time as nt
from . import pg_proto

log = logging.getLogger("jepsen_tpu.dbs.cockroach")

DIR = "/opt/cockroach"
PORT = 26257
HTTP_PORT = 8080
DB_NAME = "jepsen"
DB_USER = "root"

TIMEOUT_DELAY = 10.0   # default op timeout, s (client.clj:21)
MAX_TIMEOUT = 30.0     # connect timeout, s (client.clj:22)

NEMESIS_DELAY = 5      # s between interruptions (nemesis.clj:20)
NEMESIS_DURATION = 5   # s per interruption (nemesis.clj:23)


def _cfg(test) -> dict:
    return test.get("cockroach") or {}


def node_host(test, node) -> str:
    fn = _cfg(test).get("addr_fn")
    return fn(node) if fn else str(node)


def node_port(test, node) -> int:
    ports = _cfg(test).get("ports")
    return ports[node] if ports else PORT


def node_dir(test, node) -> str:
    d = _cfg(test).get("dir", DIR)
    return d(node) if callable(d) else d


def data_dir(test, node) -> str:
    """The node's --store dir; single source of truth — the faultfs
    FUSE layer mounts over exactly this path."""
    return f"{node_dir(test, node)}/data"


# ---------------------------------------------------------------------------
# DB (auto.clj:142-223)


class CockroachDB(db.DB, db.LogFiles):
    """Installs and runs one cockroach node per node. The first node
    starts solo; the rest join it (auto.clj:157-190)."""

    def __init__(self, tarball: str | None = None,
                 ready_timeout: float = 60.0):
        self.tarball = tarball
        self.ready_timeout = ready_timeout

    def setup(self, test, node) -> None:
        self.install(test, node)
        self.start_and_await(test, node)

    def install(self, test, node) -> None:
        """Fetch + unpack only — split from start_and_await so the
        faultfs FUSE layer can mount over the store dir between the
        install's tree wipe and the daemon opening its first file
        (fsfault.FaultFsDB)."""
        remote = test["remote"]
        d = node_dir(test, node)
        sudo = _cfg(test).get("sudo", True)
        url = self.tarball or _cfg(test).get("tarball")
        if not url:
            raise db.SetupFailed(
                "cockroach tarball url required (binary distribution, or "
                "the crdb_sim archive for hermetic runs)")
        cu.install_archive(remote, node, url, d, sudo=sudo)

    def start_and_await(self, test, node) -> None:
        start_node(test, node)
        self.await_ready(test, node)
        # Ensure the jepsen database exists (auto.clj's csql! bootstrap)
        conn = pg_proto.PgConn(node_host(test, node), node_port(test, node),
                               user=DB_USER, database=DB_NAME,
                               timeout=5.0, connect_timeout=5.0)
        try:
            try:
                conn.query(f"create database if not exists {DB_NAME}")
            except pg_proto.PgError:
                pass  # sim has no databases; real crdb accepts this
        finally:
            conn.close()

    def await_ready(self, test, node) -> None:
        deadline = time.monotonic() + self.ready_timeout
        while True:
            try:
                conn = pg_proto.PgConn(
                    node_host(test, node), node_port(test, node),
                    user=DB_USER, database=DB_NAME,
                    timeout=2.0, connect_timeout=2.0,
                )
                try:
                    conn.query("select 1")
                    return
                finally:
                    conn.close()
            except (OSError, pg_proto.PgError, pg_proto.PgProtocolError):
                pass
            if time.monotonic() > deadline:
                raise db.SetupFailed(f"cockroach on {node} never ready")
            time.sleep(0.2)

    def teardown(self, test, node) -> None:
        remote = test["remote"]
        d = node_dir(test, node)
        log.info("%s tearing down cockroach", node)
        cu.stop_daemon(remote, node, f"{d}/cockroach.pid")
        remote.exec(node, ["rm", "-rf", d],
                    sudo=_cfg(test).get("sudo", True), check=False)

    def log_files(self, test, node) -> list:
        return [f"{node_dir(test, node)}/cockroach.log"]


def start_node(test, node) -> None:
    """(Re)start cockroach on a node — used by setup and as the
    startkill nemesis's start_fn. Bootstrap follows the reference
    (auto.clj:157-190): the first node starts solo and the rest join
    it, so a fresh real cluster actually initializes."""
    remote = test["remote"]
    d = node_dir(test, node)
    primary = test["nodes"][0]
    join_args = (
        [] if node == primary
        else ["--join", f"{node_host(test, primary)}:"
                        f"{node_port(test, primary)}"]
    )
    cu.start_daemon(
        remote, node, f"{d}/cockroach", "start",
        "--insecure",
        "--port", str(node_port(test, node)),
        *join_args,
        "--store", data_dir(test, node),
        logfile=f"{d}/cockroach.log",
        pidfile=f"{d}/cockroach.pid",
        chdir=d,
    )


def kill_node(test, node) -> None:
    """Kill -9 cockroach on a node (auto.clj:206-211)."""
    remote = test["remote"]
    d = node_dir(test, node)
    cu.stop_daemon(remote, node, f"{d}/cockroach.pid")


# ---------------------------------------------------------------------------
# Client helpers (client.clj)


def conn_wrapper(test, node) -> reconnect.Wrapper:
    """A reconnect-wrapped pgwire connection to one node
    (client.clj:76-96)."""
    host, port = node_host(test, node), node_port(test, node)

    def open_conn():
        return pg_proto.PgConn(host, port, user=DB_USER, database=DB_NAME,
                               timeout=TIMEOUT_DELAY,
                               connect_timeout=MAX_TIMEOUT)

    return reconnect.wrapper(
        open=open_conn,
        close=lambda c: c.close(),
        name=f"cockroach {node}",
    ).open()


@contextmanager
def txn(c: pg_proto.PgConn):
    """BEGIN/COMMIT bracket; ROLLBACK (best-effort) on error
    (client.clj:159-163)."""
    c.query("begin")
    try:
        yield c
    except BaseException:
        try:
            c.query("rollback")
        except (OSError, pg_proto.PgError, pg_proto.PgProtocolError):
            pass
        raise
    else:
        c.query("commit")


def txn_retry(body, attempts: int = 30, backoff: float = 0.02):
    """Run body(), retrying SQLSTATE 40001 'restart transaction' errors
    with jittered exponential backoff (client.clj:143-157)."""
    while True:
        try:
            return body()
        except pg_proto.PgError as e:
            if not e.retryable or attempts <= 0:
                raise
            attempts -= 1
            time.sleep(backoff)
            backoff *= 4 + 0.5 * (random.random() - 0.5)


def with_idempotent(idempotent_fs, op):
    """Remap :info to :fail for idempotent op classes — a read that
    maybe-happened didn't change anything (client.clj:110-116)."""
    if op.f in idempotent_fs and op.type == "info":
        return op.with_(type="fail")
    return op


def exception_to_op(op, e):
    """Map an exception to a completed op per the reference's
    determinacy taxonomy (client.clj:183-226): 40001 restart-transaction
    errors definitely failed; connection-refused definitely failed
    (nothing was sent); timeouts and other server errors are
    indeterminate."""
    if isinstance(e, pg_proto.PgError):
        if e.retryable:
            return op.with_(type="fail", error=("restart-transaction",
                                                e.message))
        if e.sqlstate == "23505":
            # unique violation: the insert definitely did NOT commit
            return op.with_(type="fail", error=("duplicate-key",
                                                e.message))
        return op.with_(type="info", error=("psql-exception", str(e)))
    if isinstance(e, ConnectionRefusedError):
        return op.with_(type="fail", error="connection-refused")
    if isinstance(e, (socket.timeout, TimeoutError)):
        return op.with_(type="info", error="timeout")
    if isinstance(e, (ConnectionError, pg_proto.PgProtocolError, OSError)):
        return op.with_(type="info", error=str(e))
    return None  # unrecognized: re-raise


def invoke_with_taxonomy(wrapper, op, body, idempotent_fs=frozenset()):
    """The with-exception->op + with-conn + with-idempotent stack every
    cockroach client shares (client.clj:98-116,228-234). body(conn) must
    return a completed op."""
    try:
        with wrapper.with_conn() as c:
            return with_idempotent(idempotent_fs, body(c))
    except Exception as e:  # noqa: BLE001
        mapped = exception_to_op(op, e)
        if mapped is None:
            raise
        return with_idempotent(idempotent_fs, mapped)


# ---------------------------------------------------------------------------
# Nemesis registry (nemesis.clj:26-316)


def nemesis_single_gen() -> dict:
    """start/stop cycle with the standard delay/duration
    (nemesis.clj:31-37)."""
    import itertools

    return {
        "during": gen.seq(itertools.cycle([
            gen.sleep(NEMESIS_DELAY),
            {"type": "info", "f": "start"},
            gen.sleep(NEMESIS_DURATION),
            {"type": "info", "f": "stop"},
        ])),
        "final": gen.once({"type": "info", "f": "stop"}),
    }


def none() -> dict:
    """The blank nemesis (nemesis.clj:110-115)."""
    return {"name": "blank", "client": nemesis.noop, "clocks": False,
            "during": gen.void, "final": gen.void}


def parts() -> dict:
    """Random-halves partitions (nemesis.clj:118-124)."""
    return {**nemesis_single_gen(), "name": "parts",
            "client": nemesis.partition_random_halves(), "clocks": False}


def majring() -> dict:
    """Majorities-ring partition (nemesis.clj:145-150)."""
    return {**nemesis_single_gen(), "name": "majring",
            "client": nemesis.partition_majorities_ring(), "clocks": False}


def startstop(n: int = 1) -> dict:
    """SIGSTOP/SIGCONT n random nodes (nemesis.clj:127-133)."""
    return {**nemesis_single_gen(),
            "name": "startstop" + (str(n) if n > 1 else ""),
            "client": nemesis.hammer_time(
                "cockroach",
                targeter=lambda nodes: random.sample(list(nodes),
                                                     min(n, len(nodes)))),
            "clocks": False}


def fs_break(pct: int | None = None) -> dict:
    """EIO storms on the --store dir via the faultfs FUSE layer —
    needs the DB wrapped in FaultFsDB (basic_test wires that when
    --nemesis/--nemesis2 name an fs-break mode); this entry is only
    the switch flipper (charybdefs.clj:72-85 semantics)."""
    from ..nemesis import fsfault

    return {**nemesis_single_gen(),
            "name": "fs-break" + ("-1pct" if pct == 1 else ""),
            "client": fsfault.fs_fault_nemesis(
                backend="fuse", manage_mounts=False,
                default_mode=("break-one-percent" if pct == 1
                              else "break-all")),
            "clocks": False}


def startkill(n: int = 1) -> dict:
    """Kill and restart cockroach on n random nodes
    (nemesis.clj:135-142)."""
    return {**nemesis_single_gen(),
            "name": "startkill" + (str(n) if n > 1 else ""),
            "client": nemesis.node_start_stopper(
                lambda nodes: random.sample(list(nodes),
                                            min(n, len(nodes))),
                kill_node, start_node),
            "clocks": False}


class Slowing(nemesis.Nemesis):
    """Wraps a nemesis: slows the network while the inner nemesis is
    active, restores speed on stop (nemesis.clj:152-174)."""

    def __init__(self, nem, dt: float):
        self.nem = nem
        self.dt = dt

    def _net(self, test):
        return test.get("net") or net_mod.noop

    def setup(self, test):
        self._net(test).fast(test)
        self.nem.setup(test)
        return self

    def invoke(self, test, op):
        if op.f == "start":
            self._net(test).slow(test)
            return self.nem.invoke(test, op)
        if op.f == "stop":
            try:
                return self.nem.invoke(test, op)
            finally:
                self._net(test).fast(test)
        return self.nem.invoke(test, op)

    def teardown(self, test):
        self._net(test).fast(test)
        self.nem.teardown(test)


class Restarting(nemesis.Nemesis):
    """Wraps a nemesis: after its :stop completes, restarts cockroach
    on every node (nemesis.clj:176-199)."""

    def __init__(self, nem):
        self.nem = nem

    def setup(self, test):
        self.nem.setup(test)
        return self

    def invoke(self, test, op):
        out = self.nem.invoke(test, op)
        if op.f == "stop":
            from ..util import real_pmap

            def restart(node):
                try:
                    start_node(test, node)
                    return "started"
                except Exception as e:  # noqa: BLE001
                    return str(e)

            statuses = real_pmap(restart, test["nodes"])
            return out.with_(value=[out.value, statuses])
        return out

    def teardown(self, test):
        self.nem.teardown(test)


class BumpTime(nemesis.Nemesis):
    """On :start, bump clocks by dt seconds on a random half of the
    nodes; on :stop, reset all clocks (nemesis.clj:231-253)."""

    def __init__(self, dt: float):
        self.dt = dt

    def setup(self, test):
        remote = test["remote"]
        for node in test["nodes"]:
            nt.install(remote, node)
            nt.reset_time(remote, node)
        return self

    def invoke(self, test, op):
        remote = test["remote"]
        if op.f == "start":
            bumped = {}
            for node in test["nodes"]:
                if random.random() < 0.5:
                    nt.bump_time(remote, node, self.dt * 1000)
                    bumped[node] = self.dt
                else:
                    bumped[node] = 0
            return op.with_(value=bumped)
        if op.f == "stop":
            for node in test["nodes"]:
                nt.reset_time(remote, node)
            return op.with_(value="clocks-reset")
        return op

    def teardown(self, test):
        remote = test["remote"]
        for node in test["nodes"]:
            nt.reset_time(remote, node)


def skew(name: str, offset: float, slow: float | None = None) -> dict:
    """A clock-skew nemesis, optionally wrapped in slowing
    (nemesis.clj:255-268)."""
    client = Restarting(BumpTime(offset))
    if slow is not None:
        client = Slowing(client, slow)
    return {**nemesis_single_gen(), "name": name, "client": client,
            "clocks": True}


def small_skews() -> dict:
    return skew("small-skews", 0.100)


def subcritical_skews() -> dict:
    return skew("subcritical-skews", 0.200)


def critical_skews() -> dict:
    return skew("critical-skews", 0.250)


def big_skews() -> dict:
    return skew("big-skews", 0.5, slow=0.5)


def huge_skews() -> dict:
    return skew("huge-skews", 5, slow=5)


class StrobeTime(nemesis.Nemesis):
    """Strobe the clock between now and delta ms ahead for duration s
    (nemesis.clj:201-223)."""

    def __init__(self, delta_ms: float, period_ms: float, duration_s: float):
        self.delta_ms = delta_ms
        self.period_ms = period_ms
        self.duration_s = duration_s

    def setup(self, test):
        remote = test["remote"]
        for node in test["nodes"]:
            nt.install(remote, node)
            nt.reset_time(remote, node)
        return self

    def invoke(self, test, op):
        remote = test["remote"]
        if op.f == "start":
            for node in test["nodes"]:
                nt.strobe_time(remote, node, self.delta_ms, self.period_ms,
                               self.duration_s)
            return op.with_(value="strobed")
        return op.with_(value=None)

    def teardown(self, test):
        remote = test["remote"]
        for node in test["nodes"]:
            nt.reset_time(remote, node)


def strobe_skews() -> dict:
    import itertools

    return {
        "during": gen.seq(itertools.cycle([
            {"type": "info", "f": "start"},
            {"type": "info", "f": "stop"},
        ])),
        "final": gen.once({"type": "info", "f": "stop"}),
        "name": "strobe-skews",
        "client": Restarting(StrobeTime(200, 10, 10)),
        "clocks": True,
    }


def update_keyrange(test, table: str, k) -> None:
    """Record that the test touched (table, k), so the split nemesis
    can split just-written ranges (cockroach.clj:121-128). A test map
    without a keyrange simply doesn't track (the reference throws; here
    workloads always install one via the shared scaffold)."""
    kr = test.get("keyrange")
    if kr is None:
        return
    with kr["lock"]:
        kr["keys"].setdefault(table, set()).add(k)


class SplitNemesis(nemesis.Nemesis):
    """Splits a table range just below a recently written key
    (nemesis.clj:273-308): pick a not-yet-split key from the test's
    keyrange and issue `ALTER TABLE .. SPLIT AT VALUES (k)` on a
    random node; re-splitting is reported, not an error."""

    def __init__(self):
        self._already: dict = {}

    def invoke(self, test, op: Op) -> Op:
        kr = test.get("keyrange")
        if kr is None:
            return op.with_(type="info", value="no-keyrange")
        with kr["lock"]:
            candidates = [
                (t, k) for t, ks in kr["keys"].items()
                for k in ks - self._already.get(t, set())]
        if not candidates:
            return op.with_(type="info", value="nothing-to-split")
        table, k = random.choice(candidates)
        node = random.choice(list(test["nodes"]))
        wrapper = None
        try:
            # inside the try: conn_wrapper connects eagerly, and a
            # down node (e.g. split composed with start-kill) must
            # complete as an error value, not crash the nemesis worker
            wrapper = conn_wrapper(test, node)
            lit = k if isinstance(k, (int, float)) else f"'{k}'"
            with wrapper.with_conn() as c:
                c.query(f"alter table {table} split at values ({lit})")
            self._already.setdefault(table, set()).add(k)
            return op.with_(type="info", value=["split", table, k])
        except pg_proto.PgError as e:
            if "already split" in str(e):
                self._already.setdefault(table, set()).add(k)
                return op.with_(type="info",
                                value=["already-split", table, k])
            return op.with_(type="info", value=["error", str(e)])
        except (OSError, TimeoutError) as e:
            return op.with_(type="info", value=["error", str(e)])
        finally:
            if wrapper is not None:
                wrapper.close()


def splits(interval: float = 2.0) -> dict:
    """The split-nemesis package (nemesis.clj:310-316). A bare op dict
    coerces to a repeat-forever generator under gen.delay. `interval`
    paces the splits; note that under gen.mix a slow member's delay
    runs inside op() and starves its siblings' share of a bounded
    window (same hazard as the reference's generator.clj:337-349), so
    tests composing this package should shrink it."""
    return {
        "during": gen.delay(interval, {"type": "info", "f": "split"}),
        "final": None,
        "name": "splits",
        "client": SplitNemesis(),
        "clocks": False,
        "fs": ("split",),  # compose routing vocabulary
    }


def _named_f_gen(name: str, inner) -> gen.Generator:
    """Wrap a nemesis's generator so emitted fs become (name, f) tuples
    for compose routing (nemesis.clj:84-103)."""
    return gen.f_map(lambda f, name=name: (name, f), inner)


class _FMap(dict):
    """A dict usable as a nemesis.compose routing key (hashable by
    identity; compose only reads it)."""

    __hash__ = object.__hash__


def compose_nemeses(nemeses: list) -> dict:
    """Merge named-nemesis maps: ops carry (name, inner-f) fs; the
    composed client routes each back to its owner via an outer-f →
    inner-f map (nemesis.clj:61-106)."""
    nemeses = [n for n in nemeses if n is not None]
    routes = {}
    for nem in nemeses:
        name = nem["name"]
        # a package may declare its op vocabulary; start/stop is the
        # partition-style default (splits emit f="split")
        fs = nem.get("fs", ("start", "stop"))
        routes[_FMap({(name, f): f for f in fs})] = nem["client"]
    return {
        "name": "+".join(n["name"] for n in nemeses),
        "clocks": any(n.get("clocks") for n in nemeses),
        "client": nemesis.compose(routes),
        "during": gen.mix([_named_f_gen(n["name"], n["during"])
                           for n in nemeses]),
        "final": gen.concat(*[_named_f_gen(n["name"], n["final"])
                              for n in nemeses]),
    }


def nemeses() -> dict:
    """Named registry for --nemesis (runner.clj:21-41)."""
    return {
        "none": none,
        "parts": parts,
        "majority-ring": majring,
        "start-stop": lambda: startstop(1),
        "start-stop-2": lambda: startstop(2),
        "start-kill": lambda: startkill(1),
        "start-kill-2": lambda: startkill(2),
        "small-skews": small_skews,
        "subcritical-skews": subcritical_skews,
        "critical-skews": critical_skews,
        "big-skews": big_skews,
        "huge-skews": huge_skews,
        "strobe-skews": strobe_skews,
        "split": splits,
        "fs-break": fs_break,
        "fs-break-1pct": lambda: fs_break(1),
    }


def resolve_nemesis(opts: dict) -> dict:
    """Build the (possibly composed) nemesis map from --nemesis /
    --nemesis2 options (runner.clj:43-52)."""
    registry = nemeses()
    n1 = registry[opts.get("nemesis") or "none"]()
    n2_name = opts.get("nemesis2")
    if n2_name:
        return compose_nemeses([n1, registry[n2_name]()])
    return n1


# ---------------------------------------------------------------------------
# Shared test scaffold (cockroach.clj:83-164)


def basic_test(opts: dict, workload: dict) -> dict:
    """Merge the suite scaffold, a workload map {client, during,
    final_client?, checker, model?}, and CLI opts into a runnable test
    map (cockroach.clj:83-164): client ops bracketed by the nemesis's
    during/final generators, then any final client phase after heal +
    quiescence."""
    from ..testlib import noop_test

    nem = resolve_nemesis(opts)
    time_limit = opts.get("time_limit", 60)
    generator = gen.time_limit(
        time_limit,
        gen.nemesis(nem["during"], workload["during"]),
    )
    phases = [generator,
              gen.log("Stopping nemesis"),
              gen.nemesis(nem["final"])]
    if workload.get("final_client") is not None:
        phases += [
            gen.log("Waiting for quiescence"),
            gen.sleep(opts.get("quiesce", 30)),
            gen.clients(workload["final_client"]),
        ]
    db_ = CockroachDB(tarball=opts.get("tarball"))
    from .common import FSFAULT_NEMESIS_NAMES

    if {opts.get("nemesis"), opts.get("nemesis2")} \
            & set(FSFAULT_NEMESIS_NAMES):
        # cockroach is a statically linked Go binary: FS faults need
        # the FUSE backend, mounted between install and start. The
        # switch flipper (the registry entry above) and this wrapper
        # both resolve opt_dir from the test map's fsfault_opt_dir.
        from ..nemesis import fsfault

        db_ = fsfault.FaultFsDB(db_, data_dir)
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": f"cockroachdb {workload['name']} {nem['name']}",
            "os": osdist.debian,
            "db": db_,
            "client": workload["client"],
            "nemesis": nem["client"],
            "generator": gen.phases(*phases),
            "checker": workload["checker"],
            "model": workload.get("model"),
            # written-key tracker for the split nemesis
            # (cockroach.clj:112-128's :keyrange atom)
            "keyrange": {"lock": threading.Lock(), "keys": {}},
        }
    )
    return test
