"""Minimal RESP (REdis Serialization Protocol) client — the transport
for the raftis suite (redis GET/SET on a replicated register) and the
disque suite (ADDJOB/GETJOB/ACKJOB). The reference goes through carmine
and jedisque (raftis.clj:5, disque.clj:26-28); neither has a Python
equivalent baked into this environment, so we speak the wire protocol
directly: inline command arrays out, simple-string / error / integer /
bulk / array replies back."""

from __future__ import annotations

import socket


class RespError(Exception):
    """Server '-ERR ...' reply."""


class RespConn:
    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self._buf = b""

    # -- wire -------------------------------------------------------------

    def _send(self, *args) -> None:
        parts = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            parts.append(b"$%d\r\n%s\r\n" % (len(b), b))
        self.sock.sendall(b"".join(parts))

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("resp connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("resp connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_reply(self):
        line = self._read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RespError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self._read_exact(n)
            self._read_exact(2)  # trailing \r\n
            return data
        if t == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RespError(f"bad reply type {line!r}")

    # -- public -----------------------------------------------------------

    def call(self, *args):
        self._send(*args)
        return self._read_reply()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
