"""Minimal MongoDB wire protocol (OP_MSG, opcode 2013) — the transport
for the mongodb suites. Commands are BSON documents with `$db`; replies
are single body-section BSON documents with an `ok` field (the
reference rides monger/the Java driver, mongodb_smartos/core.clj:25).
"""

from __future__ import annotations

import itertools
import socket
import struct

from . import bson

OP_MSG = 2013


class MongoError(Exception):
    def __init__(self, doc: dict):
        super().__init__(doc.get("errmsg", str(doc)))
        self.code = doc.get("code")
        self.doc = doc


class MongoConn:
    _request_ids = itertools.count(1)

    def __init__(self, host: str, port: int, timeout: float = 5.0,
                 connect_timeout: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.settimeout(timeout)

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("mongo connection closed")
            buf += chunk
        return buf

    def command(self, db: str, cmd: dict) -> dict:
        """Run one command; raises MongoError when ok != 1."""
        body = dict(cmd)
        body["$db"] = db
        payload = b"\x00\x00\x00\x00"  # flags
        payload += b"\x00"             # section kind 0: body
        payload += bson.encode(body)
        req_id = next(self._request_ids)
        header = struct.pack("<iiii", 16 + len(payload), req_id, 0, OP_MSG)
        self.sock.sendall(header + payload)

        (length,) = struct.unpack("<i", self._read_exact(4))
        rest = self._read_exact(length - 4)
        _resp_id, _reply_to, opcode = struct.unpack_from("<iii", rest, 0)
        if opcode != OP_MSG:
            raise MongoError({"errmsg": f"unexpected opcode {opcode}"})
        # flags (4) + section kind (1)
        doc, _ = bson.decode(rest, 12 + 4 + 1)
        if doc.get("ok") != 1 and doc.get("ok") != 1.0:
            raise MongoError(doc)
        return doc

    # -- convenience wrappers -------------------------------------------

    def find_one(self, db: str, coll: str, filter_: dict):
        out = self.command(db, {"find": coll, "filter": filter_,
                                "limit": 1})
        batch = out["cursor"]["firstBatch"]
        return batch[0] if batch else None

    def find_all(self, db: str, coll: str, filter_: dict | None = None):
        out = self.command(db, {"find": coll, "filter": filter_ or {}})
        return out["cursor"]["firstBatch"]

    def insert(self, db: str, coll: str, docs: list, w="majority") -> dict:
        return self.command(db, {
            "insert": coll, "documents": docs,
            "writeConcern": {"w": w},
        })

    def find_and_modify(self, db: str, coll: str, query: dict | None
                        = None, sort: dict | None = None,
                        remove: bool = False) -> dict:
        cmd: dict = {"findAndModify": coll, "query": query or {}}
        if sort:
            cmd["sort"] = sort
        if remove:
            cmd["remove"] = True
        return self.command(db, cmd)

    def update(self, db: str, coll: str, q: dict, u: dict,
               upsert: bool = False, w="majority") -> dict:
        """Returns the server reply; reply['n'] is matched docs."""
        return self.command(db, {
            "update": coll,
            "updates": [{"q": q, "u": u, "upsert": upsert}],
            "writeConcern": {"w": w},
        })

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
