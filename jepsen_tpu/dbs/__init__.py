"""Per-database test suites (reference: the 26 per-DB projects, SURVEY.md
§1 L9 / §2.1).

Each suite module supplies, like its reference counterpart:
  - a DB implementation (install/start/teardown through the control plane)
  - a Client with the suite's exception-determinacy taxonomy
  - op generators and a `*_test(opts)` test-map constructor
  - a `main()` built from cli.single_test_cmd + cli.serve_cmd

Suites here run against real clusters over SSH, and hermetically against
an in-repo protocol simulator through the same code paths (install
archive → daemon → wire protocol), so the whole stack is CI-testable
without network access (SURVEY.md §4.2).
"""
