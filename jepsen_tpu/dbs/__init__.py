"""Per-database test suites (reference: the 26 per-DB projects, SURVEY.md
§1 L9 / §2.1).

Each suite module supplies, like its reference counterpart:
  - a DB implementation (install/start/teardown through the control plane)
  - a Client with the suite's exception-determinacy taxonomy
  - op generators and a `*_test(opts)` test-map constructor
  - a `main()` built from cli.single_test_cmd + cli.serve_cmd

Suites here run against real clusters over SSH, and hermetically against
an in-repo protocol simulator through the same code paths (install
archive → daemon → wire protocol), so the whole stack is CI-testable
without network access (SURVEY.md §4.2).
"""

#: suite-module registry: every reference per-DB project and the module
#: that covers it (txn/charybdefs/docker live outside dbs/ — see
#: jepsen_tpu.txn, jepsen_tpu.nemesis.fsfault, and docker/)
SUITES = {
    "aerospike": "jepsen_tpu.dbs.aerospike",
    "chronos": "jepsen_tpu.dbs.chronos",
    "cockroachdb": "jepsen_tpu.dbs.cockroach_workloads",
    "consul": "jepsen_tpu.dbs.consul",
    "crate": "jepsen_tpu.dbs.crate",
    "dgraph": "jepsen_tpu.dbs.dgraph",
    "disque": "jepsen_tpu.dbs.disque",
    "elasticsearch": "jepsen_tpu.dbs.elasticsearch",
    "etcd": "jepsen_tpu.dbs.etcd",
    "galera": "jepsen_tpu.dbs.galera",
    "hazelcast": "jepsen_tpu.dbs.hazelcast",
    "logcabin": "jepsen_tpu.dbs.logcabin",
    "mongodb-rocks": "jepsen_tpu.dbs.mongodb",
    "mongodb-smartos": "jepsen_tpu.dbs.mongodb",
    "mysql-cluster": "jepsen_tpu.dbs.mysql_cluster",
    "percona": "jepsen_tpu.dbs.percona",
    "postgres-rds": "jepsen_tpu.dbs.postgres_rds",
    "rabbitmq": "jepsen_tpu.dbs.rabbitmq",
    "raftis": "jepsen_tpu.dbs.raftis",
    "rethinkdb": "jepsen_tpu.dbs.rethinkdb",
    "robustirc": "jepsen_tpu.dbs.robustirc",
    "tidb": "jepsen_tpu.dbs.tidb",
    "zookeeper": "jepsen_tpu.dbs.zookeeper",
}
