"""Hazelcast test suite: seven workloads over distributed data
structures — queue (total-queue), lock (linearizable mutex), three
unique-ID generators, and two set-as-map workloads (reference:
/root/reference/hazelcast/src/jepsen/hazelcast.clj:1-449).

Pieces, mirroring the reference:
  - HazelcastDB        — jdk + server install, daemon lifecycle with a
                         --members cluster list (hazelcast.clj:63-112)
  - HzConn             — HTTP connection with Hazelcast's 5 s
                         invocation-timeout defaults (hazelcast.clj:117-127)
  - QueueClient        — enqueue/dequeue/drain (hazelcast.clj:211-237)
  - LockClient         — tryLock/unlock through a reconnect wrapper with
                         the reference's failure taxonomy
                         (hazelcast.clj:260-301)
  - AtomicLongIdClient / AtomicRefIdClient / IdGenIdClient
                         (hazelcast.clj:155-205)
  - MapClient          — set-as-sorted-array CAS adds (hazelcast.clj:306-346)
  - workloads()        — workload registry (hazelcast.clj:364-399)
  - hazelcast_test     — test map w/ majorities-ring nemesis and the
                         heal-then-drain final phase (hazelcast.clj:401-433)
  - main()             — CLI entry with --workload (hazelcast.clj:435-448)

The real path installs a Hazelcast distribution and an HTTP shim; the
hermetic path installs dbs/hz_sim.py through the identical archive +
daemon code. Either way the client speaks the same HTTP/JSON protocol.
"""

from __future__ import annotations

import itertools
import json
import logging
import random
import socket
import time
import urllib.error
import urllib.request
import uuid

from .. import checker as checker_mod
from .. import cli, client, db, generator as gen, models, nemesis, osdist
from .. import reconnect
from ..control import util as cu
from . import common as cmn
from ..history import Op

log = logging.getLogger("jepsen_tpu.dbs.hazelcast")

DIR = "/opt/hazelcast"
PORT = 5701
QUEUE_POLL_TIMEOUT_MS = 1  # hazelcast.clj:207-209
LOCK_WAIT_MS = 5000        # hazelcast.clj:276
MAP_NAME = "jepsen.map"
CRDT_MAP_NAME = "jepsen.crdt-map"


def _cfg(test) -> dict:
    return test.get("hazelcast") or {}


def node_host(test, node) -> str:
    fn = _cfg(test).get("addr_fn")
    return fn(node) if fn else str(node)


def node_port(test, node) -> int:
    ports = _cfg(test).get("ports")
    return ports[node] if ports else PORT


def node_dir(test, node) -> str:
    d = _cfg(test).get("dir", DIR)
    return d(node) if callable(d) else d


class HazelcastDB(db.DB, db.LogFiles):
    """Installs and runs one Hazelcast member per node
    (hazelcast.clj:93-112): jdk, the server archive, then a daemon
    started with the other nodes' addresses as --members."""

    def __init__(self, archive_url: str | None = None,
                 jdk: bool = True, ready_timeout: float = 60.0):
        self.archive_url = archive_url
        self.jdk = jdk
        self.ready_timeout = ready_timeout

    def setup(self, test, node) -> None:
        remote = test["remote"]
        d = node_dir(test, node)
        sudo = _cfg(test).get("sudo", True)
        url = self.archive_url or _cfg(test).get("archive_url")
        if not url:
            raise db.SetupFailed(
                "hazelcast archive_url required (server distribution "
                "tarball, or the hz_sim archive for hermetic runs)")
        if self.jdk:
            # A real Hazelcast server archive needs a JVM (the reference
            # runs a fat jar, hazelcast.clj:51-69,100); the hz_sim
            # archive ships its own interpreter, so suites pass
            # jdk=False for it.
            osdist.install_jdk(remote, node)
        cu.install_archive(remote, node, url, d, sudo=sudo)
        members = ",".join(
            node_host(test, n) for n in test["nodes"] if n != node
        )
        cu.start_daemon(
            remote, node, f"{d}/hazelcast-server",
            "--port", str(node_port(test, node)),
            "--name", str(node),
            "--members", members,
            logfile=f"{d}/server.log",
            pidfile=f"{d}/server.pid",
            chdir=d,
        )
        self.await_ready(test, node)

    def probe_ready(self, test, node) -> bool:
        url = (f"http://{node_host(test, node)}:{node_port(test, node)}"
               "/health")
        with urllib.request.urlopen(url, timeout=2) as resp:
            return resp.status == 200

    def await_ready(self, test, node) -> None:
        if cmn.poll_until_ready(self, test, [node], self.ready_timeout):
            raise db.SetupFailed(f"hazelcast on {node} never healthy")

    def teardown(self, test, node) -> None:
        remote = test["remote"]
        d = node_dir(test, node)
        log.info("%s tearing down hazelcast", node)
        cu.stop_daemon(remote, node, f"{d}/server.pid")
        remote.exec(node, ["rm", "-rf", d],
                    sudo=_cfg(test).get("sudo", True), check=False)

    def log_files(self, test, node) -> list:
        return [f"{node_dir(test, node)}/server.log"]


# ---------------------------------------------------------------------------
# Connection


class HzError(Exception):
    def __init__(self, kind: str, message: str = ""):
        super().__init__(message or kind)
        self.kind = kind


class HzConn:
    """One member's HTTP endpoint, with Hazelcast's aggressive op
    timeouts (invocation timeout 5 s, hazelcast.clj:119-127)."""

    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.base = f"http://{host}:{port}"
        self.timeout = timeout

    def call(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.load(resp)
        except urllib.error.HTTPError as e:
            try:
                payload = json.load(e)
            except (json.JSONDecodeError, ValueError):
                raise HzError("http", f"HTTP {e.code}") from e
            raise HzError(payload.get("error", "http"),
                          payload.get("message", "")) from e

    def close(self) -> None:
        pass  # per-request sockets


def _connect(test, node, timeout: float = 5.0) -> HzConn:
    return HzConn(node_host(test, node), node_port(test, node),
                  timeout=timeout)


# ---------------------------------------------------------------------------
# Clients (hazelcast.clj:155-346)


class QueueClient(client.Client):
    """enqueue/dequeue/drain against a distributed queue
    (hazelcast.clj:211-237). enqueue must :info on indeterminate errors
    (the item may have been enqueued); dequeue/drain read-modify but an
    indeterminate dequeue is also :info (an item may be lost otherwise);
    an empty poll is a definite :fail :empty."""

    def __init__(self, conn: HzConn | None = None,
                 queue_name: str = "jepsen.queue"):
        self.conn = conn
        self.queue_name = queue_name

    def open(self, test, node):
        return QueueClient(_connect(test, node), self.queue_name)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "enqueue":
                self.conn.call("/queue/put",
                               {"name": self.queue_name, "value": op.value})
                return op.with_(type="ok")
            if op.f == "dequeue":
                got = self.conn.call(
                    "/queue/poll",
                    {"name": self.queue_name,
                     "timeout_ms": QUEUE_POLL_TIMEOUT_MS},
                )["value"]
                if got is None:
                    return op.with_(type="fail", error="empty")
                return op.with_(type="ok", value=got)
            if op.f == "drain":
                values = []
                while True:
                    got = self.conn.call(
                        "/queue/poll",
                        {"name": self.queue_name,
                         "timeout_ms": QUEUE_POLL_TIMEOUT_MS},
                    )["value"]
                    if got is None:
                        return op.with_(type="ok", value=values)
                    values.append(got)
            raise ValueError(f"unknown op {op.f!r}")
        except (socket.timeout, TimeoutError, urllib.error.URLError,
                OSError, HzError) as e:
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


class LockClient(client.Client):
    """acquire/release on a distributed lock through a reconnect
    wrapper (hazelcast.clj:260-301). Failure taxonomy from the
    reference: lock timeout → :fail; unlock-by-non-owner → :fail
    :not-lock-owner; quorum loss → :fail :quorum; client-down IO → :fail
    :client-down. All are definite :fails — an un-acquired lock and an
    un-released release don't change state."""

    def __init__(self, conn=None, lock_name: str = "jepsen.lock",
                 session: str | None = None):
        self.conn = conn
        self.lock_name = lock_name
        self.session = session

    def open(self, test, node):
        wrapped = reconnect.wrapper(
            open=lambda: _connect(test, node),
            close=lambda c: c.close(),
            name=f"hazelcast {node}",
        ).open()
        return LockClient(wrapped, self.lock_name, session=str(uuid.uuid4()))

    def invoke(self, test, op: Op) -> Op:
        try:
            with self.conn.with_conn() as c:
                if op.f == "acquire":
                    got = c.call("/lock/acquire", {
                        "name": self.lock_name, "session": self.session,
                        "timeout_ms": LOCK_WAIT_MS,
                    })["acquired"]
                    return op.with_(type="ok" if got else "fail")
                if op.f == "release":
                    c.call("/lock/release", {
                        "name": self.lock_name, "session": self.session,
                    })
                    return op.with_(type="ok")
                raise ValueError(f"unknown op {op.f!r}")
        except HzError as e:
            if e.kind == "not-lock-owner":
                return op.with_(type="fail", error="not-lock-owner")
            if e.kind == "quorum":
                time.sleep(1)
                return op.with_(type="fail", error="quorum")
            return op.with_(type="info", error=str(e))
        except (socket.timeout, TimeoutError) as e:
            # A lost acquire/release response is indeterminate: the
            # server may have granted the lock (reference's analog is
            # the client-down IOException → :fail only when the packet
            # was provably never sent, hazelcast.clj:290-298)
            return op.with_(type="info", error=str(e))
        except (ConnectionRefusedError,) as e:
            return op.with_(type="fail", error="client-down")
        except (urllib.error.URLError, OSError) as e:
            cause = getattr(e, "reason", None)
            if isinstance(cause, ConnectionRefusedError):
                return op.with_(type="fail", error="client-down")
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


class AtomicLongIdClient(client.Client):
    """IDs from AtomicLong.incrementAndGet (hazelcast.clj:155-169)."""

    def __init__(self, conn: HzConn | None = None,
                 name: str = "jepsen.atomic-long"):
        self.conn = conn
        self.name = name

    def open(self, test, node):
        return AtomicLongIdClient(_connect(test, node), self.name)

    def invoke(self, test, op: Op) -> Op:
        assert op.f == "generate"
        try:
            v = self.conn.call("/atomic-long/inc", {"name": self.name})["value"]
            return op.with_(type="ok", value=v)
        except (socket.timeout, TimeoutError, urllib.error.URLError,
                OSError, HzError) as e:
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


class AtomicRefIdClient(client.Client):
    """IDs via AtomicReference get + compareAndSet; a lost CAS is a
    definite :fail :cas-failed (hazelcast.clj:171-189)."""

    def __init__(self, conn: HzConn | None = None,
                 name: str = "jepsen.atomic-ref"):
        self.conn = conn
        self.name = name

    def open(self, test, node):
        return AtomicRefIdClient(_connect(test, node), self.name)

    def invoke(self, test, op: Op) -> Op:
        assert op.f == "generate"
        try:
            v = self.conn.call("/atomic-ref/get", {"name": self.name})["value"]
            v2 = (v or 0) + 1
            ok = self.conn.call(
                "/atomic-ref/cas",
                {"name": self.name, "old": v, "new": v2},
            )["swapped"]
            if ok:
                return op.with_(type="ok", value=v2)
            return op.with_(type="fail", error="cas-failed")
        except (socket.timeout, TimeoutError, urllib.error.URLError,
                OSError, HzError) as e:
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


class IdGenIdClient(client.Client):
    """IDs from the block-allocating IdGenerator (hazelcast.clj:191-205)."""

    def __init__(self, conn: HzConn | None = None):
        self.conn = conn

    def open(self, test, node):
        return IdGenIdClient(_connect(test, node))

    def invoke(self, test, op: Op) -> Op:
        assert op.f == "generate"
        try:
            v = self.conn.call("/id-gen/new", {})["value"]
            return op.with_(type="ok", value=v)
        except (socket.timeout, TimeoutError, urllib.error.URLError,
                OSError, HzError) as e:
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


class MapClient(client.Client):
    """A grow-only set stored as a sorted array in one map key, added
    to via replace/putIfAbsent CAS (hazelcast.clj:306-346; Hazelcast
    can't serialize HashSet, hence the sorted-array encoding — we keep
    the same encoding so histories read the same). crdt=True targets
    the merge-policy map the reference calls the CRDT map."""

    def __init__(self, conn: HzConn | None = None, crdt: bool = False):
        self.conn = conn
        self.crdt = crdt

    @property
    def map_name(self) -> str:
        return CRDT_MAP_NAME if self.crdt else MAP_NAME

    def open(self, test, node):
        return MapClient(_connect(test, node), crdt=self.crdt)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                cur = self.conn.call(
                    "/map/get", {"name": self.map_name, "key": "hi"}
                )["value"]
                if cur is not None:
                    new = sorted(set(cur) | {op.value})
                    ok = self.conn.call("/map/replace", {
                        "name": self.map_name, "key": "hi",
                        "old": cur, "new": new,
                    })["replaced"]
                    return (op.with_(type="ok") if ok
                            else op.with_(type="fail", error="cas-failed"))
                prev = self.conn.call("/map/put-if-absent", {
                    "name": self.map_name, "key": "hi",
                    "value": [op.value],
                })["previous"]
                return (op.with_(type="fail", error="cas-failed")
                        if prev is not None else op.with_(type="ok"))
            if op.f == "read":
                cur = self.conn.call(
                    "/map/get", {"name": self.map_name, "key": "hi"}
                )["value"]
                return op.with_(type="ok", value=sorted(set(cur or [])))
            raise ValueError(f"unknown op {op.f!r}")
        except (socket.timeout, TimeoutError, urllib.error.URLError,
                OSError, HzError) as e:
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


# ---------------------------------------------------------------------------
# Workloads (hazelcast.clj:239-399)


def queue_gen() -> gen.Generator:
    """Enqueues of sequential ints mixed with dequeues, staggered 1 s
    (hazelcast.clj:239-248)."""
    counter = itertools.count()

    def enqueue(test, process):
        return {"type": "invoke", "f": "enqueue", "value": next(counter)}

    return gen.stagger(1, gen.mix([
        enqueue, {"type": "invoke", "f": "dequeue"},
    ]))


def map_workload(crdt: bool) -> dict:
    return {
        "client": MapClient(crdt=crdt),
        "generator": gen.stagger(
            0.1,
            gen.seq({"type": "invoke", "f": "add", "value": x}
                    for x in itertools.count()),
        ),
        "final_generator": gen.each(
            lambda: gen.once({"type": "invoke", "f": "read"})),
        "checker": checker_mod.set_checker(),
    }


def workloads() -> dict:
    """Fresh workload registry — workloads hold stateful generators
    (hazelcast.clj:364-399)."""
    return {
        "crdt-map": map_workload(crdt=True),
        "map": map_workload(crdt=False),
        "lock": {
            "client": LockClient(),
            "generator": gen.each(lambda: gen.seq(itertools.cycle([
                {"type": "invoke", "f": "acquire"},
                {"type": "invoke", "f": "release"},
            ]))),
            "checker": checker_mod.linearizable(),
            "model": models.Mutex(),
        },
        "queue": {
            "client": QueueClient(),
            "generator": queue_gen(),
            "final_generator": gen.each(
                lambda: gen.once({"type": "invoke", "f": "drain"})),
            "checker": checker_mod.total_queue(),
        },
        "atomic-ref-ids": {
            "client": AtomicRefIdClient(),
            "generator": gen.stagger(
                1, {"type": "invoke", "f": "generate"}),
            "checker": checker_mod.unique_ids(),
        },
        "atomic-long-ids": {
            "client": AtomicLongIdClient(),
            "generator": gen.stagger(
                1, {"type": "invoke", "f": "generate"}),
            "checker": checker_mod.unique_ids(),
        },
        "id-gen-ids": {
            "client": IdGenIdClient(),
            "generator": gen.to_gen({"type": "invoke", "f": "generate"}),
            "checker": checker_mod.unique_ids(),
        },
    }


def hazelcast_test(opts: dict) -> dict:
    """Test map from CLI options (hazelcast.clj:401-433): chosen
    workload under a start/stop(30,15) majorities-ring partition
    nemesis; when the workload has a final generator, phases heal the
    cluster, wait for quiescence, then run it on every client."""
    from ..testlib import noop_test

    wl = workloads()[opts["workload"]]
    db_ = HazelcastDB(archive_url=opts.get("archive_url"),
                      jdk=opts.get("install_jdk", True))
    generator = gen.time_limit(
        opts.get("time_limit", 60),
        gen.nemesis(gen.start_stop(30, 15), wl["generator"]),
    )
    if wl.get("final_generator") is not None:
        generator = gen.phases(
            generator,
            gen.log("Healing cluster"),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.log("Waiting for quiescence"),
            gen.sleep(opts.get("quiesce", 500)),
            cmn.ready_gated_final(db_, gen.clients(wl["final_generator"]),
                                opts),
        )

    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": f"hazelcast {opts['workload']}",
            "os": osdist.debian,
            "db": db_,
            "client": wl["client"],
            "nemesis": cmn.pick_nemesis(db_, opts, default="majority-ring"),
            "generator": generator,
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "timeline": checker_mod.timeline_html(),
                "workload": wl["checker"],
            }),
            "model": wl.get("model"),
        }
    )
    return test


def _opt_spec(p) -> None:
    # HazelcastDB manages its own daemon (not an ArchiveDB), so only
    # the partition modes exist — reject others at parse time
    cmn.nemesis_opt(p, names=cmn.PARTITION_NEMESIS_NAMES,
                    default="majority-ring")
    p.add_argument(
        "--workload", required=True, choices=sorted(workloads().keys()),
        help="Test workload to run, e.g. atomic-long-ids.",
    )
    p.add_argument("--archive-url", dest="archive_url", default=None,
                   help="Hazelcast server archive (or hz_sim archive).")
    p.add_argument("--quiesce", type=float, default=500,
                   help="Seconds to wait before the final drain phase.")


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(hazelcast_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
