"""A hermetic CockroachDB lookalike: a PostgreSQL-wire-protocol server
with a miniature SQL engine and serializable transactions, so the
cockroachdb suite's real code paths (pgwire client, txn retry loops,
SQLSTATE 40001 handling, archive install, daemon lifecycle) run on one
machine with no network access.

Like the other sims, all member processes share one flock-guarded JSON
state file. Serializability comes from pessimistic global locking:
BEGIN takes the flock (bounded wait — contention surfaces as SQLSTATE
40001, the class of CockroachDB's 'restart transaction' errors, which
is exactly what the suite's with_txn_retry machinery expects to see:
/root/reference/cockroachdb/src/jepsen/cockroach/client.clj:131-161 —
cited for behavioral parity, not copied); COMMIT writes the snapshot
back and releases.

The SQL subset is the statement shapes the suites issue: CREATE/DROP
TABLE, INSERT (multi-row, with or without a column list), SELECT of
columns / * / max(col) with WHERE conjunctions of `col = lit` and
`col % n = m` predicates, UPDATE with rowcount tags, DELETE, BEGIN/
COMMIT/ROLLBACK, and cluster_logical_timestamp() for the monotonic
workload.
"""

from __future__ import annotations

import argparse
import random
import re
import socketserver
import struct
import sys
import time

from . import pg_proto
from .simbase import Store, StoreTxn, build_sim_archive

TXN_LOCK_TIMEOUT = 2.0
# Must comfortably exceed basic_test's default quiesce wait (30 s) or
# the one-shot final read of sets/monotonic lands on a closed socket.
SESSION_IDLE_TIMEOUT = 120.0

_RESTART_MSG = "restart transaction: retry txn (lock contention)"


class SqlError(Exception):
    def __init__(self, sqlstate: str, message: str):
        super().__init__(message)
        self.sqlstate = sqlstate
        self.message = message


# ---------------------------------------------------------------------------
# Mini SQL engine. State shape:
#   {"tables": {name: {"cols": [...], "rows": [[...], ...]}},
#    "hlc": int}


_LIT = r"(?:-?\d+|'(?:[^']*)'|NULL|TRUE|FALSE)"


def _parse_lit(tok: str):
    t = tok.strip()
    u = t.upper()
    if u == "NULL":
        return None
    if u == "TRUE":
        return True
    if u == "FALSE":
        return False
    if t.startswith("'"):
        return t[1:-1]
    try:
        return int(t)
    except ValueError:
        # unsupported literal (float, bareword, ...) — a proper
        # ErrorResponse, not a dead connection
        raise SqlError("42601", f"can't parse literal: {t!r}") from None


def _fmt(v) -> str | None:
    """Text-format pgwire encoding."""
    if v is None:
        return None
    if v is True:
        return "t"
    if v is False:
        return "f"
    return str(v)


class _Cond:
    """One WHERE conjunct: col = lit, or col % n = m."""

    def __init__(self, col: str, mod: int | None, rhs):
        self.col = col
        self.mod = mod
        self.rhs = rhs

    def matches(self, row: dict) -> bool:
        v = row.get(self.col)
        if self.mod is not None:
            return v is not None and v % self.mod == self.rhs
        return v == self.rhs


def _parse_where(clause: str | None) -> list:
    if not clause:
        return []
    conds = []
    for part in re.split(r"\s+and\s+", clause.strip(), flags=re.I):
        m = re.fullmatch(
            rf"(\w+)\s*%\s*(\d+)\s*=\s*({_LIT})", part.strip(), flags=re.I)
        if m:
            conds.append(_Cond(m.group(1).lower(), int(m.group(2)),
                               _parse_lit(m.group(3))))
            continue
        m = re.fullmatch(rf"(\w+)\s*=\s*({_LIT})", part.strip(), flags=re.I)
        if m:
            conds.append(_Cond(m.group(1).lower(), None,
                               _parse_lit(m.group(2))))
            continue
        raise SqlError("42601", f"can't parse WHERE conjunct: {part!r}")
    return conds


def _table(data: dict, name: str) -> dict:
    t = (data.get("tables") or {}).get(name)
    if t is None:
        raise SqlError("42P01", f'relation "{name}" does not exist')
    return t


def _rows_as_dicts(t: dict):
    for row in t["rows"]:
        yield dict(zip(t["cols"], row))


def execute(data: dict, sql: str) -> tuple:
    """Run one statement against the state dict IN PLACE. Returns
    (columns, rows, tag) with rows already text-encoded."""
    s = sql.strip().rstrip(";").strip()

    # -- DDL -------------------------------------------------------------
    m = re.fullmatch(r"drop\s+table\s+(if\s+exists\s+)?(\w+)", s, re.I)
    if m:
        data.setdefault("tables", {})
        if m.group(2).lower() in data["tables"]:
            del data["tables"][m.group(2).lower()]
        elif not m.group(1):
            raise SqlError("42P01",
                           f'relation "{m.group(2)}" does not exist')
        return [], [], "DROP TABLE"

    m = re.fullmatch(r"create\s+table\s+(if\s+not\s+exists\s+)?(\w+)\s*"
                     r"\((.*)\)", s, re.I | re.S)
    if m:
        name = m.group(2).lower()
        data.setdefault("tables", {})
        if name in data["tables"]:
            if m.group(1):
                return [], [], "CREATE TABLE"
            raise SqlError("42P07", f'relation "{name}" already exists')
        cols = []
        pkey = None
        for coldef in m.group(3).split(","):
            word = coldef.strip().split()
            if not word or word[0].lower() in ("primary", "unique",
                                               "constraint", "index"):
                continue  # table-level constraint, not a column
            cols.append(word[0].lower())
            # inline `<col> <type> primary key`
            if "primary" in (w.lower() for w in word[1:]):
                pkey = word[0].lower()
        # legacy convention: an `id` column acts as the key even
        # without a declared constraint (matches the old hardcoded
        # duplicate check, which several suites rely on)
        if pkey is None and "id" in cols:
            pkey = "id"
        data["tables"][name] = {"cols": cols, "rows": [], "pkey": pkey}
        return [], [], "CREATE TABLE"

    # `alter table t split at values (k)` — CockroachDB's range-split
    # hint (cockroach/client.clj:304-311). The sim records the split
    # point per table (sharding is internal, so data is unaffected) and
    # rejects re-splitting with the server's message, which the split
    # nemesis pattern-matches (nemesis.clj:295-299).
    m = re.fullmatch(r"alter\s+table\s+(\w+)\s+split\s+at\s+values\s*"
                     rf"\(\s*({_LIT})\s*\)", s, re.I)
    if m:
        t = _table(data, m.group(1).lower())
        k = _parse_lit(m.group(2))
        splits = t.setdefault("splits", [])
        if k in splits:
            raise SqlError("XX000", "range is already split")
        splits.append(k)
        return [], [], "ALTER TABLE"

    # crate-style implicit MVCC column: `alter table t add _version`
    # gives every row a server-managed _version (1 on insert, bumped on
    # every update) that WHERE clauses may check optimistically
    m = re.fullmatch(r"alter\s+table\s+(\w+)\s+add\s+_version", s, re.I)
    if m:
        t = _table(data, m.group(1).lower())
        if "_version" not in t["cols"]:
            t["cols"].append("_version")
            t["rows"] = [row + [1] for row in t["rows"]]
        return [], [], "ALTER TABLE"

    # -- INSERT ----------------------------------------------------------
    m = re.fullmatch(r"insert\s+into\s+(\w+)\s*(?:\(([^)]*)\)\s*)?"
                     r"values\s*(.+)", s, re.I | re.S)
    if m:
        t = _table(data, m.group(1).lower())
        cols = ([c.strip().lower() for c in m.group(2).split(",")]
                if m.group(2) else t["cols"])
        count = 0
        for tup in re.finditer(r"\(([^)]*)\)", m.group(3)):
            vals = [_parse_lit(v) for v in tup.group(1).split(",")]
            if len(vals) != len(cols):
                raise SqlError("42601", "column/value count mismatch")
            by_col = dict(zip(cols, vals))
            if "_version" in t["cols"] and "_version" not in by_col:
                by_col["_version"] = 1  # server-managed MVCC column
            row = [by_col.get(c) for c in t["cols"]]
            # duplicate check on the declared primary key column
            pk = t.get("pkey")
            if pk and pk in by_col and any(
                r.get(pk) == by_col[pk] for r in _rows_as_dicts(t)
            ):
                raise SqlError(
                    "23505", "duplicate key value violates unique constraint")
            t["rows"].append(row)
            count += 1
        return [], [], f"INSERT 0 {count}"

    # -- SELECT ----------------------------------------------------------
    # `for update` row locking is a no-op here: every transaction holds
    # the global lock anyway
    s_nolock = re.sub(r"\s+for\s+update\s*$", "", s, flags=re.I)
    m = re.fullmatch(r"select\s+(.+?)\s+from\s+(\w+)"
                     r"(?:\s+where\s+(.+))?", s_nolock, re.I | re.S)
    if m:
        t = _table(data, m.group(2).lower())
        conds = _parse_where(m.group(3))
        rows = [r for r in _rows_as_dicts(t)
                if all(c.matches(r) for c in conds)]
        expr = m.group(1).strip()
        agg = re.fullmatch(r"max\s*\(\s*(\w+)\s*\)(?:\s+as\s+(\w+))?",
                           expr, re.I)
        if agg:
            col = agg.group(1).lower()
            vals = [r[col] for r in rows if r.get(col) is not None]
            out = max(vals) if vals else None
            name = (agg.group(2) or "max").lower()
            return [name], [(_fmt(out),)], "SELECT 1"
        if expr == "*":
            cols = t["cols"]
        else:
            cols = [c.strip().lower() for c in expr.split(",")]
        out_rows = [tuple(_fmt(r.get(c)) for c in cols) for r in rows]
        return cols, out_rows, f"SELECT {len(out_rows)}"

    # SELECT without FROM: functions / literals
    m = re.fullmatch(r"select\s+(.+)", s, re.I | re.S)
    if m:
        expr = m.group(1).strip()
        if re.fullmatch(r"cluster_logical_timestamp\s*\(\s*\)", expr, re.I):
            data["hlc"] = int(data.get("hlc") or 0) + 1
            # cockroach returns a decimal <walltime>.<logical>
            return (["cluster_logical_timestamp"],
                    [(f"{data['hlc']}.0000000000",)], "SELECT 1")
        if re.fullmatch(r"now\s*\(\s*\)", expr, re.I):
            return ["now"], [(str(time.time()),)], "SELECT 1"
        if re.fullmatch(r"\d+", expr):
            return ["?column?"], [(expr,)], "SELECT 1"
        raise SqlError("42601", f"can't parse SELECT expr: {expr!r}")

    # -- UPDATE ----------------------------------------------------------
    m = re.fullmatch(r"update\s+(\w+)\s+set\s+(.+?)"
                     r"(?:\s+where\s+(.+))?", s, re.I | re.S)
    if m:
        t = _table(data, m.group(1).lower())
        # quote-aware assignment scan (commas may appear INSIDE string
        # literals, so splitting the clause on "," would mangle them)
        sets = []  # (col, fn(row-dict) -> value)
        set_clause = m.group(2).strip()
        assign_re = re.compile(
            rf"(\w+)\s*=\s*({_LIT}|\w+\s*[+-]\s*\d+)\s*(?:,\s*|$)", re.I)
        pos = 0
        while pos < len(set_clause):
            sm = assign_re.match(set_clause, pos)
            if not sm:
                raise SqlError("42601",
                               f"can't parse SET: {set_clause[pos:]!r}")
            col, rhs = sm.group(1).lower(), sm.group(2).strip()
            am = re.fullmatch(r"(\w+)\s*([+-])\s*(\d+)", rhs)
            if am and am.group(1).lower() == col:
                # arithmetic in place: col = col [+-] n (bank's
                # in-place transfer shape)
                delta = int(am.group(3))
                if am.group(2) == "-":
                    delta = -delta
                sets.append((col,
                             lambda rd, col=col, delta=delta:
                             (rd.get(col) or 0) + delta))
            else:
                lit = _parse_lit(rhs)
                sets.append((col, lambda rd, lit=lit: lit))
            pos = sm.end()
        conds = _parse_where(m.group(3))
        count = 0
        for i, row in enumerate(t["rows"]):
            rd = dict(zip(t["cols"], row))
            if all(c.matches(rd) for c in conds):
                for col, fn in sets:
                    rd[col] = fn(rd)
                if "_version" in t["cols"]:
                    rd["_version"] = (rd.get("_version") or 0) + 1
                t["rows"][i] = [rd.get(c) for c in t["cols"]]
                count += 1
        return [], [], f"UPDATE {count}"

    # -- DELETE ----------------------------------------------------------
    m = re.fullmatch(r"delete\s+from\s+(\w+)(?:\s+where\s+(.+))?", s,
                     re.I | re.S)
    if m:
        t = _table(data, m.group(1).lower())
        conds = _parse_where(m.group(2))
        keep, dropped = [], 0
        for row in t["rows"]:
            rd = dict(zip(t["cols"], row))
            if all(c.matches(rd) for c in conds):
                dropped += 1
            else:
                keep.append(row)
        t["rows"] = keep
        return [], [], f"DELETE {dropped}"

    raise SqlError("42601", f"can't parse statement: {s!r}")


# ---------------------------------------------------------------------------
# pgwire server


def _msg(t: bytes, payload: bytes = b"") -> bytes:
    return t + struct.pack("!i", 4 + len(payload)) + payload


def _error_response(sqlstate: str, message: str) -> bytes:
    fields = (b"SERROR\x00"
              + b"C" + sqlstate.encode() + b"\x00"
              + b"M" + message.encode() + b"\x00\x00")
    return _msg(b"E", fields)


def _row_description(cols: list) -> bytes:
    body = struct.pack("!h", len(cols))
    for c in cols:
        body += c.encode() + b"\x00"
        body += struct.pack("!ihihih", 0, 0, 25, -1, -1, 0)  # oid 25 = text
    return _msg(b"T", body)


def _data_row(row: tuple) -> bytes:
    body = struct.pack("!h", len(row))
    for v in row:
        if v is None:
            body += struct.pack("!i", -1)
        else:
            b = v.encode()
            body += struct.pack("!i", len(b)) + b
    return _msg(b"D", body)


class Handler(socketserver.BaseRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0

    def _read_exact(self, n: int) -> bytes:
        return pg_proto._read_exact(self.request, n)

    def handle(self):
        self.request.settimeout(SESSION_IDLE_TIMEOUT)
        txn = StoreTxn(self.store)
        aborted = False  # txn hit an error; only ROLLBACK accepted
        try:
            # startup (possibly preceded by an SSLRequest)
            while True:
                (length,) = struct.unpack("!i", self._read_exact(4))
                payload = self._read_exact(length - 4)
                (code,) = struct.unpack("!i", payload[:4])
                if code == pg_proto.SSL_REQUEST:
                    self.request.sendall(b"N")
                    continue
                break  # StartupMessage; params ignored (trust auth)
            self.request.sendall(_msg(b"R", struct.pack("!i", 0)))
            self.request.sendall(
                _msg(b"S", b"server_version\x00jepsen-tpu-crdb-sim\x00"))
            self.request.sendall(_msg(b"Z", b"I"))

            while True:
                t = self._read_exact(1)
                (length,) = struct.unpack("!i", self._read_exact(4))
                payload = self._read_exact(length - 4)
                if t == b"X":
                    return
                if t != b"Q":
                    self.request.sendall(_error_response(
                        "0A000", f"unsupported message {t!r}"))
                    self.request.sendall(_msg(b"Z", b"I"))
                    continue
                sql = payload.rstrip(b"\x00").decode()
                if self.mean_latency > 0:
                    time.sleep(random.expovariate(1.0 / self.mean_latency))
                txn, aborted = self._statement(sql, txn, aborted)
        except (ConnectionError, TimeoutError, OSError):
            pass
        finally:
            txn.rollback()

    def _statement(self, sql: str, txn: StoreTxn, aborted: bool) -> tuple:
        s = sql.strip().rstrip(";").strip().upper()
        out = []
        try:
            if s in ("BEGIN", "START TRANSACTION"):
                if not txn.active:
                    if not txn.begin(timeout=TXN_LOCK_TIMEOUT):
                        raise SqlError("40001", _RESTART_MSG)
                out.append(_msg(b"C", b"BEGIN\x00"))
                aborted = False
            elif s == "COMMIT":
                if aborted:
                    txn.rollback()
                    out.append(_msg(b"C", b"ROLLBACK\x00"))
                    aborted = False
                else:
                    if txn.active:
                        txn.commit()
                    out.append(_msg(b"C", b"COMMIT\x00"))
            elif s == "ROLLBACK":
                txn.rollback()
                aborted = False
                out.append(_msg(b"C", b"ROLLBACK\x00"))
            elif aborted:
                raise SqlError(
                    "25P02",
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block")
            elif txn.active:
                cols, rows, tag = execute(txn.data, sql)
                if cols:
                    out.append(_row_description(cols))
                    out.extend(_data_row(r) for r in rows)
                out.append(_msg(b"C", tag.encode() + b"\x00"))
            else:
                # autocommit: one bounded-wait txn around the statement
                one = StoreTxn(self.store)
                if not one.begin(timeout=TXN_LOCK_TIMEOUT):
                    raise SqlError("40001", _RESTART_MSG)
                try:
                    cols, rows, tag = execute(one.data, sql)
                    one.commit()
                except BaseException:
                    one.rollback()
                    raise
                if cols:
                    out.append(_row_description(cols))
                    out.extend(_data_row(r) for r in rows)
                out.append(_msg(b"C", tag.encode() + b"\x00"))
        except SqlError as e:
            out.append(_error_response(e.sqlstate, e.message))
            if txn.active:
                aborted = True
        status = b"T" if txn.active else b"I"
        if txn.active and aborted:
            status = b"E"
        out.append(_msg(b"Z", status))
        self.request.sendall(b"".join(out))
        return txn, aborted


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def parse_args(argv):
    p = argparse.ArgumentParser(description="cockroachdb pgwire sim",
                                allow_abbrev=False)
    p.add_argument("command", nargs="?", default="start")  # `cockroach start`
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=26257)
    p.add_argument("--name", default="sim")
    # cockroach flags tolerated for command-line compatibility:
    p.add_argument("--join", default=None)
    p.add_argument("--insecure", action="store_true")
    p.add_argument("--store", default=None)
    p.add_argument("--http-port", default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    srv = Server(("127.0.0.1", args.port), Handler)
    print(f"crdb-sim {args.name} serving pgwire on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    """A cockroach-shaped tar.gz whose `cockroach` binary launches this
    sim (installed through the suite's normal install_archive path)."""
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.crdb_sim", "cockroach", "cockroach-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
