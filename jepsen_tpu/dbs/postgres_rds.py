"""Postgres-RDS test suite: bank transfers against a managed Postgres
endpoint (reference:
/root/reference/postgres-rds/src/jepsen/postgres_rds.clj:1-294).

The managed-service shape: there is NO DB lifecycle — the endpoint
exists outside the test (RDS), so db is a no-op and the node list names
the endpoint. The client holds a reconnect-on-failure pgwire connection
(the reference's with-conn atom dance, postgres_rds.clj:44-66), runs
transfers in explicit transactions with optional `for update` row
locks, converts txn aborts to definite :fails, and the checker demands
every read total the starting balance.

Hermetically testable against dbs/crdb_sim (any pgwire server works).
"""

from __future__ import annotations

import logging
import random
import socket

from .. import checker as checker_mod
from .common import once as _once, shared_flag as _shared_flag
from .. import cli, client, db, generator as gen, nemesis, reconnect
from ..checker import Checker
from ..history import Op, ops as _ops
from . import pg_proto

log = logging.getLogger("jepsen_tpu.dbs.postgres_rds")

PORT = 5432


def _cfg(test) -> dict:
    return test.get("postgres_rds") or {}


def endpoint(test) -> tuple:
    """(host, port) of the managed endpoint — the first 'node', or an
    explicit endpoint option (postgres_rds.clj:276-281 ignores the node
    list and dials the AWS hostname)."""
    cfg = _cfg(test)
    if cfg.get("endpoint"):
        return cfg["endpoint"]
    node = test["nodes"][0]
    fn = cfg.get("addr_fn")
    host = fn(node) if fn else str(node)
    ports = cfg.get("ports")
    return host, (ports[node] if ports else PORT)


TXN_ABORT_MARKERS = (
    "restart transaction",                       # cockroach-style
    "deadlock found when trying to get lock",    # galera-style
    "was aborted",                               # postgres batch aborts
    "serialization failure",
)


def txn_aborted(e: pg_proto.PgError) -> bool:
    """Aborted transactions definitely did not commit
    (postgres_rds.clj:68-99's capture-txn-abort)."""
    return e.retryable or any(
        m in str(e).lower() for m in TXN_ABORT_MARKERS)


class BankClient(client.Client):
    """Account transfers in explicit transactions
    (postgres_rds.clj:118-202). lock_type=' for update' reproduces the
    reference's row-locking variant; in_place=True updates balances
    with arithmetic in SQL instead of read-modify-write."""

    def __init__(self, n: int = 8, starting_balance: int = 10,
                 lock_type: str = "", in_place: bool = False,
                 conn=None, flag=None):
        self.n = n
        self.starting_balance = starting_balance
        self.lock_type = lock_type
        self.in_place = in_place
        self.conn = conn
        self.flag = flag or _shared_flag()

    def open(self, test, node):
        host, port = endpoint(test)
        wrapped = reconnect.wrapper(
            open=lambda: pg_proto.PgConn(host, port, user="jepsen",
                                         database="jepsen", timeout=10.0),
            close=lambda c: c.close(),
            name=f"postgres-rds {node}",
        ).open()
        return BankClient(self.n, self.starting_balance, self.lock_type,
                          self.in_place, wrapped, self.flag)

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                c.query("drop table if exists accounts")
                c.query("create table accounts "
                        "(id int not null primary key, "
                        "balance bigint not null)")
                for i in range(self.n):
                    try:
                        c.query(f"insert into accounts (id, balance) values "
                                f"({i}, {self.starting_balance})")
                    except pg_proto.PgError as e:
                        if "duplicate key" not in str(e):
                            raise

        _once(self.flag, create)

    def invoke(self, test, op: Op) -> Op:
        try:
            with self.conn.with_conn() as c:
                c.query("begin")
                try:
                    out = self._body(c, op)
                except BaseException:
                    try:
                        c.query("rollback")
                    except (OSError, pg_proto.PgError,
                            pg_proto.PgProtocolError):
                        pass
                    raise
                c.query("commit")
                return out
        except pg_proto.PgError as e:
            if txn_aborted(e):
                return op.with_(type="fail", error=("txn-abort", str(e)))
            crash = "fail" if op.f == "read" else "info"
            return op.with_(type=crash, error=str(e))
        except (socket.timeout, TimeoutError):
            return op.with_(
                type="fail" if op.f == "read" else "info", error="timeout")
        except (ConnectionError, pg_proto.PgProtocolError, OSError) as e:
            return op.with_(
                type="fail" if op.f == "read" else "info", error=str(e))

    def _body(self, c, op: Op) -> Op:
        if op.f == "read":
            rows = c.query(
                f"select id, balance from accounts{self.lock_type}").rows
            balances = [int(b) for _, b in
                        sorted(rows, key=lambda r: int(r[0]))]
            return op.with_(type="ok", value=balances)
        if op.f == "transfer":
            frm, to = op.value["from"], op.value["to"]
            amount = op.value["amount"]
            b1 = int(c.query(
                f"select balance from accounts where id = {frm}"
                f"{self.lock_type}").scalars()[0]) - amount
            b2 = int(c.query(
                f"select balance from accounts where id = {to}"
                f"{self.lock_type}").scalars()[0]) + amount
            if b1 < 0:
                return op.with_(type="fail", error=("negative", frm, b1))
            if b2 < 0:
                return op.with_(type="fail", error=("negative", to, b2))
            if self.in_place:
                # arithmetic updates in SQL (postgres_rds.clj:195-198)
                c.query(f"update accounts set balance = balance - {amount}"
                        f" where id = {frm}")
                c.query(f"update accounts set balance = balance + {amount}"
                        f" where id = {to}")
            else:
                c.query(f"update accounts set balance = {b1} "
                        f"where id = {frm}")
                c.query(f"update accounts set balance = {b2} "
                        f"where id = {to}")
            return op.with_(type="ok")
        raise ValueError(f"unknown op {op.f!r}")

    def close(self, test):
        if self.conn:
            self.conn.close()


class RdsBankChecker(Checker):
    """Every ok read must list exactly n balances totalling n×starting
    (postgres_rds.clj:235-260)."""

    def __init__(self, n: int, total: int):
        self.n = n
        self.total = total

    def check(self, test, history, opts=None) -> dict:
        bad = []
        for o in _ops(history):
            if not (o.is_ok and o.f == "read"):
                continue
            balances = o.value
            if len(balances) != self.n:
                bad.append({"type": "wrong-n", "expected": self.n,
                            "found": len(balances), "op": o.to_dict()})
            elif sum(balances) != self.total:
                bad.append({"type": "wrong-total", "expected": self.total,
                            "found": sum(balances), "op": o.to_dict()})
        return {"valid": not bad, "bad_reads": bad[:10]}


def bank_read(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def bank_transfer(test, process):
    n = test.get("accounts_n", 8)
    return {"type": "invoke", "f": "transfer",
            "value": {"from": random.randrange(n),
                      "to": random.randrange(n),
                      "amount": random.randrange(5)}}


def bank_diff_transfer():
    return gen.filter_gen(
        lambda op: op["value"]["from"] != op["value"]["to"], bank_transfer)


def rds_test(opts: dict) -> dict:
    """Bank test against a managed endpoint (postgres_rds.clj:269-294):
    no DB lifecycle, no nemesis (the service's failovers ARE the
    nemesis), mixed reads/transfers then a final quiescent read."""
    from ..testlib import noop_test

    n = opts.get("accounts", 8)
    starting = opts.get("starting_balance", 10)
    lock_type = " for update" if opts.get("lock") else ""
    bank = BankClient(n, starting, lock_type=lock_type,
                      in_place=opts.get("in_place", False))
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": "postgres-rds bank",
            "os": None,
            "db": None,
            "client": bank,
            "nemesis": nemesis.noop,
            "accounts_n": n,
            "generator": gen.phases(
                gen.time_limit(
                    opts.get("time_limit", 20),
                    gen.clients(gen.stagger(
                        opts.get("stagger", 0.1),
                        gen.mix([bank_read, bank_diff_transfer()]))),
                ),
                gen.log("waiting for quiescence"),
                gen.sleep(opts.get("quiesce", 10)),
                gen.clients(gen.once(bank_read)),
            ),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "bank": RdsBankChecker(n, n * starting),
            }),
        }
    )
    return test


def _opt_spec(p) -> None:
    p.add_argument("--accounts", type=int, default=8)
    p.add_argument("--starting-balance", dest="starting_balance",
                   type=int, default=10)
    p.add_argument("--lock", action="store_true",
                   help="select ... for update row locking")
    p.add_argument("--in-place", dest="in_place", action="store_true")


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(rds_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
