"""Hermetic MySQL Cluster (NDB) archive: the mgmd/ndbd/mysqld ROLES.

The real deployment runs three process types with distinct node-id
bands and data dirs (/root/reference/mysql-cluster/src/jepsen/
mysql_cluster.clj:53-57,140-168): ndb_mgmd (management, port 1186),
ndbd (storage, on the first four nodes), and mysqld (SQL, 3306). The
archive mirrors that shape: `ndb_mgmd` and `ndbd` are role
placeholders (dbs/role_sim — real pids, ports, logs; kill/restart
targets), `mysqld` is the MySQL-protocol sim. All three share the same
state file, standing in for NDB's replicated storage.
"""

from __future__ import annotations

from .simbase import build_multi_sim_archive


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_multi_sim_archive(
        dest, "mysql-cluster-sim",
        {
            "ndb_mgmd": "jepsen_tpu.dbs.role_sim",
            "ndbd": "jepsen_tpu.dbs.role_sim",
            "mysqld": "jepsen_tpu.dbs.mysql_sim",
        },
        data_path, mean_latency=mean_latency, python=python,
    )
