"""A hermetic RESP server covering both redis-like registers (GET/SET —
the raftis suite's surface, raftis.clj:37-42) and disque-like job
queues (ADDJOB/GETJOB/ACKJOB — disque.clj:141-152), plus PING and
CLUSTER MEET. Studied from the reference suites' command usage, not
copied.

Shared flock-guarded JSON state across member processes, like the other
sims. Job state: enqueued ids per queue plus an in-flight set — GETJOB
moves a job to in-flight with a timestamp, ACKJOB deletes it, and jobs
in-flight longer than RETRY_S are REDELIVERED on the next GETJOB
(disque's at-least-once semantics: a consumer that crashes between
GETJOB and ACKJOB must not strand the job)."""

from __future__ import annotations

import argparse
import random
import socketserver
import sys
import time

from .simbase import Store, build_sim_archive

RETRY_S = 1.0  # in-flight jobs older than this are redelivered


class Handler(socketserver.StreamRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0

    # -- wire -------------------------------------------------------------

    def _read_command(self) -> list | None:
        line = self.rfile.readline()
        if not line:
            return None
        line = line.strip()
        if not line.startswith(b"*"):
            # inline command
            return [p.decode() for p in line.split()]
        n = int(line[1:])
        args = []
        for _ in range(n):
            hdr = self.rfile.readline().strip()
            assert hdr.startswith(b"$"), hdr
            size = int(hdr[1:])
            args.append(self.rfile.read(size).decode())
            self.rfile.read(2)
        return args

    def _simple(self, s: str) -> None:
        self.wfile.write(b"+" + s.encode() + b"\r\n")

    def _error(self, s: str) -> None:
        self.wfile.write(b"-" + s.encode() + b"\r\n")

    def _bulk(self, s) -> None:
        if s is None:
            self.wfile.write(b"$-1\r\n")
            return
        b = s if isinstance(s, bytes) else str(s).encode()
        self.wfile.write(b"$%d\r\n%s\r\n" % (len(b), b))

    def _array(self, items) -> None:
        if items is None:
            self.wfile.write(b"*-1\r\n")
            return
        self.wfile.write(b"*%d\r\n" % len(items))
        for it in items:
            if isinstance(it, (list, tuple)):
                self._array(it)
            else:
                self._bulk(it)

    # -- dispatch ---------------------------------------------------------

    def handle(self):
        while True:
            try:
                args = self._read_command()
            except (ConnectionError, OSError, AssertionError):
                return
            if args is None:
                return
            if self.mean_latency > 0:
                time.sleep(random.expovariate(1.0 / self.mean_latency))
            cmd = args[0].upper()
            try:
                fn = getattr(self, f"cmd_{cmd.lower()}", None)
                if fn is None:
                    self._error(f"ERR unknown command '{cmd}'")
                else:
                    fn(args[1:])
                self.wfile.flush()
            except (ConnectionError, OSError):
                return

    # -- commands ---------------------------------------------------------

    def cmd_ping(self, args):
        self._simple("PONG")

    def cmd_set(self, args):
        k, v = args[0], args[1]

        def put(data):
            kv = dict(data.get("kv") or {})
            kv[k] = v
            new = dict(data)
            new["kv"] = kv
            return None, new

        self.store.transact(put)
        self._simple("OK")

    def cmd_get(self, args):
        k = args[0]

        def get(data):
            return (data.get("kv") or {}).get(k), None

        self._bulk(self.store.transact(get))

    def cmd_cluster(self, args):
        # CLUSTER MEET <ip> <port> — membership is implicit (shared
        # state), so meeting always succeeds
        self._simple("OK")

    def cmd_addjob(self, args):
        # ADDJOB <queue> <body> <ms-timeout> [...params]
        queue, body = args[0], args[1]

        def add(data):
            counter = int(data.get("job_counter") or 0) + 1
            job_id = f"D-{counter:08d}"
            jobs = dict(data.get("jobs") or {})
            jobs[job_id] = {"queue": queue, "body": body, "state": "queued"}
            queues = dict(data.get("queues") or {})
            queues[queue] = list(queues.get(queue) or []) + [job_id]
            new = dict(data)
            new["jobs"], new["queues"], new["job_counter"] = (
                jobs, queues, counter)
            return job_id, new

        self._bulk(self.store.transact(add))

    def cmd_getjob(self, args):
        # GETJOB [TIMEOUT ms] [COUNT n] FROM queue [queue ...]
        timeout_ms = 0
        count = 1
        queues: list = []
        i = 0
        while i < len(args):
            a = args[i].upper()
            if a == "TIMEOUT":
                timeout_ms = int(args[i + 1])
                i += 2
            elif a == "COUNT":
                count = int(args[i + 1])
                i += 2
            elif a == "FROM":
                queues = args[i + 1:]
                break
            else:
                i += 1

        def take(data):
            out = []
            jobs = dict(data.get("jobs") or {})
            qmap = dict(data.get("queues") or {})
            now = time.time()
            # redeliver in-flight jobs whose consumer went quiet
            for jid, job in jobs.items():
                if (job.get("state") == "active"
                        and now - job.get("taken_at", 0) > RETRY_S
                        and jid not in (qmap.get(job["queue"]) or [])):
                    qmap[job["queue"]] = (list(qmap.get(job["queue"]) or [])
                                          + [jid])
            for q in queues:
                pending = list(qmap.get(q) or [])
                while pending and len(out) < count:
                    jid = pending.pop(0)
                    if jid not in jobs:
                        continue  # acked while redelivery-queued: drop
                    job = dict(jobs[jid])
                    job["state"] = "active"
                    job["taken_at"] = now
                    jobs[jid] = job
                    out.append((q, jid, job["body"]))
                qmap[q] = pending
                if len(out) >= count:
                    break
            if not out and not any(
                j.get("state") == "active" for j in jobs.values()
            ):
                return None, None
            new = dict(data)
            new["jobs"], new["queues"] = jobs, qmap
            return out or None, new

        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            got = self.store.transact(take)
            if got is not None:
                return self._array([list(j) for j in got])
            if time.monotonic() >= deadline:
                return self._array(None)
            time.sleep(0.005)

    def cmd_ackjob(self, args):
        def ack(data):
            jobs = dict(data.get("jobs") or {})
            qmap = dict(data.get("queues") or {})
            n = 0
            for jid in args:
                if jid in jobs:
                    # drop from the job table AND any queue the
                    # redelivery scan may have put it back on — a
                    # dangling id would poison later GETJOBs
                    q = jobs[jid]["queue"]
                    if jid in (qmap.get(q) or []):
                        qmap[q] = [j for j in qmap[q] if j != jid]
                    del jobs[jid]
                    n += 1
            new = dict(data)
            new["jobs"], new["queues"] = jobs, qmap
            return n, new

        n = self.store.transact(ack)
        self.wfile.write(b":%d\r\n" % n)


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def parse_args(argv):
    p = argparse.ArgumentParser(description="redis/disque RESP sim",
                                allow_abbrev=False)
    p.add_argument("config_file", nargs="?", default=None)  # disque-server X
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=7711)
    p.add_argument("--name", default="sim")
    p.add_argument("--cluster", default=None)  # raftis flag, tolerated
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    srv = Server(("127.0.0.1", args.port), Handler)
    print(f"redis-sim {args.name} serving RESP on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, binary: str = "disque-server",
                  mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.redis_sim", binary, f"{binary}-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
