"""RabbitMQ test suite: a durable queue driven with confirmed
publishes and auto-ack gets, checked with total-queue (reference:
/root/reference/rabbitmq/src/jepsen/rabbitmq.clj:1-263).

The determinacy taxonomy follows the reference: a publish whose
confirm never arrives is :info (the broker may have it); an empty get
is a definite :fail :exhausted; values ride the framework codec
(EDN-in-the-reference, JSON here — rabbitmq.clj:111,157)."""

from __future__ import annotations

import itertools
import logging
import socket
import time

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, codec, generator as gen, osdist
from ..history import Op
from . import amqp_proto as aq
from .common import ArchiveDB, SuiteCfg, ready_gated_final

log = logging.getLogger("jepsen_tpu.dbs.rabbitmq")

PORT = 5672
QUEUE = "jepsen.queue"


_suite = SuiteCfg("rabbitmq", PORT, "/opt/rabbitmq")
node_host = _suite.host
node_port = _suite.port


class RabbitMQDB(ArchiveDB):
    """rabbitmq-server per node (rabbitmq.clj:40-99's apt/cluster
    bring-up condensed to the archive+daemon path)."""

    binary = "rabbitmq-server"
    log_name = "rabbitmq.log"
    pid_name = "rabbitmq.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        return ["--port", str(node_port(test, node))]

    def probe_ready(self, test, node) -> bool:
        conn = aq.AmqpConn(node_host(test, node), node_port(test, node),
                           timeout=2.0, connect_timeout=2.0)
        conn.close()
        return True


class QueueClient(client.Client):
    """Confirmed enqueues / auto-ack dequeues / drain
    (rabbitmq.clj:126-183)."""

    def __init__(self, conn: aq.AmqpConn | None = None):
        self.conn = conn

    def open(self, test, node):
        conn = aq.AmqpConn(node_host(test, node), node_port(test, node))
        conn.queue_declare(QUEUE, durable=True)
        conn.confirm_select()
        return QueueClient(conn)

    def _dequeue(self, op: Op) -> Op:
        body = self.conn.get(QUEUE)
        if body is None:
            return op.with_(type="fail", error="exhausted")
        return op.with_(type="ok", value=codec.decode(body))

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "enqueue":
                ok = self.conn.publish(QUEUE, codec.encode(op.value))
                return op.with_(type="ok" if ok else "fail")
            if op.f == "dequeue":
                return self._dequeue(op)
            if op.f == "drain":
                values = []
                deadline = time.monotonic() + 10.0
                try:
                    while time.monotonic() < deadline:
                        body = self.conn.get(QUEUE)
                        if body is None:
                            return op.with_(type="ok", value=values)
                        values.append(codec.decode(body))
                    return op.with_(type="info", error="drain-timeout",
                                    value=values)
                except (aq.AmqpError, ConnectionError, socket.timeout,
                        TimeoutError, OSError) as e:
                    # keep what was already auto-acked
                    return op.with_(type="info", error=str(e),
                                    value=values)
            raise ValueError(f"unknown op {op.f!r}")
        except aq.AmqpError as e:
            return op.with_(type="info", error=str(e))
        except (socket.timeout, TimeoutError):
            return op.with_(type="info", error="timeout")
        except (ConnectionError, OSError) as e:
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


def queue_gen() -> gen.Generator:
    counter = itertools.count()

    def enqueue(test, process):
        return {"type": "invoke", "f": "enqueue", "value": next(counter)}

    return gen.mix([enqueue, {"type": "invoke", "f": "dequeue"}])


def rabbitmq_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = RabbitMQDB(archive_url=opts.get("archive_url"))
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": "rabbitmq queue",
            "os": osdist.debian,
            "db": db_,
            "client": QueueClient(),
            "nemesis": cmn.pick_nemesis(db_, opts),
            "generator": gen.phases(
                gen.time_limit(
                    opts.get("time_limit", 60),
                    gen.nemesis(
                        gen.start_stop(10, 10),
                        gen.stagger(opts.get("stagger", 1 / 10),
                                    queue_gen()),
                    ),
                ),
                gen.log("Healing cluster"),
                gen.nemesis(gen.once({"type": "info", "f": "stop"})),
                gen.sleep(opts.get("quiesce", 10)),
                ready_gated_final(
                    db_,
                    gen.clients(gen.each(
                        lambda: gen.once(
                            {"type": "invoke", "f": "drain"}))),
                    opts),
            ),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "queue": checker_mod.total_queue(),
            }),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--archive-url", dest="archive_url", default=None)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(rabbitmq_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
