"""RabbitMQ test suite: a durable queue driven with confirmed
publishes and auto-ack gets, checked with total-queue (reference:
/root/reference/rabbitmq/src/jepsen/rabbitmq.clj:1-263), plus the
distributed-semaphore mutex workload (rabbitmq.clj:185-263): ONE
message in a durable queue, where holding the unacked delivery is
holding the lock and release is a reject-with-requeue — checked
against the linearizable mutex model, which is exactly how the
pattern's unsafety shows up (the broker requeues a partitioned
holder's message, so a second acquire succeeds with no intervening
release).

The determinacy taxonomy follows the reference: a publish whose
confirm never arrives is :info (the broker may have it); an empty get
is a definite :fail :exhausted; values ride the framework codec
(EDN-in-the-reference, JSON here — rabbitmq.clj:111,157). The mutex
client's taxonomy is the reference's too: acquires that time out or
hit channel errors are :fail, releases report :ok even on errors
because a dead channel requeues — the release "takes effect" either
way (rabbitmq.clj:218-259)."""

from __future__ import annotations

import itertools
import logging
import socket
import threading
import time

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, codec, generator as gen, osdist
from ..history import Op
from ..models import Mutex
from . import amqp_proto as aq
from .common import ArchiveDB, SuiteCfg, ready_gated_final

log = logging.getLogger("jepsen_tpu.dbs.rabbitmq")

PORT = 5672
QUEUE = "jepsen.queue"
SEMAPHORE = "jepsen.semaphore"


_suite = SuiteCfg("rabbitmq", PORT, "/opt/rabbitmq")
node_host = _suite.host
node_port = _suite.port


class RabbitMQDB(ArchiveDB):
    """rabbitmq-server per node (rabbitmq.clj:40-99's apt/cluster
    bring-up condensed to the archive+daemon path)."""

    binary = "rabbitmq-server"
    log_name = "rabbitmq.log"
    pid_name = "rabbitmq.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 60.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        return ["--port", str(node_port(test, node))]

    def probe_ready(self, test, node) -> bool:
        conn = aq.AmqpConn(node_host(test, node), node_port(test, node),
                           timeout=2.0, connect_timeout=2.0)
        conn.close()
        return True


class QueueClient(client.Client):
    """Confirmed enqueues / auto-ack dequeues / drain
    (rabbitmq.clj:126-183)."""

    def __init__(self, conn: aq.AmqpConn | None = None):
        self.conn = conn

    def open(self, test, node):
        conn = aq.AmqpConn(node_host(test, node), node_port(test, node))
        conn.queue_declare(QUEUE, durable=True)
        conn.confirm_select()
        return QueueClient(conn)

    def _dequeue(self, op: Op) -> Op:
        body = self.conn.get(QUEUE)
        if body is None:
            return op.with_(type="fail", error="exhausted")
        return op.with_(type="ok", value=codec.decode(body))

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "enqueue":
                ok = self.conn.publish(QUEUE, codec.encode(op.value))
                return op.with_(type="ok" if ok else "fail")
            if op.f == "dequeue":
                return self._dequeue(op)
            if op.f == "drain":
                values = []
                deadline = time.monotonic() + 10.0
                try:
                    while time.monotonic() < deadline:
                        body = self.conn.get(QUEUE)
                        if body is None:
                            return op.with_(type="ok", value=values)
                        values.append(codec.decode(body))
                    return op.with_(type="info", error="drain-timeout",
                                    value=values)
                except (aq.AmqpError, ConnectionError, socket.timeout,
                        TimeoutError, OSError) as e:
                    # keep what was already auto-acked
                    return op.with_(type="info", error=str(e),
                                    value=values)
            raise ValueError(f"unknown op {op.f!r}")
        except aq.AmqpError as e:
            return op.with_(type="info", error=str(e))
        except (socket.timeout, TimeoutError):
            return op.with_(type="info", error="timeout")
        except (ConnectionError, OSError) as e:
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


def queue_gen() -> gen.Generator:
    counter = itertools.count()

    def enqueue(test, process):
        return {"type": "invoke", "f": "enqueue", "value": next(counter)}

    return gen.mix([enqueue, {"type": "invoke", "f": "dequeue"}])


class MutexClient(client.Client):
    """The distributed-semaphore mutex (rabbitmq.clj:188-263): one
    message seeded into a durable queue; acquire = basic.get WITHOUT
    auto-ack (holding the unacked delivery is holding the lock),
    release = basic.reject with requeue. Seeding happens exactly once
    across all workers (the reference's shared `enqueued?` atom,
    :198-205): purge, publish one body, confirmed."""

    def __init__(self, conn: aq.AmqpConn | None = None,
                 seeded: threading.Event | None = None):
        self.conn = conn
        self.tag: int | None = None
        # shared across every opened copy (open() is called on the
        # prototype, like the reference's one (mutex) record)
        self._seeded = seeded or threading.Event()
        self._seed_lock = threading.Lock()

    def open(self, test, node):
        addr = (node_host(test, node), node_port(test, node))
        conn = aq.AmqpConn(*addr)
        conn.queue_declare(SEMAPHORE, durable=True)
        with self._seed_lock:
            if not self._seeded.is_set():
                conn.confirm_select()
                conn.queue_purge(SEMAPHORE)
                if not conn.publish(SEMAPHORE, b""):
                    raise RuntimeError(
                        "couldn't enqueue initial semaphore message!")
                self._seeded.set()
                # the seeding connection has confirms on; that only
                # affects publish, which the mutex never does again
        c = MutexClient(conn, self._seeded)
        c._seed_lock = self._seed_lock
        c._addr = addr
        return c

    def _reconnect(self) -> None:
        """Fresh connection after a channel error (the reference
        reopens its channel the same way, rabbitmq.clj:231-234). Any
        delivery the old connection held is requeued by the broker."""
        try:
            self.conn.close()
        except OSError:
            pass
        try:
            self.conn = aq.AmqpConn(*self._addr)
            self.conn.queue_declare(SEMAPHORE, durable=True)
        except (aq.AmqpError, ConnectionError, socket.timeout,
                TimeoutError, OSError):
            pass  # next op will fail and retry

    def invoke(self, test, op: Op) -> Op:
        if op.f == "acquire":
            if self.tag is not None:
                return op.with_(type="fail", error="already-held")
            try:
                got = self.conn.get_unacked(SEMAPHORE)
            except (aq.AmqpError, ConnectionError, socket.timeout,
                    TimeoutError, OSError) as e:
                # an errored acquire did not hand us a tag; whatever
                # the broker took it will requeue when this channel
                # dies — the reference calls these :fail (:222-241)
                self._reconnect()
                return op.with_(type="fail", error=str(e) or "timeout")
            if got is None:
                return op.with_(type="fail", error="empty")
            self.tag = got[0]
            return op.with_(type="ok", value=self.tag)
        if op.f == "release":
            if self.tag is None:
                return op.with_(type="fail", error="not-held")
            tag, self.tag = self.tag, None
            try:
                self.conn.reject(tag, requeue=True)
            except (aq.AmqpError, ConnectionError, socket.timeout,
                    TimeoutError, OSError) as e:
                # still :ok — a dead channel requeues the delivery, so
                # the lock IS released either way (rabbitmq.clj:245-259)
                self._reconnect()
                return op.with_(type="ok", error=str(e) or "timeout")
            return op.with_(type="ok")
        raise ValueError(f"unknown op {op.f!r}")

    def close(self, test):
        # dropping the connection releases any held delivery (the
        # broker requeues it)
        if self.conn:
            self.conn.close()


def mutex_gen() -> gen.Generator:
    """Each process alternates acquire/release forever — the reference
    test's (gen/each (gen/seq (cycle [acquire release]))),
    rabbitmq_test.clj:30-34 — built from the same combinators."""

    def alternating():
        return gen.seq(itertools.cycle(
            [{"type": "invoke", "f": "acquire"},
             {"type": "invoke", "f": "release"}]))

    return gen.each(alternating)


def rabbitmq_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = RabbitMQDB(archive_url=opts.get("archive_url"))
    test = noop_test()
    test.update(opts)
    workload = opts.get("workload", "queue")
    if workload == "mutex":
        # rabbitmq_test.clj:18-43: the Semaphore client against the
        # linearizable mutex model under a partition nemesis — the
        # workload EXPECTS to catch the pattern's unsafety on a real
        # broker. The reference paces each process at 180 s because
        # its partitions run 100 s; the cadence scales with
        # time-limit here.
        delay = opts.get("mutex_delay")
        if delay is None:
            delay = max(1.0, opts.get("time_limit", 60) / 20)
        test.update(
            {
                "name": "rabbitmq mutex",
                "os": osdist.debian,
                "db": db_,
                "client": MutexClient(),
                "nemesis": cmn.pick_nemesis(db_, opts),
                "generator": gen.phases(
                    gen.time_limit(
                        opts.get("time_limit", 60),
                        gen.nemesis(
                            gen.start_stop(5, 15),
                            gen.delay(delay, mutex_gen()),
                        ),
                    ),
                    gen.log("Healing cluster"),
                    gen.nemesis(gen.once({"type": "info", "f": "stop"})),
                ),
                "checker": checker_mod.compose({
                    "perf": checker_mod.perf_checker(),
                    "timeline": checker_mod.timeline_html(),
                    "linear": checker_mod.linearizable(Mutex()),
                }),
            }
        )
        return test
    test.update(
        {
            "name": "rabbitmq queue",
            "os": osdist.debian,
            "db": db_,
            "client": QueueClient(),
            "nemesis": cmn.pick_nemesis(db_, opts),
            "generator": gen.phases(
                gen.time_limit(
                    opts.get("time_limit", 60),
                    gen.nemesis(
                        gen.start_stop(10, 10),
                        gen.stagger(opts.get("stagger", 1 / 10),
                                    queue_gen()),
                    ),
                ),
                gen.log("Healing cluster"),
                gen.nemesis(gen.once({"type": "info", "f": "stop"})),
                gen.sleep(opts.get("quiesce", 10)),
                ready_gated_final(
                    db_,
                    gen.clients(gen.each(
                        lambda: gen.once(
                            {"type": "invoke", "f": "drain"}))),
                    opts),
            ),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "queue": checker_mod.total_queue(),
            }),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--archive-url", dest="archive_url", default=None)
    p.add_argument("--workload", default="queue",
                   choices=["queue", "mutex"])
    p.add_argument("--mutex-delay", dest="mutex_delay", type=float,
                   default=None)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(rabbitmq_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
