"""A hermetic Consul lookalike: the /v1/kv subset the consul suite
drives — base64-encoded values with CreateIndex/ModifyIndex, ?cas=index
check-and-set, X-Consul-Index headers — plus /v1/status/leader
(reference behavior: consul/src/jepsen/consul.clj:66-146 — studied for
parity, not copied).

Like the other sims, member processes share one flock-guarded JSON
state file; every op takes the exclusive lock, so the simulated cluster
is linearizable by construction."""

from __future__ import annotations

import argparse
import base64
import json
import random
import sys
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .simbase import Store, build_sim_archive

KV_PREFIX = "/v1/kv/"


class Handler(BaseHTTPRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        sys.stdout.write("%s - %s\n" % (self.address_string(), fmt % args))
        sys.stdout.flush()

    def _jitter(self):
        if self.mean_latency > 0:
            time.sleep(random.expovariate(1.0 / self.mean_latency))

    def _reply(self, status: int, body, headers: dict | None = None):
        payload = (body if isinstance(body, bytes)
                   else json.dumps(body).encode())
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(payload)

    def _key(self) -> str | None:
        path = urllib.parse.urlparse(self.path).path
        if not path.startswith(KV_PREFIX):
            return None
        return urllib.parse.unquote(path[len(KV_PREFIX):])

    def do_GET(self):
        self._jitter()
        path = urllib.parse.urlparse(self.path).path
        if path == "/v1/status/leader":
            return self._reply(200, "127.0.0.1:8300")
        k = self._key()
        if k is None:
            return self._reply(404, {})

        def read(data):
            kv = data.get("kv") or {}
            return kv.get(k), None

        entry = self.store.transact(read)
        if entry is None:
            return self._reply(404, b"", {"X-Consul-Index": 1})
        body = [{
            "CreateIndex": entry["create"],
            "ModifyIndex": entry["modify"],
            "Key": k,
            "Flags": 0,
            "Value": entry["value"],  # already base64
        }]
        self._reply(200, body, {"X-Consul-Index": entry["modify"]})

    def do_PUT(self):
        self._jitter()
        k = self._key()
        if k is None:
            return self._reply(404, {})
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length)
        value = base64.b64encode(raw).decode()
        query = urllib.parse.parse_qs(
            urllib.parse.urlparse(self.path).query)
        cas = query.get("cas")

        def put(data):
            kv = dict(data.get("kv") or {})
            next_index = int(data.get("index") or 0) + 1
            cur = kv.get(k)
            if cas is not None:
                want = int(cas[0])
                # consul cas semantics: 0 means "create only"; else the
                # ModifyIndex must match
                if want == 0 and cur is not None:
                    return False, None
                if want != 0 and (cur is None or cur["modify"] != want):
                    return False, None
            kv[k] = {
                "create": cur["create"] if cur else next_index,
                "modify": next_index,
                "value": value,
            }
            new = dict(data)
            new["kv"] = kv
            new["index"] = next_index
            return True, new

        ok = self.store.transact(put)
        self._reply(200, b"true" if ok else b"false")


def parse_args(argv):
    p = argparse.ArgumentParser(description="consul kv sim",
                                allow_abbrev=False)
    p.add_argument("command", nargs="?", default="agent")  # `consul agent`
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("-http-port", dest="http_port", type=int, default=None)
    # consul agent flags tolerated for command-line compatibility:
    p.add_argument("-server", action="store_true")
    p.add_argument("-bootstrap", action="store_true")
    p.add_argument("-bind", default=None)
    p.add_argument("-client", default=None)
    p.add_argument("-join", default=None)
    p.add_argument("-node", default="sim")
    p.add_argument("-data-dir", default=None)
    p.add_argument("-log-level", default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    port = args.http_port or args.port
    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    print(f"consul-sim {args.node} serving on {port}, "
          f"data={args.data}")
    sys.stdout.flush()
    httpd.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.consul_sim", "consul", "consul-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
