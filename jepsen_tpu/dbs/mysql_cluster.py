"""MySQL Cluster (NDB) test suite: bank and sets workloads over the
MySQL protocol (reference:
/root/reference/mysql-cluster/src/jepsen/mysql_cluster.clj:1-227;
clients live in mysql_common.py).

The deployment is the real ROLE SPLIT: ndb_mgmd (management, port
1186) on every node, ndbd (storage) on the FIRST FOUR nodes only
(mysql_cluster.clj:100-103's ndbd-nodes), and mysqld (SQL, 3306) on
every node — with the reference's node-id bands (mgmd +1, ndbd +11,
mysqld +21; mysql_cluster.clj:53-73) and distinct data dirs, brought
up in order: mgmd everywhere, then ndbd once the management quorum
answers, then mysqld (the reference synchronizes between stages;
here each stage polls ports). The kill-mgmd / kill-ndbd / kill-mysqld
nemeses target roles independently — killing an ndbd must leave the
node's mysqld serving, which tests/test_mysql_suites.py exercises.

Hermetic runs install dbs/mysql_cluster_sim's archive: mgmd/ndbd as
role placeholders with real pids/ports/logs, mysqld as the
MySQL-protocol sim.
"""

from __future__ import annotations

from .. import cli
from ..control import util as cu
from .mysql_common import make_sql_suite

MGMD_PORT = 1186
NDBD_PORT = 2202
# reference node-id bands (mysql_cluster.clj:57-73)
MGMD_ID_OFFSET = 1
NDBD_ID_OFFSET = 11
MYSQLD_ID_OFFSET = 21
NDBD_NODE_COUNT = 4  # ndbd runs on the first four nodes only

ROLES = ("mgmd", "ndbd", "mysqld")
_ROLE_TAG = {"mgmd": "jepsen-mgmd", "ndbd": "jepsen-ndbd",
             "mysqld": "jepsen-mysqld"}
_ROLE_BIN = {"mgmd": "ndb_mgmd", "ndbd": "ndbd", "mysqld": "mysqld"}
_ROLE_OFFSET = {"mgmd": MGMD_ID_OFFSET, "ndbd": NDBD_ID_OFFSET,
                "mysqld": MYSQLD_ID_OFFSET}


def _make_db(suite):
    from .common import MultiDaemonDB

    class MysqlClusterDB(MultiDaemonDB):
        """mgmd/ndbd/mysqld per node with the reference's ordered
        bring-up (mysql_cluster.clj:140-199). The base-class
        single-daemon surface points at mysqld, so the shared
        start-kill/hammer-time nemeses hit the SQL daemon while the
        management and storage roles stay up."""

        binary = "mysqld"
        log_name = "jepsen-mysqld.log"
        pid_name = "jepsen-mysqld.pid"

        ROLES = ROLES
        ROLE_TAG = _ROLE_TAG
        ROLE_BIN = _ROLE_BIN
        # reference stop order: mysqld, ndbd, mgmd
        # (mysql_cluster.clj:201-207)
        STOP_ORDER = ("mysqld", "ndbd", "mgmd")

        def __init__(self, archive_url=None, ready_timeout=60.0):
            super().__init__(suite, archive_url, ready_timeout)

        # ---- role topology ----

        def node_id(self, test, node, role) -> int:
            return _ROLE_OFFSET[role] + list(test["nodes"]).index(node)

        def role_nodes(self, test, role) -> list:
            if role == "ndbd":
                return list(test["nodes"])[:NDBD_NODE_COUNT]
            return list(test["nodes"])

        def role_port(self, test, node, role) -> int:
            if role == "mysqld":
                return suite.port(test, node)
            ports = suite.cfg(test).get(f"{role}_ports")
            if ports:
                return ports[node]
            return MGMD_PORT if role == "mgmd" else NDBD_PORT

        def connect_string(self, test) -> str:
            return ",".join(
                f"{suite.host(test, n)}:{self.role_port(test, n, 'mgmd')}"
                for n in test["nodes"])

        def role_args(self, test, node, role) -> list:
            d = suite.dir(test, node)
            nid = self.node_id(test, node, role)
            port = self.role_port(test, node, role)
            if role == "mgmd":
                return [f"--ndb-nodeid={nid}",
                        "--port", str(port),
                        "--configdir", f"{d}/cluster"]
            if role == "ndbd":
                return [f"--ndb-nodeid={nid}",
                        "--port", str(port),
                        f"--ndb-connectstring={self.connect_string(test)}",
                        "--datadir", f"{d}/data"]
            return ["--port", str(port),
                    f"--ndb-nodeid={nid}",
                    f"--ndb-connectstring={self.connect_string(test)}",
                    "--datadir", f"{d}/mysql"]

        def daemon_args(self, test, node) -> list:
            return self.role_args(test, node, "mysqld")

        # ---- ordered bring-up (mysql_cluster.clj:140-199) ----

        def setup(self, test, node) -> None:
            remote = test["remote"]
            d = suite.dir(test, node)
            cu.install_archive(remote, node, self.resolve_url(test), d,
                               sudo=suite.sudo(test))
            self.start_component(test, node, "mgmd")
            self._await_ports(test, "mgmd", self.ready_timeout)
            if node in self.role_nodes(test, "ndbd"):
                self.start_component(test, node, "ndbd")
            self._await_ports(test, "ndbd", self.ready_timeout)
            self.start_component(test, node, "mysqld")
            self.await_ready(test, node)

        def probe_ready(self, test, node) -> bool:
            from .mysql_common import probe_mysql_ready

            return probe_mysql_ready(suite, test, node)

    return MysqlClusterDB


from .common import ComponentKiller  # noqa: E402 — shared with tidb

COMPONENT_NEMESES = ("kill-mgmd", "kill-ndbd", "kill-mysqld")


def _extra_nemeses(db) -> dict:
    return {
        f"kill-{role}": (lambda role=role: ComponentKiller(db, role))
        for role in ROLES
    }


def _daemon_args(suite, test, node) -> list:
    # retained for factory-API compatibility; the role DB overrides
    # daemon_args with its per-role builder
    mgmt = suite.host(test, test["nodes"][0])
    return ["--port", str(suite.port(test, node)),
            f"--ndb-connectstring={mgmt}"]


suite, MysqlClusterDB, workloads, mysql_cluster_test, _opt_spec = \
    make_sql_suite("mysql-cluster", 3306, "mysqld", _daemon_args,
                   ("bank", "sets"),
                   db_cls=_make_db,
                   extra_nemeses=_extra_nemeses,
                   extra_nemesis_names=COMPONENT_NEMESES)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(mysql_cluster_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
