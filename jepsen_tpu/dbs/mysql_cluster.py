"""MySQL Cluster (NDB) test suite: bank and sets workloads over the
MySQL protocol (reference:
/root/reference/mysql-cluster/src/jepsen/mysql_cluster.clj:1-227;
clients live in mysql_common.py). mysqld nodes point at the management
node (the first node) via --ndb-connectstring.

A real NDB deployment is THREE process types (ndb_mgmd + ndbd +
mysqld, mysql_cluster.clj's bring-up); like the tidb suite, the
archive's mysqld binary is expected to wrap that bring-up (start
ndb_mgmd/ndbd when local, then exec mysqld) — the hermetic path runs
dbs/mysql_sim through the same daemon machinery."""

from __future__ import annotations

from .. import cli
from .mysql_common import make_sql_suite


def _daemon_args(suite, test, node) -> list:
    mgmt = suite.host(test, test["nodes"][0])
    return ["--port", str(suite.port(test, node)),
            f"--ndb-connectstring={mgmt}"]


suite, MysqlClusterDB, workloads, mysql_cluster_test, _opt_spec = \
    make_sql_suite("mysql-cluster", 3306, "mysqld", _daemon_args,
                   ("bank", "sets"))


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(mysql_cluster_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
