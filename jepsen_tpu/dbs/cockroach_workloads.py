"""CockroachDB workloads: register, bank, sets, monotonic, and G2,
plus the runner CLI (reference:
/root/reference/cockroachdb/src/jepsen/cockroach/{register,bank,sets,
monotonic,adya,runner}.clj).

Every client follows the same stack as the reference: reconnect-wrapped
pgwire connection, SQL inside explicit transactions, 40001 retry loops,
and the exception→op determinacy taxonomy from cockroach.py.
"""

from __future__ import annotations

import itertools
import logging
import random
import threading
import zlib

from .. import checker as checker_mod
from .. import cli, client, generator as gen, independent, models
from ..checker import Checker
from ..history import Op, ops as _ops
from ..workloads import adya as adya_wl
from ..workloads import bank as bank_wl
from . import cockroach as cr

log = logging.getLogger("jepsen_tpu.dbs.cockroach_workloads")


def _shared_flag():
    return {"lock": threading.Lock(), "created": False}


def _once(flag, fn) -> None:
    """Run fn exactly once across all clones (the reference's
    (locking tbl-created? (compare-and-set! ...)) idiom)."""
    with flag["lock"]:
        if not flag["created"]:
            fn()
            flag["created"] = True


# ---------------------------------------------------------------------------
# Register (register.clj)


class RegisterClient(client.Client):
    """Independent-key linearizable registers in a `test` table
    (register.clj:22-81): read = select; write = upsert inside a txn;
    cas = conditional UPDATE whose rowcount decides ok/fail. Reads are
    idempotent → indeterminate reads remap to :fail."""

    def __init__(self, conn=None, flag=None):
        self.conn = conn
        self.flag = flag or _shared_flag()

    def open(self, test, node):
        return RegisterClient(cr.conn_wrapper(test, node), self.flag)

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                cr.txn_retry(lambda: c.query("drop table if exists test"))
                cr.txn_retry(lambda: c.query(
                    "create table test (id int primary key, val int)"))

        _once(self.flag, create)

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value

        def body(c):
            if op.f == "read":
                vals = c.query(
                    f"select val from test where id = {k}").scalars()
                val = int(vals[0]) if vals and vals[0] is not None else None
                return op.with_(type="ok",
                                value=independent.tuple_(k, val))
            if op.f == "write":
                def w():
                    with cr.txn(c):
                        rows = c.query(
                            f"select val from test where id = {k}").rows
                        if rows:
                            c.query(f"update test set val = {v} "
                                    f"where id = {k}")
                        else:
                            c.query(f"insert into test values ({k}, {v})")
                cr.txn_retry(w)
                cr.update_keyrange(test, "test", k)
                return op.with_(type="ok")
            if op.f == "cas":
                old, new = v

                def swap():
                    with cr.txn(c):
                        return c.query(
                            f"update test set val = {new} "
                            f"where id = {k} and val = {old}").rowcount
                count = cr.txn_retry(swap)
                return op.with_(type="ok" if count else "fail")
            raise ValueError(f"unknown op {op.f!r}")

        return cr.invoke_with_taxonomy(self.conn, op, body,
                                       idempotent_fs={"read"})

    def close(self, test):
        if self.conn:
            self.conn.close()


def _r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def _w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def _cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


def register_workload(opts: dict) -> dict:
    """10 threads/key: 5 reserved for writes/cas, 5 reading; 100 ops/key
    (register.clj:83-104)."""
    per_key = opts.get("ops_per_key", 100)
    threads_per_key = opts.get("threads_per_key", 10)
    return {
        "name": "register",
        "client": RegisterClient(),
        "during": independent.concurrent_generator(
            threads_per_key,
            itertools.count(),
            lambda k: gen.limit(
                per_key,
                gen.stagger(
                    0.1,
                    gen.delay_til(
                        0.5,
                        gen.reserve(threads_per_key // 2,
                                    gen.mix([_w, _cas, _cas]), _r)),
                ),
            ),
        ),
        "model": models.CASRegister(),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "details": independent.checker(checker_mod.compose({
                "timeline": checker_mod.timeline_html(),
                "linearizable": checker_mod.linearizable(),
            })),
        }),
    }


# ---------------------------------------------------------------------------
# Bank (bank.clj)


class BankClient(client.Client):
    """Transfers between account rows inside serializable transactions
    (bank.clj:21-88). Reads snapshot every balance; transfers fail
    definitely on insufficient funds."""

    def __init__(self, n: int = 5, starting_balance: int = 10,
                 conn=None, flag=None):
        self.n = n
        self.starting_balance = starting_balance
        self.conn = conn
        self.flag = flag or _shared_flag()

    def open(self, test, node):
        return BankClient(self.n, self.starting_balance,
                          cr.conn_wrapper(test, node), self.flag)

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                cr.txn_retry(
                    lambda: c.query("drop table if exists accounts"))
                cr.txn_retry(lambda: c.query(
                    "create table accounts "
                    "(id int not null primary key, balance bigint not null)"))
                for i in range(self.n):
                    cr.txn_retry(lambda i=i: c.query(
                        f"insert into accounts (id, balance) "
                        f"values ({i}, {self.starting_balance})"))

        _once(self.flag, create)

    def invoke(self, test, op: Op) -> Op:
        def body(c):
            def run():
                with cr.txn(c):
                    if op.f == "read":
                        rows = c.query(
                            "select id, balance from accounts").rows
                        balances = {int(i): int(b) for i, b in rows}
                        return op.with_(type="ok", value=balances)
                    if op.f == "transfer":
                        frm = op.value["from"]
                        to = op.value["to"]
                        amount = op.value["amount"]
                        b1 = int(c.query(
                            f"select balance from accounts where id = {frm}"
                        ).scalars()[0]) - amount
                        b2 = int(c.query(
                            f"select balance from accounts where id = {to}"
                        ).scalars()[0]) + amount
                        if b1 < 0:
                            return op.with_(type="fail",
                                            error=("negative", frm, b1))
                        if b2 < 0:
                            return op.with_(type="fail",
                                            error=("negative", to, b2))
                        c.query(f"update accounts set balance = {b1} "
                                f"where id = {frm}")
                        c.query(f"update accounts set balance = {b2} "
                                f"where id = {to}")
                        cr.update_keyrange(test, "accounts", frm)
                        cr.update_keyrange(test, "accounts", to)
                        return op.with_(type="ok")
                    raise ValueError(f"unknown op {op.f!r}")

            return cr.txn_retry(run)

        return cr.invoke_with_taxonomy(self.conn, op, body,
                                       idempotent_fs={"read"})

    def close(self, test):
        if self.conn:
            self.conn.close()


def bank_workload(opts: dict) -> dict:
    """Random transfers vs whole-table reads; the snapshot-isolation
    total checker + plotter from the framework bank workload
    (bank.clj:90-178)."""
    n = opts.get("accounts", 5)
    starting = opts.get("starting_balance", 10)
    return {
        "name": "bank",
        "client": BankClient(n, starting),
        "during": gen.stagger(opts.get("stagger", 0.1),
                              bank_wl.generator()),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "timeline": checker_mod.timeline_html(),
            "bank": bank_wl.checker(),
            "plot": bank_wl.plotter(),
        }),
        # test-map options the bank generator/checker read
        "test_opts": {"accounts": list(range(n)),
                      "total_amount": n * starting,
                      "max_transfer": 5},
    }


# ---------------------------------------------------------------------------
# Sets (sets.clj)


class SetsClient(client.Client):
    """Unique-int inserts with a final whole-table read
    (sets.clj:66-107)."""

    def __init__(self, conn=None, flag=None):
        self.conn = conn
        self.flag = flag or _shared_flag()

    def open(self, test, node):
        return SetsClient(cr.conn_wrapper(test, node), self.flag)

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                cr.txn_retry(lambda: c.query("drop table if exists sets"))
                cr.txn_retry(lambda: c.query(
                    "create table sets (val int primary key)"))

        _once(self.flag, create)

    def invoke(self, test, op: Op) -> Op:
        def body(c):
            if op.f == "add":
                cr.txn_retry(lambda: c.query(
                    f"insert into sets values ({op.value})"))
                cr.update_keyrange(test, "sets", op.value)
                return op.with_(type="ok")
            if op.f == "read":
                vals = sorted(
                    int(v) for v in
                    c.query("select val from sets").scalars())
                return op.with_(type="ok", value=vals)
            raise ValueError(f"unknown op {op.f!r}")

        return cr.invoke_with_taxonomy(self.conn, op, body,
                                       idempotent_fs={"read"})

    def close(self, test):
        if self.conn:
            self.conn.close()


def sets_workload(opts: dict) -> dict:
    return {
        "name": "sets",
        "client": SetsClient(),
        "during": gen.stagger(
            opts.get("stagger", 0.05),
            gen.seq({"type": "invoke", "f": "add", "value": x}
                    for x in itertools.count()),
        ),
        "final_client": gen.once({"type": "invoke", "f": "read"}),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "set": checker_mod.set_checker(),
        }),
    }


# ---------------------------------------------------------------------------
# Monotonic (monotonic.clj)


class MonotonicChecker(Checker):
    """The final read's rows, ordered by system timestamp, must carry
    strictly increasing values (monotonic.clj's analysis: a txn that
    read max=n and wrote n+1 at a later timestamp must see every earlier
    write). Reports reorders (value decreases along the sts order) and
    duplicates."""

    def check(self, test, history, opts=None) -> dict:
        final = None
        for o in _ops(history):
            if o.is_ok and o.f == "read":
                final = o.value
        if final is None:
            return {"valid": "unknown", "error": "Table was never read"}
        rows = sorted(final, key=lambda r: (int(str(r[1]).split(".")[0]),
                                            str(r[1])))
        vals = [r[0] for r in rows]
        reorders = [
            (vals[i], vals[i + 1])
            for i in range(len(vals) - 1)
            if vals[i + 1] <= vals[i]
        ]
        dup_counts: dict = {}
        for v in vals:
            dup_counts[v] = dup_counts.get(v, 0) + 1
        dups = {v: c for v, c in dup_counts.items() if c > 1}
        return {
            "valid": not reorders and not dups,
            "row_count": len(vals),
            "reorders": reorders[:10],
            "duplicates": dups,
        }


class MonotonicClient(client.Client):
    """Each :add reads the current max, asks for the cluster's logical
    timestamp, and inserts max+1 in one serializable txn
    (monotonic.clj:84-130); the final :read returns [val, sts, node,
    process] rows."""

    def __init__(self, conn=None, flag=None, nodenum: int = -1):
        self.conn = conn
        self.flag = flag or _shared_flag()
        self.nodenum = nodenum

    def open(self, test, node):
        nodenum = list(test["nodes"]).index(node)
        return MonotonicClient(cr.conn_wrapper(test, node), self.flag,
                               nodenum)

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                cr.txn_retry(lambda: c.query("drop table if exists mono"))
                # val as the primary key (monotonic.clj:32-48's
                # val-as-pkey? mode) so split-at-val hits real ranges
                cr.txn_retry(lambda: c.query(
                    "create table mono (val int primary key, sts string, "
                    "node int, process int, tb int)"))

        _once(self.flag, create)

    def invoke(self, test, op: Op) -> Op:
        def body(c):
            if op.f == "add":
                def run():
                    with cr.txn(c):
                        cur = c.query(
                            "select max(val) as m from mono").scalars()[0]
                        cur = int(cur) if cur is not None else 0
                        sts = c.query(
                            "select cluster_logical_timestamp()"
                        ).scalars()[0]
                        c.query(
                            "insert into mono (val, sts, node, process, tb)"
                            f" values ({cur + 1}, '{sts}', {self.nodenum},"
                            f" {op.process}, 0)")
                        cr.update_keyrange(test, "mono", cur + 1)
                        return cur + 1

                val = cr.txn_retry(run)
                return op.with_(type="ok", value=val)
            if op.f == "read":
                rows = c.query(
                    "select val, sts, node, process from mono").rows
                out = [(int(v), s, int(n), int(p))
                       for v, s, n, p in rows]
                return op.with_(type="ok", value=out)
            raise ValueError(f"unknown op {op.f!r}")

        return cr.invoke_with_taxonomy(self.conn, op, body,
                                       idempotent_fs={"read"})

    def close(self, test):
        if self.conn:
            self.conn.close()


def monotonic_workload(opts: dict) -> dict:
    return {
        "name": "monotonic",
        "client": MonotonicClient(),
        "during": gen.stagger(opts.get("stagger", 0.05),
                              {"type": "invoke", "f": "add"}),
        "final_client": gen.once({"type": "invoke", "f": "read"}),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "monotonic": MonotonicChecker(),
        }),
    }


# ---------------------------------------------------------------------------
# G2 / Adya (adya.clj)


class G2Client(client.Client):
    """Anti-dependency-cycle txns over two tables (adya.clj:25-88):
    each insert predicate-reads both tables for its key (value % 3 = 0)
    and inserts only if both came back empty; under serializability at
    most one insert per key may commit."""

    def __init__(self, conn=None, flag=None):
        self.conn = conn
        self.flag = flag or _shared_flag()

    def open(self, test, node):
        return G2Client(cr.conn_wrapper(test, node), self.flag)

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                for t in ("a", "b"):
                    cr.txn_retry(
                        lambda t=t: c.query(f"drop table if exists {t}"))
                    cr.txn_retry(lambda t=t: c.query(
                        f"create table {t} (id int primary key, key int, "
                        "value int)"))

        _once(self.flag, create)

    def invoke(self, test, op: Op) -> Op:
        k, ids = op.value

        def body(c):
            if op.f == "insert":
                a_id, b_id = ids

                def run():
                    with cr.txn(c):
                        first, second = (("a", "b")
                                         if random.random() < 0.5
                                         else ("b", "a"))
                        rows = []
                        for t in (first, second):
                            rows += c.query(
                                f"select id from {t} where key = {k} "
                                "and value % 3 = 0").rows
                        if rows:
                            return op.with_(type="fail", error="too-late")
                        table = "a" if a_id is not None else "b"
                        row_id = a_id if a_id is not None else b_id
                        c.query(
                            f"insert into {table} (id, key, value) "
                            f"values ({row_id}, {k}, 30)")
                        cr.update_keyrange(test, table, row_id)
                        return op.with_(type="ok")

                return cr.txn_retry(run, attempts=5)
            if op.f == "read":
                found = []
                for t in ("a", "b"):
                    found += c.query(
                        f"select id from {t} where key = {k} "
                        "and value % 3 = 0").scalars()
                return op.with_(
                    type="ok",
                    value=independent.tuple_(k, [int(i) for i in found]))
            raise ValueError(f"unknown op {op.f!r}")

        return cr.invoke_with_taxonomy(self.conn, op, body,
                                       idempotent_fs={"read"})

    def close(self, test):
        if self.conn:
            self.conn.close()


def g2_workload(opts: dict) -> dict:
    return {
        "name": "g2",
        "client": G2Client(),
        "during": adya_wl.g2_gen(),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "g2": adya_wl.g2_checker(),
        }),
    }


# ---------------------------------------------------------------------------
# Sequential (sequential.clj): per-key subkeys written in order into
# hash-distributed tables; reads traverse in REVERSE order, so seeing a
# later subkey while an earlier one is missing is a sequential-
# consistency violation.


def _stable_hash(x) -> int:
    return zlib.crc32(str(x).encode())


SEQ_TABLE_PREFIX = "seq_"


class SequentialClient(client.Client):
    """sequential.clj:30-90: write inserts k_0..k_{n-1} in order, each
    into table seq_{hash(subkey) % table_count}; read selects the
    subkeys in reverse order and reports which were present."""

    def __init__(self, table_count: int = 5, key_count: int = 5,
                 conn=None, flag=None):
        self.table_count = table_count
        self.key_count = key_count
        self.conn = conn
        self.flag = flag or _shared_flag()

    def _table(self, subkey) -> str:
        return (SEQ_TABLE_PREFIX
                + str(_stable_hash(subkey) % self.table_count))

    def _subkeys(self, k) -> list:
        return [f"{k}_{i}" for i in range(self.key_count)]

    def open(self, test, node):
        return SequentialClient(self.table_count, self.key_count,
                                cr.conn_wrapper(test, node), self.flag)

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                for i in range(self.table_count):
                    t = f"{SEQ_TABLE_PREFIX}{i}"
                    cr.txn_retry(
                        lambda t=t: c.query(f"drop table if exists {t}"))
                    cr.txn_retry(lambda t=t: c.query(
                        f"create table {t} (key varchar primary key)"))

        _once(self.flag, create)

    def invoke(self, test, op: Op) -> Op:
        k = op.value

        def body(c):
            if op.f == "write":
                for sub in self._subkeys(k):
                    cr.txn_retry(lambda sub=sub: c.query(
                        f"insert into {self._table(sub)} (key) "
                        f"values ('{sub}')"))
                    cr.update_keyrange(test, self._table(sub), sub)
                return op.with_(type="ok")
            if op.f == "read":
                found = []
                for sub in reversed(self._subkeys(k)):
                    rows = cr.txn_retry(lambda sub=sub: c.query(
                        f"select key from {self._table(sub)} "
                        f"where key = '{sub}'").rows)
                    found.append(sub if rows else None)
                return op.with_(type="ok", value=(k, found))
            raise ValueError(f"unknown op {op.f!r}")

        return cr.invoke_with_taxonomy(self.conn, op, body,
                                       idempotent_fs={"read"})

    def close(self, test):
        if self.conn:
            self.conn.close()


class SequentialChecker(Checker):
    """In a read's reverse traversal (latest-written subkey first),
    once any subkey is seen every LATER-traversed (earlier-written)
    subkey must be present — a gap means writes became visible out of
    order (sequential.clj's analysis)."""

    def check(self, test, history, opts=None) -> dict:
        bad = []
        for o in _ops(history):
            if not (o.is_ok and o.f == "read"):
                continue
            k, found = o.value
            seen = False
            for sub in found:
                if sub is not None:
                    seen = True
                elif seen:
                    bad.append({"key": k, "read": found,
                                "op": o.to_dict()})
                    break
        return {"valid": not bad, "bad_reads": bad[:10]}


def sequential_workload(opts: dict) -> dict:
    keys = itertools.count()
    lock = threading.Lock()
    written: list = []

    def w(test, process):
        with lock:
            k = next(keys)
            written.append(k)
        return {"type": "invoke", "f": "write", "value": k}

    def r(test, process):
        with lock:
            k = random.choice(written) if written else 0
        return {"type": "invoke", "f": "read", "value": k}

    return {
        "name": "sequential",
        "client": SequentialClient(opts.get("tables", 5),
                                   opts.get("key_count", 5)),
        "during": gen.stagger(opts.get("stagger", 0.05),
                              gen.mix([w, r, r])),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "sequential": SequentialChecker(),
        }),
    }


# ---------------------------------------------------------------------------
# Comments (comments.clj): the stale-comment anomaly — if write w1
# completed before write w2 began, a read that sees w2's id must see
# w1's id.


COMMENT_TABLE_PREFIX = "comment_"


class CommentsClient(client.Client):
    """comments.clj:36-80: writes insert (id, key) into
    comment_{hash(id) % table_count}; reads union all tables' ids for
    the key inside one transaction."""

    def __init__(self, table_count: int = 5, conn=None, flag=None):
        self.table_count = table_count
        self.conn = conn
        self.flag = flag or _shared_flag()

    def _table(self, comment_id) -> str:
        return (COMMENT_TABLE_PREFIX
                + str(_stable_hash(comment_id) % self.table_count))

    def open(self, test, node):
        return CommentsClient(self.table_count,
                              cr.conn_wrapper(test, node), self.flag)

    def setup(self, test):
        def create():
            with self.conn.with_conn() as c:
                for i in range(self.table_count):
                    t = f"{COMMENT_TABLE_PREFIX}{i}"
                    cr.txn_retry(
                        lambda t=t: c.query(f"drop table if exists {t}"))
                    cr.txn_retry(lambda t=t: c.query(
                        f"create table {t} (id int primary key, "
                        "key int)"))

        _once(self.flag, create)

    def invoke(self, test, op: Op) -> Op:
        k, comment_id = op.value

        def body(c):
            if op.f == "write":
                cr.txn_retry(lambda: c.query(
                    f"insert into {self._table(comment_id)} (id, key) "
                    f"values ({comment_id}, {k})"))
                cr.update_keyrange(test, self._table(comment_id),
                                   comment_id)
                return op.with_(type="ok")
            if op.f == "read":
                def run():
                    with cr.txn(c):
                        ids = []
                        for i in range(self.table_count):
                            ids += c.query(
                                f"select id from {COMMENT_TABLE_PREFIX}"
                                f"{i} where key = {k}").scalars()
                        return sorted(int(x) for x in ids)

                ids = cr.txn_retry(run)
                return op.with_(type="ok", value=(k, ids))
            raise ValueError(f"unknown op {op.f!r}")

        return cr.invoke_with_taxonomy(self.conn, op, body,
                                       idempotent_fs={"read"})

    def close(self, test):
        if self.conn:
            self.conn.close()


class CommentsChecker(Checker):
    """For writes w1, w2 on the same key where w1's :ok precedes w2's
    :invoke in real time, any read that includes w2's id must include
    w1's id (comments.clj's analysis of the lost-comment anomaly)."""

    def check(self, test, history, opts=None) -> dict:
        ops = _ops(history)
        # per-key write windows: id -> (invoke_index, ok_index)
        invoked: dict = {}
        windows: dict = {}
        for i, o in enumerate(ops):
            if o.f != "write":
                continue
            key = o.value
            if o.is_invoke:
                invoked[(o.process, key)] = i
            elif o.is_ok:
                start = invoked.get((o.process, key))
                if start is not None:
                    k, comment_id = key
                    windows.setdefault(k, []).append(
                        (comment_id, start, i))
        bad = []
        for i, o in enumerate(ops):
            if not (o.is_ok and o.f == "read"):
                continue
            k, ids = o.value
            seen = set(ids)
            for id2, inv2, ok2 in windows.get(k, []):
                if id2 not in seen:
                    continue
                for id1, inv1, ok1 in windows.get(k, []):
                    if id1 == id2 or id1 in seen:
                        continue
                    # w1 finished before w2 began, and before this read
                    if ok1 < inv2 and ok1 < i:
                        bad.append({"key": k, "saw": id2,
                                    "missing": id1,
                                    "op": o.to_dict()})
                        break
        return {"valid": not bad, "anomalies": bad[:10]}


def comments_workload(opts: dict) -> dict:
    ids = itertools.count()
    lock = threading.Lock()
    n_keys = opts.get("keys", 3)

    def w(test, process):
        with lock:
            comment_id = next(ids)
        return {"type": "invoke", "f": "write",
                "value": (random.randrange(n_keys), comment_id)}

    def r(test, process):
        return {"type": "invoke", "f": "read",
                "value": (random.randrange(n_keys), None)}

    return {
        "name": "comments",
        "client": CommentsClient(opts.get("tables", 5)),
        "during": gen.stagger(opts.get("stagger", 0.05),
                              gen.mix([w, r])),
        "checker": checker_mod.compose({
            "perf": checker_mod.perf_checker(),
            "comments": CommentsChecker(),
        }),
    }


# ---------------------------------------------------------------------------
# Runner (runner.clj)


def workloads() -> dict:
    return {
        "register": register_workload,
        "bank": bank_workload,
        "sets": sets_workload,
        "monotonic": monotonic_workload,
        "sequential": sequential_workload,
        "comments": comments_workload,
        "g2": g2_workload,
    }


def cockroach_test(opts: dict) -> dict:
    wl = workloads()[opts["workload"]](opts)
    test = cr.basic_test(opts, wl)
    test.update(wl.get("test_opts") or {})
    return test


def _opt_spec(p) -> None:
    p.add_argument("--workload", required=True,
                   choices=sorted(workloads().keys()),
                   help="Test workload to run, e.g. register.")
    nem_names = sorted(cr.nemeses().keys())
    p.add_argument("--nemesis", default="none", choices=nem_names,
                   help="Primary nemesis (runner.clj:21-41).")
    p.add_argument("--nemesis2", default=None, choices=nem_names,
                   help="Secondary nemesis to compose with the first.")
    p.add_argument("--tarball", default=None,
                   help="CockroachDB binary tarball url (or the crdb_sim "
                        "archive for hermetic runs).")
    p.add_argument("--quiesce", type=float, default=30,
                   help="Seconds to wait before final-read phases.")


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(cockroach_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
