"""Shared plumbing for per-DB suites: the per-suite config accessors
(addr_fn/ports/dir/sudo overrides under one test-map key) and the
archive-install + daemon DB lifecycle that most suites share.

Every suite keeps its own protocol client, workloads, and readiness
probe — this factors only the mechanical parts so a lifecycle fix lands
once instead of once per suite."""

from __future__ import annotations

import logging
import random
import threading
import time

from .. import db, nemesis
from .. import generator as gen
from ..control import util as cu

log = logging.getLogger("jepsen_tpu.dbs.common")


class SuiteCfg:
    """Accessors for a suite's config sub-map (test[name]): addressing,
    ports, install dir, sudo — the knobs that differ between a real
    cluster and a LocalRemote sandbox."""

    def __init__(self, name: str, default_port: int, default_dir: str):
        self.name = name
        self.default_port = default_port
        self.default_dir = default_dir

    def cfg(self, test) -> dict:
        return test.get(self.name) or {}

    def host(self, test, node) -> str:
        fn = self.cfg(test).get("addr_fn")
        return fn(node) if fn else str(node)

    def port(self, test, node) -> int:
        ports = self.cfg(test).get("ports")
        return ports[node] if ports else self.default_port

    def dir(self, test, node) -> str:
        d = self.cfg(test).get("dir", self.default_dir)
        return d(node) if callable(d) else d

    def sudo(self, test):
        return self.cfg(test).get("sudo", True)


class ArchiveDB(db.DB, db.Kill, db.Pause, db.LogFiles):
    """The common suite DB shape: install an archive, start one daemon,
    poll until ready, stop + wipe on teardown. Subclasses provide
    `binary`, `daemon_args(test, node)`, and `probe_ready(test, node)
    -> bool`; anything extra (cluster joins, bootstrap flags) hooks in
    via `post_start(test, node)`.

    Implements db.Kill (SIGKILL via pidfile + the shared start()) and
    db.Pause (SIGSTOP/SIGCONT), so every archive suite — mongodb's
    mongo_sim-backed MongoDB included — can host the kill/pause fault
    families from nemesis.combined."""

    binary = "server"
    log_name = "server.log"
    pid_name = "server.pid"

    def __init__(self, suite: SuiteCfg, archive_url: str | None = None,
                 ready_timeout: float = 30.0):
        self.suite = suite
        self.archive_url = archive_url
        self.ready_timeout = ready_timeout

    def resolve_url(self, test) -> str:
        url = self.archive_url or self.suite.cfg(test).get("archive_url")
        if not url:
            raise db.SetupFailed(
                f"{self.suite.name} archive_url required (release "
                "archive, or the in-repo sim archive for hermetic runs)")
        return url

    def daemon_args(self, test, node) -> list:
        return []

    def start(self, test, node) -> None:
        """Start (or restart) the daemon — the single invocation both
        setup and kill/restart nemeses use, so they can't drift."""
        d = self.suite.dir(test, node)
        cu.start_daemon(
            test["remote"], node, f"{d}/{self.binary}",
            *self.daemon_args(test, node),
            logfile=f"{d}/{self.log_name}",
            pidfile=f"{d}/{self.pid_name}",
            chdir=d,
        )

    def setup(self, test, node) -> None:
        self.install(test, node)
        self.start_and_await(test, node)

    def install(self, test, node) -> None:
        """Fetch + unpack only — split from start_and_await so
        interposers (the faultfs FUSE layer) can mount over the data
        dir between the install's tree wipe and the daemon opening
        its first file (fsfault.FaultFsDB)."""
        remote = test["remote"]
        d = self.suite.dir(test, node)
        cu.install_archive(remote, node, self.resolve_url(test), d,
                           sudo=self.suite.sudo(test))

    def start_and_await(self, test, node) -> None:
        self.start(test, node)
        self.await_ready(test, node)
        self.post_start(test, node)

    def probe_ready(self, test, node) -> bool:
        raise NotImplementedError

    def await_ready(self, test, node) -> None:
        down = poll_until_ready(self, test, [node], self.ready_timeout)
        if down:
            raise db.SetupFailed(
                f"{self.suite.name} on {node} never became ready")

    def post_start(self, test, node) -> None:
        pass

    def teardown(self, test, node) -> None:
        remote = test["remote"]
        d = self.suite.dir(test, node)
        log.info("%s tearing down %s", node, self.suite.name)
        cu.stop_daemon(remote, node, f"{d}/{self.pid_name}")
        remote.exec(node, ["rm", "-rf", d], sudo=self.suite.sudo(test),
                    check=False)

    def log_files(self, test, node) -> list:
        return [f"{self.suite.dir(test, node)}/{self.log_name}"]

    # -- db.Kill / db.Pause / db.Process ------------------------------------

    def _pidfile(self, test, node) -> str:
        return f"{self.suite.dir(test, node)}/{self.pid_name}"

    def kill(self, test, node) -> None:
        """Crash-like stop: SIGKILL via pidfile (db.Kill). start() above
        is the matching revive — the same invocation setup uses."""
        cu.stop_daemon(test["remote"], node, self._pidfile(test, node))

    def _signal(self, test, node, sig: str) -> None:
        r = test["remote"].exec(node, ["cat", self._pidfile(test, node)],
                                check=False)
        pid = r.out.strip()
        if pid:
            test["remote"].exec(node, ["kill", f"-{sig}", pid],
                                check=False)

    def pause(self, test, node) -> None:
        self._signal(test, node, "STOP")

    def resume(self, test, node) -> None:
        self._signal(test, node, "CONT")

    def alive(self, test, node):
        return cu.daemon_running(test["remote"], node,
                                 self._pidfile(test, node))


def shared_flag() -> dict:
    """A once-guard shared across a client's clones (the reference's
    (locking tbl-created? (compare-and-set! ...)) idiom)."""
    import threading

    return {"lock": threading.Lock(), "created": False}


def once(flag: dict, fn) -> None:
    """Run fn exactly once across all holders of the flag."""
    with flag["lock"]:
        if not flag["created"]:
            fn()
            flag["created"] = True


class ArchiveKillNemesis:
    """Bounded-dead-set kill/restart for any ArchiveDB suite (the
    aerospike reference's kill-nemesis shape, nemesis.clj:17-58,
    generalized): :kill stops the daemon on the named nodes while the
    dead set stays under max_dead (a majority survives); :restart
    revives them via the DB's own start() so the invocation can't
    drift from setup. Subclasses add suite-specific maintenance ops via
    extra_op()."""

    def __init__(self, db: ArchiveDB, max_dead: int = 2):
        self.db = db
        self.max_dead = max_dead
        self.dead: set = set()
        self._lock = threading.Lock()

    def setup(self, test):
        return self

    def invoke(self, test, op):
        remote = test["remote"]
        targets = list(op.value or test["nodes"])
        results = {}
        for node in targets:
            if op.f == "kill":
                with self._lock:
                    if node in self.dead or len(self.dead) < self.max_dead:
                        self.dead.add(node)
                        allowed = True
                    else:
                        allowed = False
                if allowed:
                    d = self.db.suite.dir(test, node)
                    cu.stop_daemon(remote, node,
                                   f"{d}/{self.db.pid_name}")
                    results[node] = "killed"
                else:
                    results[node] = "still-alive"
            elif op.f == "restart":
                self.db.start(test, node)
                with self._lock:
                    self.dead.discard(node)
                results[node] = "started"
            else:
                results[node] = self.extra_op(test, node, op)
        return op.with_(type="info", value=results)

    def extra_op(self, test, node, op):
        raise ValueError(
            f"{type(self).__name__} can't handle {op.f!r}")

    def teardown(self, test):
        pass


def archive_kill_nemesis(db: ArchiveDB,
                         max_dead: int = 2) -> ArchiveKillNemesis:
    return ArchiveKillNemesis(db, max_dead)


class StartKillNemesis(ArchiveKillNemesis):
    """ArchiveKillNemesis behind the partitioner's start/stop op
    convention (the shape tidb/nemesis.clj:134-142's startkill takes):
    :start kills up to n random nodes, :stop restarts whatever died."""

    def __init__(self, db: ArchiveDB, n: int = 1):
        super().__init__(db, max_dead=n)
        self.n = n

    def invoke(self, test, op):
        import random as _random

        if op.f == "start":
            nodes = list(test["nodes"])
            targets = _random.sample(nodes, min(self.n, len(nodes)))
            return super().invoke(test, op.with_(f="kill",
                                                 value=targets)
                                  ).with_(f="start")
        if op.f == "stop":
            with self._lock:
                targets = sorted(self.dead)
            if not targets:
                # nothing died since the last stop: a bare [] would
                # fall through invoke's "falsy means all nodes" default
                # and restart every healthy daemon
                return op.with_(type="info", value={})
            out = super().invoke(test, op.with_(f="restart",
                                                value=targets))
            return out.with_(f="stop")
        return super().invoke(test, op)


def poll_until_ready(db, test, nodes, timeout: float) -> list:
    """Poll db.probe_ready on `nodes` (in parallel) until all answer or
    the timeout passes; returns the still-down nodes. ANY probe
    exception counts as not-ready — a daemon mid-startup can refuse
    connections (OSError), speak garbage HTTP (http.client errors), or
    answer protocol-level errors ("-LOADING"), and a probe must poll
    through all of them, never crash the caller."""
    from ..util import real_pmap

    def probe(node) -> bool:
        try:
            return bool(db.probe_ready(test, node))
        except NotImplementedError:
            raise  # missing override is a programming error, not "down"
        except Exception:
            return False

    deadline = time.monotonic() + timeout
    down = list(nodes)
    while True:
        up = real_pmap(probe, down)
        down = [n for n, ok in zip(down, up) if not ok]
        if not down or time.monotonic() > deadline:
            return down
        time.sleep(0.2)


class AwaitReadyGen(gen.Generator):
    """A generator gate: delay the wrapped (final) generator until every
    node answers db.probe_ready, or the timeout passes. A kill/restart
    nemesis's heal returns as soon as the daemon is spawned; a fixed
    quiesce sleep races the daemon's bind on slow machines, while
    probing is deterministic. On expiry the gate logs the still-down
    nodes and proceeds — the final ops' own failures then tell the
    story (the run must not hang forever on a node that never
    revives)."""

    def __init__(self, db, inner, timeout: float = 30.0):
        """`db` is anything with probe_ready(test, node) — ArchiveDB
        subclasses, or any DB that grows the method."""
        self.db = db
        self.name = getattr(getattr(db, "suite", None), "name",
                            type(db).__name__)
        self.inner = gen.to_gen(inner)
        self.timeout = timeout
        self._lock = threading.Lock()
        self._done = False

    def op(self, test, process):
        with self._lock:
            if not self._done:
                down = poll_until_ready(self.db, test, test["nodes"],
                                        self.timeout)
                if down:
                    log.warning(
                        "%s still not ready after %.0fs health gate: %s "
                        "— final ops may fail",
                        self.name, self.timeout, down)
                self._done = True
        return self.inner.op(test, process)


def await_ready_gen(db, inner, timeout: float = 30.0) -> AwaitReadyGen:
    return AwaitReadyGen(db, inner, timeout)


def ready_gated_final(db, inner, opts: dict) -> AwaitReadyGen:
    """The standard health-gated final phase: one place owns the
    ready_timeout option name and default for every suite."""
    return AwaitReadyGen(db, inner,
                         timeout=opts.get("ready_timeout", 30.0))


class MultiDaemonDB(ArchiveDB):
    """Shared machinery for suites whose nodes run SEVERAL daemons
    (tidb's pd/tikv/tidb triple, mysql-cluster's mgmd/ndbd/mysqld):
    per-role pid/log files, component start/stop/probe (the
    ComponentKiller surface), a readiness poll that doubles as a
    cross-node bring-up barrier, and ordered teardown. Subclasses
    declare ROLES / ROLE_TAG / ROLE_BIN / STOP_ORDER and implement
    role_args + role_port (and role_nodes when a role doesn't run
    everywhere); setup order stays suite-specific."""

    ROLES: tuple = ()
    ROLE_TAG: dict = {}
    ROLE_BIN: dict = {}
    STOP_ORDER: tuple = ()

    def role_nodes(self, test, role) -> list:
        return list(test["nodes"])

    def role_port(self, test, node, role) -> int:
        raise NotImplementedError

    def role_args(self, test, node, role) -> list:
        raise NotImplementedError

    def _role_files(self, test, node, role):
        d = self.suite.dir(test, node)
        tag = self.ROLE_TAG[role]
        return f"{d}/{tag}.log", f"{d}/{tag}.pid"

    def start_component(self, test, node, role) -> None:
        d = self.suite.dir(test, node)
        logf, pidf = self._role_files(test, node, role)
        cu.start_daemon(
            test["remote"], node, f"{d}/{self.ROLE_BIN[role]}",
            *self.role_args(test, node, role),
            logfile=logf, pidfile=pidf, chdir=d)

    def stop_component(self, test, node, role) -> None:
        _, pidf = self._role_files(test, node, role)
        cu.stop_daemon(test["remote"], node, pidf)

    def component_running(self, test, node, role):
        _, pidf = self._role_files(test, node, role)
        return cu.daemon_running(test["remote"], node, pidf)

    def _await_ports(self, test, role, timeout) -> None:
        """Poll every hosting node's `role` port from this node's
        setup — readiness-gating replaces the reference's synchronize
        + fixed sleeps (setup runs on all nodes in parallel, so this
        is an effective cross-node barrier)."""
        deadline = time.monotonic() + timeout
        pending = list(self.role_nodes(test, role))
        while pending:
            pending = [
                n for n in pending
                if not self._port_open(self.suite.host(test, n),
                                       self.role_port(test, n, role))
            ]
            if not pending:
                return
            if time.monotonic() > deadline:
                raise db.SetupFailed(
                    f"{self.suite.name} {role} never ready on {pending}")
            time.sleep(0.05)

    @staticmethod
    def _port_open(host, port) -> bool:
        import socket

        try:
            with socket.create_connection((host, port), timeout=1.0):
                return True
        except OSError:
            return False

    def teardown(self, test, node) -> None:
        remote = test["remote"]
        d = self.suite.dir(test, node)
        for role in self.STOP_ORDER:
            _, pidf = self._role_files(test, node, role)
            cu.stop_daemon(remote, node, pidf)
        remote.exec(node, ["rm", "-rf", d], sudo=self.suite.sudo(test),
                    check=False)

    def log_files(self, test, node) -> list:
        d = self.suite.dir(test, node)
        return [f"{d}/{self.ROLE_TAG[r]}.log" for r in self.ROLES]


class ComponentKiller(nemesis.Nemesis):
    """Kill one role's daemon on a random node; stop revives every
    downed instance of that role. Speaks the partitioner's start/stop
    op convention so the suites' shared nemesis generator drives it
    unchanged. For multi-daemon DBs (tidb's pd/tikv/tidb triple,
    mysql-cluster's mgmd/ndbd/mysqld roles): faults hit one component
    while the node's other daemons keep serving. The DB must expose
    start_component/stop_component(test, node, role) and may expose
    `role_nodes(test, role)` to bound which nodes host the role."""

    def __init__(self, db, role: str):
        self.db = db
        self.role = role
        self.downed: set = set()

    def _hosts(self, test) -> list:
        fn = getattr(self.db, "role_nodes", None)
        return list(fn(test, self.role)) if fn else list(test["nodes"])

    def invoke(self, test, op):
        if op.f == "start":
            candidates = [n for n in self._hosts(test)
                          if n not in self.downed]
            if not candidates:
                return op.with_(type="info", value="all-down")
            node = random.choice(candidates)
            self.db.stop_component(test, node, self.role)
            self.downed.add(node)
            return op.with_(type="info", value=[self.role, "killed", node])
        if op.f == "stop":
            revived = sorted(self.downed)
            for node in revived:
                self.db.start_component(test, node, self.role)
            self.downed.clear()
            return op.with_(type="info",
                            value=[self.role, "restarted", revived])
        raise ValueError(f"unknown nemesis op {op.f!r}")

    def teardown(self, test):
        for node in sorted(self.downed):
            try:
                self.db.start_component(test, node, self.role)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        self.downed.clear()


def standard_nemeses(db) -> dict:
    """The named-nemesis registry the per-DB runners share (the
    cockroach/tidb registries' common core, nemesis.clj:110-144):
    partitions, majorities-ring, SIGSTOP pauses, bounded kill+restart.
    Suites whose DB isn't an ArchiveDB (custom daemon management) get
    the partition entries only."""
    from .. import nemesis as nem

    out = {
        "none": lambda: nem.noop,
        "parts": nem.partition_random_halves,
        "majority-ring": nem.partition_majorities_ring,
    }
    if isinstance(db, ArchiveDB):
        out.update({
            "start-stop": lambda: nem.hammer_time(db.binary),
            "start-kill": lambda: StartKillNemesis(db, 1),
            "start-kill-2": lambda: StartKillNemesis(db, 2),
        })
    return out


NEMESIS_NAMES = ("none", "parts", "majority-ring", "start-stop",
                 "start-kill", "start-kill-2")
PARTITION_NEMESIS_NAMES = ("none", "parts", "majority-ring")


def pick_nemesis(db, opts: dict, default: str = "parts", extra=None):
    """Resolve the suite's nemesis from the shared --nemesis option
    (the cockroach/tidb CLI surface, generalized). `extra` merges
    suite-specific entries (e.g. component killers for multi-daemon
    DBs) over the standard registry."""
    name = opts.get("nemesis") or default
    registry = standard_nemeses(db)
    if extra:
        registry.update(extra)
    if name not in registry:
        raise ValueError(
            f"nemesis {name!r} not available for this suite "
            f"(have: {sorted(registry)})")
    return registry[name]()


FSFAULT_NEMESIS_NAMES = ("fs-break", "fs-break-1pct")


def fsfault_wiring(db_, opts: dict, data_dir_fn):
    """(db, nemesis) for the --nemesis fs-break modes, else
    (db, None). The DB wraps in FaultFsDB — the mount must happen
    between install and daemon start — and the nemesis only flips the
    shared fault switch; ONE opt_dir (opts['fsfault_opt_dir']) feeds
    both, since diverging control-file paths would make every
    break/clear a silent no-op. Suites add FSFAULT_NEMESIS_NAMES to
    their nemesis_opt choices and consume 'fsfault_opt_dir' in their
    merge-opts-last step."""
    name = opts.get("nemesis") or ""
    if not name.startswith("fs-break"):
        return db_, None
    from ..nemesis import fsfault

    fs_opt = opts.get("fsfault_opt_dir", fsfault.OPT_DIR)
    wrapped = fsfault.FaultFsDB(db_, data_dir_fn, opt_dir=fs_opt)
    nem = fsfault.fs_fault_nemesis(
        backend="fuse", manage_mounts=False, opt_dir=fs_opt,
        default_mode=("break-one-percent" if name == "fs-break-1pct"
                      else "break-all"))
    return wrapped, nem


def nemesis_opt(p, names=NEMESIS_NAMES, default: str = "parts") -> None:
    """argparse surface for --nemesis. The value is either a registry
    name from `names` (validated at test-build time by pick_nemesis) or
    a comma list of fault families ("kill,partition") resolved into a
    composed nemesis package by fault_package_wiring — open-ended, so
    no argparse `choices` gate. The argparse default IS `default`, so
    the help text and the resolved nemesis can't drift (pick_nemesis's
    own default only covers programmatic callers that skip the CLI)."""
    from ..nemesis.combined import FAULT_FAMILIES

    p.add_argument(
        "--nemesis", default=default, metavar="SPEC",
        help=f"named fault mode (one of: {', '.join(names)}), or a "
        f"comma list of fault families ({', '.join(FAULT_FAMILIES)}) "
        f"for a composed package (default: {default})")


def fault_package_wiring(test: dict, db_, opts: dict,
                         stability_generator=None,
                         corrupt_paths=None,
                         set_time_fn=None) -> bool:
    """When --nemesis names fault families ("kill,partition"), build
    the composed NemesisPackage and install it into the test map —
    nemesis, schedules, heal phase, stability window, recovery checker
    (nemesis.combined.wire_package). The test map's CURRENT generator
    must be the client-side generator; wiring wraps it. Returns True
    when wired, False when --nemesis is a plain registry name for
    pick_nemesis.

    --nemesis-schedule FILE takes precedence over --nemesis: the file's
    schedule document (combined.schedule_to_json / a fuzz-discovered
    schedule) is replayed VERBATIM through the real nemeses — same
    wiring, no rng."""
    from ..nemesis import combined

    sched_file = opts.get("nemesis_schedule")
    if sched_file:
        pkg = combined.load_schedule_file(
            sched_file, db=db_, corrupt_paths=corrupt_paths,
            set_time_fn=set_time_fn)
        combined.wire_package(test, pkg, {
            "time_limit": opts.get("time_limit", 60),
            "stability_period": opts.get("stability_period", 10.0),
            "stability_generator": stability_generator,
            "recovery_min_ok": opts.get("recovery_min_ok", 1),
        })
        return True
    fams = combined.parse_fault_spec(opts.get("nemesis"))
    if fams is None:
        return False
    pkg = combined.nemesis_package(
        faults=fams,
        db=db_,
        seed=opts.get("seed"),
        interval=opts.get("nemesis_interval", 10.0),
        fault_ops=opts.get("fault_ops"),
        corrupt_paths=corrupt_paths,
        set_time_fn=set_time_fn,
        targets=opts.get("targets"),
    )
    combined.wire_package(test, pkg, {
        "time_limit": opts.get("time_limit", 60),
        "stability_period": opts.get("stability_period", 10.0),
        "stability_generator": stability_generator,
        "recovery_min_ok": opts.get("recovery_min_ok", 1),
    })
    return True


def resp_ping_ready(suite: SuiteCfg, test, node,
                    timeout: float = 2.0) -> bool:
    """Readiness probe for RESP-protocol suites (disque, raftis)."""
    from . import redis_proto

    conn = redis_proto.RespConn(
        suite.host(test, node), suite.port(test, node), timeout=timeout)
    try:
        return conn.call("PING") == "PONG"
    finally:
        conn.close()
