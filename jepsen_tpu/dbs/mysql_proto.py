"""Minimal MySQL client/server wire protocol — the transport for the
galera, percona, mysql-cluster, and tidb suites (all MySQL-protocol
systems; the reference drives them through clojure.java.jdbc + the
MariaDB/MySQL JDBC drivers, e.g. galera.clj:86-93).

Implemented subset: protocol-41 handshake with mysql_native_password
auth, COM_QUERY with text resultsets, OK/ERR packets (including the
1213 deadlock code whose message — "Deadlock found when trying to get
lock; try restarting transaction" — is the exact string the suites'
txn-abort taxonomy matches on), COM_QUIT.

Packet framing: 3-byte little-endian length + 1-byte sequence id.
"""

from __future__ import annotations

import hashlib
import socket
import struct

CLIENT_LONG_PASSWORD = 0x00000001
CLIENT_FOUND_ROWS = 0x00000002
CLIENT_PROTOCOL_41 = 0x00000200
CLIENT_SECURE_CONNECTION = 0x00008000
CLIENT_PLUGIN_AUTH = 0x00080000
CLIENT_CONNECT_WITH_DB = 0x00000008
CLIENT_TRANSACTIONS = 0x00002000

DEADLOCK_MSG = ("Deadlock found when trying to get lock; "
                "try restarting transaction")

ER_DUP_ENTRY = 1062
ER_LOCK_DEADLOCK = 1213
ER_PARSE_ERROR = 1064
ER_NO_SUCH_TABLE = 1146


class MySqlError(Exception):
    def __init__(self, code: int, message: str, sqlstate: str = "HY000"):
        super().__init__(f"({code}) {message}")
        self.code = code
        self.message = message
        self.sqlstate = sqlstate

    @property
    def deadlock(self) -> bool:
        return self.code == ER_LOCK_DEADLOCK


class MySqlProtocolError(Exception):
    pass


def scramble_native(password: str, nonce: bytes) -> bytes:
    """mysql_native_password: SHA1(pw) XOR SHA1(nonce + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    p1 = hashlib.sha1(password.encode()).digest()
    p2 = hashlib.sha1(p1).digest()
    mix = hashlib.sha1(nonce + p2).digest()
    return bytes(a ^ b for a, b in zip(p1, mix))


def lenenc_int(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def lenenc_str(b: bytes) -> bytes:
    return lenenc_int(len(b)) + b


def read_lenenc_int(buf: bytes, pos: int) -> tuple:
    first = buf[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return (struct.unpack_from("<I", buf[pos + 1:pos + 4] + b"\x00")[0],
                pos + 4)
    if first == 0xFE:
        return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9
    raise MySqlProtocolError(f"bad lenenc int 0x{first:02x}")


class PacketIO:
    """Framed packet reader/writer over a socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.seq = 0

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("mysql connection closed")
            buf += chunk
        return buf

    def read_packet(self) -> bytes:
        header = self._read_exact(4)
        length = header[0] | (header[1] << 8) | (header[2] << 16)
        self.seq = (header[3] + 1) & 0xFF
        return self._read_exact(length)

    def write_packet(self, payload: bytes) -> None:
        header = struct.pack("<I", len(payload))[:3] + bytes([self.seq])
        self.seq = (self.seq + 1) & 0xFF
        self.sock.sendall(header + payload)

    def reset_seq(self) -> None:
        self.seq = 0


def parse_err(payload: bytes) -> MySqlError:
    (code,) = struct.unpack_from("<H", payload, 1)
    pos = 3
    sqlstate = "HY000"
    if pos < len(payload) and payload[pos:pos + 1] == b"#":
        sqlstate = payload[pos + 1:pos + 6].decode()
        pos += 6
    return MySqlError(code, payload[pos:].decode(errors="replace"),
                      sqlstate)


class Result:
    def __init__(self, columns: list, rows: list, affected: int = 0):
        self.columns = columns
        self.rows = rows
        self.affected = affected

    @property
    def rowcount(self) -> int:
        return self.affected

    def scalars(self) -> list:
        return [r[0] for r in self.rows]


class MySqlConn:
    """One MySQL-protocol connection. Not thread-safe."""

    def __init__(self, host: str, port: int, user: str = "jepsen",
                 password: str = "", database: str = "",
                 timeout: float = 10.0, connect_timeout: float = 30.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.settimeout(timeout)
        self.io = PacketIO(self.sock)
        self._handshake(user, password, database)

    def _handshake(self, user: str, password: str, database: str) -> None:
        payload = self.io.read_packet()
        if payload[0] == 0xFF:
            raise parse_err(payload)
        if payload[0] != 10:
            raise MySqlProtocolError(f"unsupported protocol {payload[0]}")
        pos = 1
        end = payload.index(b"\x00", pos)  # server version
        pos = end + 1 + 4                  # thread id
        nonce1 = payload[pos:pos + 8]
        pos += 8 + 1                       # filler
        pos += 2 + 1 + 2 + 2               # caps low, charset, status, caps hi
        pos += 1 + 10                      # auth data len + reserved
        nonce2 = payload[pos:pos + 12]     # 13 bytes incl NUL; use 12
        nonce = nonce1 + nonce2

        # CLIENT_FOUND_ROWS: UPDATE reports MATCHED rows, so a CAS
        # write of an identical value still counts (the JDBC drivers
        # the reference suites ride set this too)
        caps = (CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS
                | CLIENT_PROTOCOL_41 | CLIENT_SECURE_CONNECTION
                | CLIENT_PLUGIN_AUTH | CLIENT_TRANSACTIONS)
        if database:
            caps |= CLIENT_CONNECT_WITH_DB
        auth = scramble_native(password, nonce)
        resp = struct.pack("<IIB23x", caps, 1 << 24, 33)
        resp += user.encode() + b"\x00"
        resp += bytes([len(auth)]) + auth
        if database:
            resp += database.encode() + b"\x00"
        resp += b"mysql_native_password\x00"
        self.io.write_packet(resp)

        payload = self.io.read_packet()
        if payload[0] == 0xFF:
            raise parse_err(payload)
        if payload[0] == 0xFE:
            # AuthSwitchRequest (e.g. a server defaulting to
            # caching_sha2_password): switch to the requested plugin
            # when it's mysql_native_password, else give up cleanly
            end = payload.index(b"\x00", 1)
            plugin = payload[1:end].decode()
            if plugin != "mysql_native_password":
                raise MySqlProtocolError(
                    f"unsupported auth plugin {plugin!r}")
            new_nonce = payload[end + 1:].rstrip(b"\x00")
            self.io.write_packet(scramble_native(password, new_nonce))
            payload = self.io.read_packet()
            if payload[0] == 0xFF:
                raise parse_err(payload)
        if payload[0] not in (0x00,):
            raise MySqlProtocolError(
                f"unexpected auth reply 0x{payload[0]:02x}")

    def query(self, sql: str) -> Result:
        self.io.reset_seq()
        self.io.write_packet(b"\x03" + sql.encode())
        payload = self.io.read_packet()
        if payload[0] == 0xFF:
            raise parse_err(payload)
        if payload[0] == 0x00:  # OK packet
            affected, pos = read_lenenc_int(payload, 1)
            return Result([], [], affected)
        # resultset
        n_cols, _ = read_lenenc_int(payload, 0)
        columns = []
        for _ in range(n_cols):
            col = self.io.read_packet()
            columns.append(self._parse_column(col))
        eof = self.io.read_packet()
        if eof[0] != 0xFE:
            raise MySqlProtocolError("expected EOF after columns")
        rows = []
        while True:
            payload = self.io.read_packet()
            if payload[0] == 0xFE and len(payload) < 9:
                return Result(columns, rows)
            if payload[0] == 0xFF:
                raise parse_err(payload)
            row = []
            pos = 0
            for _ in range(n_cols):
                if payload[pos] == 0xFB:  # NULL
                    row.append(None)
                    pos += 1
                else:
                    length, pos = read_lenenc_int(payload, pos)
                    row.append(payload[pos:pos + length].decode())
                    pos += length
            rows.append(tuple(row))

    @staticmethod
    def _parse_column(payload: bytes) -> str:
        # catalog, schema, table, org_table, name, org_name (lenenc strs)
        pos = 0
        out = ""
        for i in range(5):
            length, pos = read_lenenc_int(payload, pos)
            s = payload[pos:pos + length]
            pos += length
            if i == 4:
                out = s.decode()
        return out

    def close(self) -> None:
        try:
            self.io.reset_seq()
            self.io.write_packet(b"\x01")  # COM_QUIT
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Server-side helpers (for the sim)


def ok_packet(affected: int = 0) -> bytes:
    return b"\x00" + lenenc_int(affected) + lenenc_int(0) + b"\x02\x00\x00\x00"


def err_packet(code: int, message: str, sqlstate: str = "HY000") -> bytes:
    return (b"\xff" + struct.pack("<H", code) + b"#"
            + sqlstate.encode()[:5].ljust(5, b"0") + message.encode())


def eof_packet() -> bytes:
    return b"\xfe\x00\x00\x02\x00"


def column_packet(name: str) -> bytes:
    b = name.encode()
    return (lenenc_str(b"def") + lenenc_str(b"") + lenenc_str(b"")
            + lenenc_str(b"") + lenenc_str(b) + lenenc_str(b)
            + b"\x0c" + struct.pack("<HIBHB", 33, 255, 0xFD, 0, 0)
            + b"\x00\x00")


def row_packet(row) -> bytes:
    out = b""
    for v in row:
        if v is None:
            out += b"\xfb"
        else:
            out += lenenc_str(str(v).encode())
    return out
