"""A hermetic RabbitMQ lookalike: the AMQP 0-9-1 server subset
amqp_proto speaks — PLAIN handshake, channel open, queue
declare/purge, publisher confirms, basic.publish (method + header +
body frames), basic.get with auto-ack. Queues are FIFO lists of
base64 bodies in the shared flock store."""

from __future__ import annotations

import argparse
import base64
import random
import socketserver
import struct
import sys
import time

from . import amqp_proto as aq
from .simbase import Store, build_sim_archive


class Handler(socketserver.BaseRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client went away")
            buf += chunk
        return buf

    def _read_frame(self) -> tuple:
        header = self._read_exact(7)
        ftype, channel, size = struct.unpack(">BHI", header)
        payload = self._read_exact(size)
        self._read_exact(1)
        return ftype, channel, payload

    def _send_frame(self, ftype: int, channel: int,
                    payload: bytes) -> None:
        self.request.sendall(struct.pack(">BHI", ftype, channel,
                                         len(payload))
                             + payload + bytes([aq.FRAME_END]))

    def _send_method(self, channel: int, cm: tuple,
                     args: bytes = b"") -> None:
        self._send_frame(aq.FRAME_METHOD, channel,
                         struct.pack(">HH", *cm) + args)

    def handle(self):
        self.request.settimeout(120.0)
        confirms = False
        publish_seq = 0
        try:
            if self._read_exact(8) != b"AMQP\x00\x00\x09\x01":
                return
            self._send_method(0, aq.CONN_START,
                              struct.pack(">BB", 0, 9)
                              + struct.pack(">I", 0)
                              + aq.longstr(b"PLAIN")
                              + aq.longstr(b"en_US"))
            self._read_frame()  # start-ok: accept anyone
            self._send_method(0, aq.CONN_TUNE,
                              struct.pack(">HIH", 0, 131072, 0))
            self._read_frame()  # tune-ok
            self._read_frame()  # open
            self._send_method(0, aq.CONN_OPEN_OK, aq.shortstr(""))

            while True:
                ftype, channel, payload = self._read_frame()
                if ftype != aq.FRAME_METHOD:
                    continue
                cm = struct.unpack_from(">HH", payload)
                args = payload[4:]
                if self.mean_latency > 0:
                    time.sleep(random.expovariate(1.0 / self.mean_latency))
                if cm == aq.CH_OPEN:
                    self._send_method(channel, aq.CH_OPEN_OK,
                                      struct.pack(">I", 0))
                elif cm == aq.Q_DECLARE:
                    queue, _ = aq.read_shortstr(args, 2)

                    def declare(data):
                        queues = dict(data.get("queues") or {})
                        if queue not in queues:
                            queues[queue] = []
                            new = dict(data)
                            new["queues"] = queues
                            return None, new
                        return None, None

                    self.store.transact(declare)
                    self._send_method(channel, aq.Q_DECLARE_OK,
                                      aq.shortstr(queue)
                                      + struct.pack(">II", 0, 0))
                elif cm == aq.Q_PURGE:
                    queue, _ = aq.read_shortstr(args, 2)

                    def purge(data):
                        queues = dict(data.get("queues") or {})
                        n = len(queues.get(queue) or [])
                        queues[queue] = []
                        new = dict(data)
                        new["queues"] = queues
                        return n, new

                    n = self.store.transact(purge)
                    self._send_method(channel, aq.Q_PURGE_OK,
                                      struct.pack(">I", n))
                elif cm == aq.CONFIRM_SELECT:
                    confirms = True
                    self._send_method(channel, aq.CONFIRM_SELECT_OK)
                elif cm == aq.BASIC_PUBLISH:
                    pos = 2
                    _exchange, pos = aq.read_shortstr(args, pos)
                    routing_key, pos = aq.read_shortstr(args, pos)
                    ftype, _ch, header = self._read_frame()
                    _cls, _w, size = struct.unpack_from(">HHQ", header)
                    body = b""
                    while len(body) < size:
                        ftype, _ch, chunk = self._read_frame()
                        body += chunk

                    def enqueue(data):
                        queues = dict(data.get("queues") or {})
                        queues[routing_key] = (
                            list(queues.get(routing_key) or [])
                            + [base64.b64encode(body).decode()])
                        new = dict(data)
                        new["queues"] = queues
                        return None, new

                    self.store.transact(enqueue)
                    if confirms:
                        publish_seq += 1
                        self._send_method(
                            channel, aq.BASIC_ACK,
                            struct.pack(">QB", publish_seq, 0))
                elif cm == aq.BASIC_GET:
                    queue, _ = aq.read_shortstr(args, 2)

                    def take(data):
                        queues = dict(data.get("queues") or {})
                        q = list(queues.get(queue) or [])
                        if not q:
                            return None, None
                        head, rest = q[0], q[1:]
                        queues[queue] = rest
                        new = dict(data)
                        new["queues"] = queues
                        return head, new

                    got = self.store.transact(take)
                    if got is None:
                        self._send_method(channel, aq.BASIC_GET_EMPTY,
                                          aq.shortstr(""))
                    else:
                        body = base64.b64decode(got)
                        self._send_method(
                            channel, aq.BASIC_GET_OK,
                            struct.pack(">QB", 1, 0)
                            + aq.shortstr("") + aq.shortstr(queue)
                            + struct.pack(">I", 0))
                        self._send_frame(
                            aq.FRAME_HEADER, channel,
                            struct.pack(">HHQ", 60, 0, len(body))
                            + struct.pack(">H", 0))
                        self._send_frame(aq.FRAME_BODY, channel, body)
                elif cm == aq.CONN_CLOSE:
                    return
        except (ConnectionError, TimeoutError, OSError, struct.error):
            return


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def parse_args(argv):
    p = argparse.ArgumentParser(description="rabbitmq AMQP sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=5672)
    p.add_argument("--name", default="sim")
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    srv = Server(("127.0.0.1", args.port), Handler)
    print(f"amqp-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.amqp_sim", "rabbitmq-server",
        "rabbitmq-sim", data_path, mean_latency=mean_latency,
        python=python,
    )


if __name__ == "__main__":
    serve()
