"""A hermetic RabbitMQ lookalike: the AMQP 0-9-1 server subset
amqp_proto speaks — PLAIN handshake, channel open, queue
declare/purge, publisher confirms, basic.publish (method + header +
body frames), basic.get with and without auto-ack, basic.reject.
Queues are FIFO lists of base64 bodies in the shared flock store.

Unacked deliveries are PERSISTED in the shared store under a
per-connection owner token (data["unacked"]); a reject-with-requeue
or the connection dying puts them back at the HEAD of the shared
queue — the broker behavior that makes the distributed-semaphore
pattern (hold the unacked message = hold the mutex,
rabbitmq.clj:185-263) unsafe under partitions: the broker requeues a
"held" semaphore the moment it declares the holder's connection dead.
Owner tokens are prefixed with this node's port, and serve() requeues
any leftovers under its own prefix at startup — a killed broker
process recovers its connections' unacked persistent messages on
restart exactly like a durable RabbitMQ node, so a kill nemesis
cannot silently lose the semaphore and leave the workload checking a
trivially-valid all-fail history."""

from __future__ import annotations

import argparse
import base64
import random
import socketserver
import struct
import sys
import time

from . import amqp_proto as aq
from .simbase import Store, build_sim_archive


def _release_unacked(store: Store, token: str, entries: list,
                     requeue: bool) -> None:
    """Drop `entries` ([queue, body_b64] pairs) from `token`'s
    persisted unacked set, prepending each to its queue if requeueing
    — one transaction for atomicity with concurrent getters."""

    def rel(data):
        new = dict(data)
        un = {k: list(v) for k, v in
              (data.get("unacked") or {}).items()}
        mine = list(un.get(token) or [])
        queues = dict(data.get("queues") or {})
        for queue, body in entries:
            if [queue, body] in mine:
                mine.remove([queue, body])
                if requeue:
                    queues[queue] = ([body]
                                     + list(queues.get(queue) or []))
        if mine:
            un[token] = mine
        else:
            un.pop(token, None)
        new["unacked"] = un
        new["queues"] = queues
        return None, new

    store.transact(rel)


def _recover_unacked(store: Store, port: int) -> int:
    """Requeue every unacked delivery owned by a connection of THIS
    node (token prefix "<port>:") — run at broker startup, when any
    such connection is necessarily dead. This is durable-RabbitMQ
    crash recovery: persistent messages that were delivered but never
    acked come back on restart."""
    prefix = f"{port}:"

    def rec(data):
        un = {k: list(v) for k, v in
              (data.get("unacked") or {}).items()}
        queues = dict(data.get("queues") or {})
        n = 0
        for token in [t for t in un if t.startswith(prefix)]:
            for queue, body in un.pop(token):
                queues[queue] = [body] + list(queues.get(queue) or [])
                n += 1
        new = dict(data)
        new["unacked"] = un
        new["queues"] = queues
        return n, new

    return store.transact(rec)


class Handler(socketserver.BaseRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client went away")
            buf += chunk
        return buf

    def _read_frame(self) -> tuple:
        header = self._read_exact(7)
        ftype, channel, size = struct.unpack(">BHI", header)
        payload = self._read_exact(size)
        self._read_exact(1)
        return ftype, channel, payload

    def _send_frame(self, ftype: int, channel: int,
                    payload: bytes) -> None:
        self.request.sendall(struct.pack(">BHI", ftype, channel,
                                         len(payload))
                             + payload + bytes([aq.FRAME_END]))

    def _send_method(self, channel: int, cm: tuple,
                     args: bytes = b"") -> None:
        self._send_frame(aq.FRAME_METHOD, channel,
                         struct.pack(">HH", *cm) + args)

    def handle(self):
        import uuid

        self.request.settimeout(120.0)
        confirms = False
        publish_seq = 0
        # This connection's owner token in the store's "unacked" area
        # (port-prefixed so a restarted node can find its orphans),
        # plus the in-memory delivery-tag -> store-entry map.
        self.token = (f"{self.server.server_address[1]}:"
                      f"{uuid.uuid4().hex[:12]}")
        self.unacked = {}
        self.next_tag = 1
        try:
            if self._read_exact(8) != b"AMQP\x00\x00\x09\x01":
                return
            self._send_method(0, aq.CONN_START,
                              struct.pack(">BB", 0, 9)
                              + struct.pack(">I", 0)
                              + aq.longstr(b"PLAIN")
                              + aq.longstr(b"en_US"))
            self._read_frame()  # start-ok: accept anyone
            self._send_method(0, aq.CONN_TUNE,
                              struct.pack(">HIH", 0, 131072, 0))
            self._read_frame()  # tune-ok
            self._read_frame()  # open
            self._send_method(0, aq.CONN_OPEN_OK, aq.shortstr(""))

            while True:
                ftype, channel, payload = self._read_frame()
                if ftype != aq.FRAME_METHOD:
                    continue
                cm = struct.unpack_from(">HH", payload)
                args = payload[4:]
                if self.mean_latency > 0:
                    time.sleep(random.expovariate(1.0 / self.mean_latency))
                if cm == aq.CH_OPEN:
                    self._send_method(channel, aq.CH_OPEN_OK,
                                      struct.pack(">I", 0))
                elif cm == aq.Q_DECLARE:
                    queue, _ = aq.read_shortstr(args, 2)

                    def declare(data):
                        queues = dict(data.get("queues") or {})
                        if queue not in queues:
                            queues[queue] = []
                            new = dict(data)
                            new["queues"] = queues
                            return None, new
                        return None, None

                    self.store.transact(declare)
                    self._send_method(channel, aq.Q_DECLARE_OK,
                                      aq.shortstr(queue)
                                      + struct.pack(">II", 0, 0))
                elif cm == aq.Q_PURGE:
                    queue, _ = aq.read_shortstr(args, 2)

                    def purge(data):
                        queues = dict(data.get("queues") or {})
                        n = len(queues.get(queue) or [])
                        queues[queue] = []
                        new = dict(data)
                        new["queues"] = queues
                        return n, new

                    n = self.store.transact(purge)
                    self._send_method(channel, aq.Q_PURGE_OK,
                                      struct.pack(">I", n))
                elif cm == aq.CONFIRM_SELECT:
                    confirms = True
                    self._send_method(channel, aq.CONFIRM_SELECT_OK)
                elif cm == aq.BASIC_PUBLISH:
                    pos = 2
                    _exchange, pos = aq.read_shortstr(args, pos)
                    routing_key, pos = aq.read_shortstr(args, pos)
                    ftype, _ch, header = self._read_frame()
                    _cls, _w, size = struct.unpack_from(">HHQ", header)
                    body = b""
                    while len(body) < size:
                        ftype, _ch, chunk = self._read_frame()
                        body += chunk

                    def enqueue(data):
                        queues = dict(data.get("queues") or {})
                        queues[routing_key] = (
                            list(queues.get(routing_key) or [])
                            + [base64.b64encode(body).decode()])
                        new = dict(data)
                        new["queues"] = queues
                        return None, new

                    self.store.transact(enqueue)
                    if confirms:
                        publish_seq += 1
                        self._send_method(
                            channel, aq.BASIC_ACK,
                            struct.pack(">QB", publish_seq, 0))
                elif cm == aq.BASIC_GET:
                    queue, pos = aq.read_shortstr(args, 2)
                    no_ack = bool(args[pos]) if pos < len(args) else True

                    def take(data):
                        queues = dict(data.get("queues") or {})
                        q = list(queues.get(queue) or [])
                        if not q:
                            return None, None
                        head, rest = q[0], q[1:]
                        queues[queue] = rest
                        new = dict(data)
                        new["queues"] = queues
                        if not no_ack:
                            # the delivery stays PERSISTED under this
                            # connection's owner token until acked,
                            # rejected, or recovered (module docstring)
                            un = {k: list(v) for k, v in
                                  (data.get("unacked") or {}).items()}
                            un[self.token] = (un.get(self.token) or
                                              []) + [[queue, head]]
                            new["unacked"] = un
                        return head, new

                    got = self.store.transact(take)
                    if got is None:
                        self._send_method(channel, aq.BASIC_GET_EMPTY,
                                          aq.shortstr(""))
                    else:
                        tag = self.next_tag
                        self.next_tag += 1
                        if not no_ack:
                            self.unacked[tag] = (queue, got)
                        body = base64.b64decode(got)
                        self._send_method(
                            channel, aq.BASIC_GET_OK,
                            struct.pack(">QB", tag, 0)
                            + aq.shortstr("") + aq.shortstr(queue)
                            + struct.pack(">I", 0))
                        self._send_frame(
                            aq.FRAME_HEADER, channel,
                            struct.pack(">HHQ", 60, 0, len(body))
                            + struct.pack(">H", 0))
                        if body:  # zero-length bodies carry NO body
                            # frame (AMQP 0-9-1 §4.2.6; readers stop
                            # at the header's body-size)
                            self._send_frame(aq.FRAME_BODY, channel,
                                             body)
                elif cm == aq.BASIC_REJECT:
                    tag, = struct.unpack_from(">Q", args)
                    requeue = bool(args[8]) if len(args) > 8 else False
                    held = self.unacked.pop(tag, None)
                    if held is not None:
                        _release_unacked(self.store, self.token,
                                         [held], requeue)
                    # basic.reject has no -ok reply
                elif cm == aq.CONN_CLOSE:
                    return
        except (ConnectionError, TimeoutError, OSError, struct.error):
            return
        finally:
            # the broker requeues everything an expiring connection
            # still held — the semaphore-breaking behavior under test
            if self.unacked:
                try:
                    _release_unacked(self.store, self.token,
                                     list(self.unacked.values()), True)
                except OSError:
                    pass


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def parse_args(argv):
    p = argparse.ArgumentParser(description="rabbitmq AMQP sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=5672)
    p.add_argument("--name", default="sim")
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    recovered = _recover_unacked(Handler.store, args.port)
    if recovered:
        print(f"amqp-sim recovered {recovered} unacked deliveries")
    srv = Server(("127.0.0.1", args.port), Handler)
    print(f"amqp-sim {args.name} serving on {args.port}, "
          f"data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.amqp_sim", "rabbitmq-server",
        "rabbitmq-sim", data_path, mean_latency=mean_latency,
        python=python,
    )


if __name__ == "__main__":
    serve()
