"""A hermetic RethinkDB lookalike: the V0_4/JSON wire protocol plus a
mini ReQL interpreter covering the term trees the rethinkdb suite
issues — db/table create, get, insert (conflict=update|error), update
with a literal patch or a FUNC body (branch/eq/get_field/error — the
CAS shape), get_field with DEFAULT fallback. State lives in the shared
flock-guarded store as {dbs: {db: {tbl: {id: row}}}}."""

from __future__ import annotations

import argparse
import json
import random
import socketserver
import struct
import sys
import time

from . import rethink_proto as rp
from .simbase import Store, build_sim_archive


class Abort(Exception):
    """r.error() raised inside an update function."""


class Interp:
    """Evaluate one query term against a state snapshot; mutations
    rewrite the snapshot in place and set self.dirty."""

    def __init__(self, data: dict):
        self.data = data
        self.dirty = False
        self.scope: dict = {}

    def _dbs(self):
        return self.data.setdefault("dbs", {})

    def eval(self, term):
        if not isinstance(term, list):
            if isinstance(term, dict):
                return {k: self.eval(v) for k, v in term.items()}
            return term
        ttype, args = term[0], term[1] if len(term) > 1 else []
        opts = term[2] if len(term) > 2 else {}
        fn = getattr(self, f"t_{ttype}", None)
        if fn is None:
            raise rp.ReqlError(rp.COMPILE_ERROR,
                               f"unsupported term {ttype}")
        return fn(args, opts)

    # -- structure --------------------------------------------------------

    def t_2(self, args, opts):  # MAKE_ARRAY
        return [self.eval(a) for a in args]

    def t_14(self, args, opts):  # DB
        return ("db", self.eval(args[0]))

    def t_57(self, args, opts):  # DB_CREATE
        name = self.eval(args[0])
        if name in self._dbs():
            raise rp.ReqlError(rp.RUNTIME_ERROR,
                               f"Database `{name}` already exists")
        self._dbs()[name] = {}
        self.dirty = True
        return {"dbs_created": 1}

    def t_60(self, args, opts):  # TABLE_CREATE
        _, dbname = self.eval(args[0])
        name = self.eval(args[1])
        tables = self._dbs().setdefault(dbname, {})
        if name in tables:
            raise rp.ReqlError(rp.RUNTIME_ERROR,
                               f"Table `{name}` already exists")
        tables[name] = {}
        self.dirty = True
        return {"tables_created": 1}

    def t_176(self, args, opts):  # RECONFIGURE
        """Topology change (rethinkdb.clj:180-194's r.reconfigure).
        The sim keeps the replica map as table metadata — data stays
        shared-store-global like a fully replicated table — and
        answers {reconfigured: 1} like a healthy cluster."""
        _, dbname, tname = self.eval(args[0])
        replicas = self.eval(opts.get("replicas") or {})
        primary = self.eval(opts.get("primary_replica_tag"))
        if primary is not None and replicas and primary not in replicas:
            raise rp.ReqlError(
                rp.RUNTIME_ERROR,
                f"Could not find any servers with server tag "
                f"`{primary}`")
        topo = self.data.setdefault("topology", {})
        topo[f"{dbname}.{tname}"] = {"shards": self.eval(
            opts.get("shards", 1)), "replicas": replicas,
            "primary": primary}
        self.dirty = True
        return {"reconfigured": 1}

    def t_15(self, args, opts):  # TABLE
        _, dbname = self.eval(args[0])
        name = self.eval(args[1])
        tbl = (self._dbs().get(dbname) or {}).get(name)
        if tbl is None:
            raise rp.ReqlError(rp.RUNTIME_ERROR,
                               f"Table `{dbname}.{name}` does not exist")
        return ("table", dbname, name)

    def t_16(self, args, opts):  # GET
        _, dbname, tname = self.eval(args[0])
        key = self.eval(args[1])
        return ("row", dbname, tname, key)

    # -- reads ------------------------------------------------------------

    def _row(self, sel):
        _, dbname, tname, key = sel
        return self._dbs()[dbname][tname].get(str(key))

    def t_31(self, args, opts):  # GET_FIELD
        target = self.eval(args[0])
        field = self.eval(args[1])
        if isinstance(target, tuple) and target[0] == "row":
            target = self._row(target)
        if target is None:
            raise rp.ReqlError(rp.RUNTIME_ERROR,
                               "Cannot perform get_field on a "
                               "non-object non-sequence `null`")
        if field not in target:
            raise rp.ReqlError(rp.RUNTIME_ERROR, f"No attribute `{field}`")
        return target[field]

    def t_92(self, args, opts):  # DEFAULT
        try:
            return self.eval(args[0])
        except rp.ReqlError:
            return self.eval(args[1])

    def t_17(self, args, opts):  # EQ
        return self.eval(args[0]) == self.eval(args[1])

    def t_12(self, args, opts):  # ERROR
        raise Abort(self.eval(args[0]))

    def t_65(self, args, opts):  # BRANCH
        if self.eval(args[0]):
            return self.eval(args[1])
        return self.eval(args[2])

    def t_10(self, args, opts):  # VAR
        return self.scope[self.eval(args[0])]

    # -- writes -----------------------------------------------------------

    def t_56(self, args, opts):  # INSERT
        _, dbname, tname = self.eval(args[0])
        doc = self.eval(args[1])
        tbl = self._dbs()[dbname][tname]
        key = str(doc["id"])
        conflict = opts.get("conflict", "error")
        if key in tbl:
            if conflict == "update":
                tbl[key] = {**tbl[key], **doc}
                self.dirty = True
                return {"inserted": 0, "replaced": 1, "errors": 0}
            return {"inserted": 0, "errors": 1,
                    "first_error": "Duplicate primary key"}
        tbl[key] = doc
        self.dirty = True
        return {"inserted": 1, "replaced": 0, "errors": 0}

    def t_53(self, args, opts):  # UPDATE
        sel = self.eval(args[0])
        patch = args[1]
        rows = []
        if isinstance(sel, tuple) and sel[0] == "row":
            _, dbname, tname, key = sel
            row = self._dbs()[dbname][tname].get(str(key))
            if row is not None:
                rows = [(str(key), row)]
            tbl = self._dbs()[dbname][tname]
        elif isinstance(sel, tuple) and sel[0] == "table":
            _, dbname, tname = sel
            tbl = self._dbs()[dbname][tname]
            rows = list(tbl.items())
        else:
            raise rp.ReqlError(rp.RUNTIME_ERROR, "can't update that")
        replaced = 0
        errors = 0
        first_error = None
        for key, row in rows:
            try:
                if (isinstance(patch, list) and patch
                        and patch[0] == rp.FUNC):
                    params = self.eval(patch[1][0])
                    self.scope[params[0]] = row
                    delta = self.eval(patch[1][1])
                else:
                    delta = self.eval(patch)
                new = {**row, **delta}
                if new != row:
                    tbl[key] = new
                    self.dirty = True
                    replaced += 1
            except Abort as e:
                errors += 1
                first_error = str(e)
        out = {"replaced": replaced, "errors": errors, "unchanged":
               len(rows) - replaced - errors, "skipped": 0}
        if first_error:
            out["first_error"] = first_error
        return out


class Handler(socketserver.BaseRequestHandler):
    store: Store = None  # type: ignore[assignment]
    mean_latency: float = 0.0

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client went away")
            buf += chunk
        return buf

    def handle(self):
        self.request.settimeout(120.0)
        try:
            (magic,) = struct.unpack("<I", self._read_exact(4))
            if magic != rp.V0_4:
                self.request.sendall(b"ERROR: bad magic\x00")
                return
            (key_len,) = struct.unpack("<I", self._read_exact(4))
            self._read_exact(key_len)  # auth key accepted
            self._read_exact(4)        # protocol magic
            self.request.sendall(b"SUCCESS\x00")
            while True:
                token = struct.unpack("<q", self._read_exact(8))[0]
                (length,) = struct.unpack("<I", self._read_exact(4))
                qtype, term, _opts = json.loads(self._read_exact(length))
                if self.mean_latency > 0:
                    time.sleep(random.expovariate(1.0 / self.mean_latency))
                if qtype != rp.START:
                    self._reply(token, rp.CLIENT_ERROR,
                                [f"unsupported query type {qtype}"])
                    continue

                def run(data):
                    interp = Interp(data)
                    try:
                        out = interp.eval(term)
                        return (rp.SUCCESS_ATOM, out), \
                            (data if interp.dirty else None)
                    except rp.ReqlError as e:
                        return (e.rtype, str(e)), None
                    except Abort as e:
                        return (rp.RUNTIME_ERROR, str(e)), None

                rtype, payload = self.store.transact(run)
                self._reply(token, rtype, [payload])
        except (ConnectionError, TimeoutError, OSError,
                json.JSONDecodeError):
            return

    def _reply(self, token: int, rtype: int, r: list) -> None:
        body = json.dumps({"t": rtype, "r": r}).encode()
        self.request.sendall(struct.pack("<q", token)
                             + struct.pack("<I", len(body)) + body)


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def parse_args(argv):
    p = argparse.ArgumentParser(description="rethinkdb ReQL sim",
                                allow_abbrev=False)
    p.add_argument("--data", required=True)
    p.add_argument("--mean-latency", type=float, default=0.0)
    p.add_argument("--port", type=int, default=28015)
    p.add_argument("--name", default="sim")
    # rethinkdb launcher flags tolerated:
    p.add_argument("--driver-port", dest="driver_port", type=int,
                   default=None)
    p.add_argument("--join", default=None)
    p.add_argument("--directory", default=None)
    return p.parse_args(argv)


def serve(argv=None) -> None:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    port = args.driver_port or args.port
    Handler.store = Store(args.data)
    Handler.mean_latency = args.mean_latency
    srv = Server(("127.0.0.1", port), Handler)
    print(f"rethink-sim {args.name} serving on {port}, data={args.data}")
    sys.stdout.flush()
    srv.serve_forever()


def build_archive(dest: str, data_path: str, mean_latency: float = 0.0,
                  python: str | None = None) -> str:
    return build_sim_archive(
        dest, "jepsen_tpu.dbs.rethink_sim", "rethinkdb", "rethinkdb-sim",
        data_path, mean_latency=mean_latency, python=python,
    )


if __name__ == "__main__":
    serve()
