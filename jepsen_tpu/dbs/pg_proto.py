"""Minimal PostgreSQL wire-protocol (v3) client — the transport the
cockroachdb suite uses to reach real CockroachDB nodes (which speak
pgwire on port 26257) and the in-repo crdb_sim.

The reference suite goes through clojure.java.jdbc + the Postgres JDBC
driver (cockroachdb/src/jepsen/cockroach/client.clj:46-69); there is no
Postgres driver baked into this environment, so we implement the small
protocol subset the suites need: startup (trust or cleartext-password
auth), simple Query, text-format results, SQLSTATE-carrying errors.

Protocol reference: PostgreSQL docs "Frontend/Backend Protocol". Only
the simple-query flow is implemented — every suite statement is a
single 'Q' message; results arrive as RowDescription / DataRow* /
CommandComplete, bracketed by ReadyForQuery.
"""

from __future__ import annotations

import socket
import struct


PROTOCOL_V3 = 196608        # 3 << 16
SSL_REQUEST = 80877103


class PgError(Exception):
    """Server ErrorResponse. sqlstate is the 5-char class code ('C'
    field) — '40001' is serialization_failure, cockroach's 'restart
    transaction' class."""

    def __init__(self, sqlstate: str | None, message: str,
                 severity: str = "ERROR"):
        super().__init__(f"{severity} {sqlstate}: {message}")
        self.sqlstate = sqlstate
        self.message = message
        self.severity = severity

    @property
    def retryable(self) -> bool:
        return self.sqlstate == "40001"


class PgProtocolError(Exception):
    pass


class Result:
    """One statement's outcome: column names, text rows (None for SQL
    NULL), and the CommandComplete tag (e.g. 'UPDATE 2')."""

    def __init__(self, columns: list, rows: list, tag: str):
        self.columns = columns
        self.rows = rows
        self.tag = tag

    @property
    def rowcount(self) -> int:
        """Rows affected, parsed off the tag (INSERT's tag is
        'INSERT <oid> <rows>')."""
        parts = self.tag.split()
        try:
            return int(parts[-1])
        except (ValueError, IndexError):
            return 0

    def scalars(self) -> list:
        return [r[0] for r in self.rows]

    def __repr__(self):
        return f"Result({self.tag!r}, {len(self.rows)} rows)"


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("pg connection closed mid-message")
        buf += chunk
    return buf


def read_message(sock: socket.socket) -> tuple:
    """(type_byte, payload) — payload excludes the length word."""
    t = _read_exact(sock, 1)
    (length,) = struct.unpack("!i", _read_exact(sock, 4))
    return t, _read_exact(sock, length - 4)


def _cstr(payload: bytes, off: int) -> tuple:
    end = payload.index(b"\x00", off)
    return payload[off:end].decode(), end + 1


def parse_error(payload: bytes) -> PgError:
    fields = {}
    off = 0
    while off < len(payload) and payload[off] != 0:
        code = chr(payload[off])
        value, off = _cstr(payload, off + 1)
        fields[code] = value
    return PgError(fields.get("C"), fields.get("M", ""),
                   fields.get("S", "ERROR"))


class PgConn:
    """One pgwire connection. Not thread-safe (one worker per client,
    like the reference's one JDBC conn per worker)."""

    def __init__(self, host: str, port: int, user: str = "root",
                 database: str = "jepsen", password: str | None = None,
                 timeout: float = 10.0, connect_timeout: float = 30.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.settimeout(timeout)
        self._startup(user, database, password)

    # -- session setup ----------------------------------------------------

    def _startup(self, user: str, database: str,
                 password: str | None) -> None:
        params = (f"user\x00{user}\x00database\x00{database}\x00\x00"
                  .encode())
        msg = struct.pack("!ii", 8 + len(params), PROTOCOL_V3) + params
        self.sock.sendall(msg)
        while True:
            t, payload = read_message(self.sock)
            if t == b"R":
                (auth,) = struct.unpack("!i", payload[:4])
                if auth == 0:
                    continue  # AuthenticationOk
                if auth == 3:  # cleartext password
                    if password is None:
                        raise PgProtocolError("server wants a password")
                    body = password.encode() + b"\x00"
                    self.sock.sendall(
                        b"p" + struct.pack("!i", 4 + len(body)) + body)
                    continue
                raise PgProtocolError(f"unsupported auth method {auth}")
            if t in (b"S", b"K", b"N"):  # params, key data, notice
                continue
            if t == b"E":
                raise parse_error(payload)
            if t == b"Z":
                return
            raise PgProtocolError(f"unexpected startup message {t!r}")

    # -- queries ----------------------------------------------------------

    def query(self, sql: str) -> Result:
        """Run one statement via simple Query; raise PgError on server
        error (after draining to ReadyForQuery so the connection stays
        usable — the JDBC driver does the same)."""
        body = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack("!i", 4 + len(body)) + body)
        columns: list = []
        rows: list = []
        tag = ""
        error: PgError | None = None
        while True:
            t, payload = read_message(self.sock)
            if t == b"T":
                columns = self._parse_row_description(payload)
            elif t == b"D":
                rows.append(self._parse_data_row(payload))
            elif t == b"C":
                tag, _ = _cstr(payload, 0)
            elif t == b"E":
                error = parse_error(payload)
            elif t in (b"N", b"S"):
                continue
            elif t == b"I":  # EmptyQueryResponse
                tag = ""
            elif t == b"Z":
                if error is not None:
                    raise error
                return Result(columns, rows, tag)
            else:
                raise PgProtocolError(f"unexpected message {t!r}")

    @staticmethod
    def _parse_row_description(payload: bytes) -> list:
        (n,) = struct.unpack("!h", payload[:2])
        cols = []
        off = 2
        for _ in range(n):
            name, off = _cstr(payload, off)
            off += 18  # tableoid i32, attnum i16, typoid i32, typlen i16,
            #            typmod i32, format i16
            cols.append(name)
        return cols

    @staticmethod
    def _parse_data_row(payload: bytes) -> tuple:
        (n,) = struct.unpack("!h", payload[:2])
        vals = []
        off = 2
        for _ in range(n):
            (length,) = struct.unpack("!i", payload[off:off + 4])
            off += 4
            if length < 0:
                vals.append(None)
            else:
                vals.append(payload[off:off + length].decode())
                off += length
        return tuple(vals)

    def close(self) -> None:
        try:
            self.sock.sendall(b"X" + struct.pack("!i", 4))
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
