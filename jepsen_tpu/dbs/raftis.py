"""Raftis test suite: a raft-replicated redis register driven with
read/write ops over RESP (reference:
/root/reference/raftis/src/jepsen/raftis.clj:1-138).

Pieces, mirroring the reference:
  - RaftisDB     — archive install + daemon with an initial-cluster
                   string "host:8901,..." (raftis.clj:61-105)
  - RaftisClient — GET/SET on key "r" with the reference's error
                   taxonomy (raftis.clj:36-57): reads always :fail;
                   "no leader" and "socket closed" writes :fail (the
                   write was rejected/never sent); other write errors
                   and timeouts :info
  - raftis_test  — register workload, partition nemesis, linearizable
                   checker (raftis.clj:107-130)
"""

from __future__ import annotations

import itertools
import logging
import random
import socket
import time

from .. import checker as checker_mod
from .. import cli, client, db, generator as gen, models, nemesis, osdist
from ..control import util as cu
from ..history import Op
from . import redis_proto

log = logging.getLogger("jepsen_tpu.dbs.raftis")

PORT = 6379
RAFT_PORT = 8901
KEY = "r"


def _cfg(test) -> dict:
    return test.get("raftis") or {}


def node_host(test, node) -> str:
    fn = _cfg(test).get("addr_fn")
    return fn(node) if fn else str(node)


def node_port(test, node) -> int:
    ports = _cfg(test).get("ports")
    return ports[node] if ports else PORT


def node_dir(test, node) -> str:
    d = _cfg(test).get("dir", "/opt/raftis")
    return d(node) if callable(d) else d


def initial_cluster(test) -> str:
    """host:8901,host:8901,... (raftis.clj:68-74)."""
    return ",".join(
        f"{node_host(test, n)}:{RAFT_PORT}" for n in test["nodes"]
    )


class RaftisDB(db.DB, db.LogFiles):
    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 30.0):
        self.archive_url = archive_url
        self.ready_timeout = ready_timeout

    def setup(self, test, node) -> None:
        remote = test["remote"]
        d = node_dir(test, node)
        sudo = _cfg(test).get("sudo", True)
        url = self.archive_url or _cfg(test).get("archive_url")
        if not url:
            raise db.SetupFailed(
                "raftis archive_url required (binary tarball, or the "
                "redis_sim archive for hermetic runs)")
        cu.install_archive(remote, node, url, d, sudo=sudo)
        cu.start_daemon(
            remote, node, f"{d}/raftis",
            "--port", str(node_port(test, node)),
            "--cluster", initial_cluster(test),
            logfile=f"{d}/raftis.log",
            pidfile=f"{d}/raftis.pid",
            chdir=d,
        )
        self.await_ready(test, node)

    def await_ready(self, test, node) -> None:
        deadline = time.monotonic() + self.ready_timeout
        while True:
            try:
                conn = redis_proto.RespConn(
                    node_host(test, node), node_port(test, node),
                    timeout=2.0)
                try:
                    if conn.call("PING") == "PONG":
                        return
                finally:
                    conn.close()
            except OSError:
                pass
            if time.monotonic() > deadline:
                raise db.SetupFailed(f"raftis on {node} never ponged")
            time.sleep(0.2)

    def teardown(self, test, node) -> None:
        remote = test["remote"]
        d = node_dir(test, node)
        log.info("%s tearing down raftis", node)
        cu.stop_daemon(remote, node, f"{d}/raftis.pid")
        remote.exec(node, ["rm", "-rf", d],
                    sudo=_cfg(test).get("sudo", True), check=False)

    def log_files(self, test, node) -> list:
        return [f"{node_dir(test, node)}/raftis.log"]


class RaftisClient(client.Client):
    """GET/SET register with raftis.clj:44-57's taxonomy."""

    def __init__(self, conn: redis_proto.RespConn | None = None,
                 timeout: float = 5.0):
        self.conn = conn
        self.timeout = timeout

    def open(self, test, node):
        conn = redis_proto.RespConn(
            node_host(test, node), node_port(test, node),
            timeout=self.timeout)
        return RaftisClient(conn, timeout=self.timeout)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                raw = self.conn.call("GET", KEY)
                value = int(raw) if raw is not None else None
                return op.with_(type="ok", value=value)
            if op.f == "write":
                self.conn.call("SET", KEY, op.value)
                return op.with_(type="ok")
            raise ValueError(f"unknown op {op.f!r}")
        except redis_proto.RespError as e:
            # "no leader" means the write was rejected — definite fail
            # (raftis.clj:46-49)
            if op.f == "read" or "no leader" in str(e):
                return op.with_(type="fail", error=str(e))
            return op.with_(type="info", error=str(e))
        except (socket.timeout, TimeoutError):
            return op.with_(
                type="fail" if op.f == "read" else "info", error="timeout")
        except ConnectionError as e:
            # socket closed: the reference treats this as :fail too
            return op.with_(type="fail", error=str(e))
        except OSError as e:
            return op.with_(
                type="fail" if op.f == "read" else "info", error=str(e))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def raftis_test(opts: dict) -> dict:
    from ..testlib import noop_test

    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": "raftis",
            "os": osdist.debian,
            "db": RaftisDB(archive_url=opts.get("archive_url")),
            "client": RaftisClient(),
            "nemesis": nemesis.partition_random_halves(),
            "model": models.Register(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "timeline": checker_mod.timeline_html(),
                "linear": checker_mod.linearizable(),
            }),
            "generator": gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(
                    gen.seq(itertools.cycle([
                        gen.sleep(5),
                        {"type": "info", "f": "start"},
                        gen.sleep(5),
                        {"type": "info", "f": "stop"},
                    ])),
                    gen.stagger(1 / 10, gen.mix([r, w])),
                ),
            ),
        }
    )
    return test


def _opt_spec(p) -> None:
    p.add_argument("--archive-url", dest="archive_url", default=None,
                   help="raftis release archive (or the in-repo sim "
                        "archive for hermetic runs).")


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(raftis_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
