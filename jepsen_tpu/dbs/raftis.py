"""Raftis test suite: a raft-replicated redis register driven with
read/write ops over RESP (reference:
/root/reference/raftis/src/jepsen/raftis.clj:1-138).

Pieces, mirroring the reference:
  - RaftisDB     — archive install + daemon with an initial-cluster
                   string "host:8901,..." (raftis.clj:61-105)
  - RaftisClient — GET/SET on key "r" with the reference's error
                   taxonomy (raftis.clj:36-57): reads always :fail;
                   "no leader" and "socket closed" writes :fail (the
                   write was rejected/never sent); other write errors
                   and timeouts :info
  - raftis_test  — register workload, partition nemesis, linearizable
                   checker (raftis.clj:107-130)
"""

from __future__ import annotations

import itertools
import logging
import random
import socket
import time

from .. import checker as checker_mod
from .. import cli, client, generator as gen, models, osdist
from .. import reconnect
from ..history import Op
from . import redis_proto
from .common import ArchiveDB, SuiteCfg, resp_ping_ready
from . import common as cmn

log = logging.getLogger("jepsen_tpu.dbs.raftis")

PORT = 6379
RAFT_PORT = 8901
KEY = "r"


_suite = SuiteCfg("raftis", PORT, "/opt/raftis")
node_host = _suite.host
node_port = _suite.port


def initial_cluster(test) -> str:
    """host:8901,host:8901,... (raftis.clj:68-74)."""
    return ",".join(
        f"{node_host(test, n)}:{RAFT_PORT}" for n in test["nodes"]
    )


class RaftisDB(ArchiveDB):
    binary = "raftis"
    log_name = "raftis.log"
    pid_name = "raftis.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 30.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        return ["--port", str(node_port(test, node)),
                "--cluster", initial_cluster(test)]

    def probe_ready(self, test, node) -> bool:
        return resp_ping_ready(_suite, test, node)


class RaftisClient(client.Client):
    """GET/SET register with raftis.clj:44-57's taxonomy. The RESP
    connection lives behind a reconnect wrapper: after a timeout the
    server's late reply would otherwise sit in the buffer and
    desynchronize every later op's reply (off-by-one histories), so any
    exception drops the connection and the next op gets a fresh one."""

    def __init__(self, conn=None, timeout: float = 5.0):
        self.conn = conn
        self.timeout = timeout

    def open(self, test, node):
        wrapped = reconnect.wrapper(
            open=lambda: redis_proto.RespConn(
                node_host(test, node), node_port(test, node),
                timeout=self.timeout),
            close=lambda c: c.close(),
            name=f"raftis {node}",
        ).open()
        return RaftisClient(wrapped, timeout=self.timeout)

    def invoke(self, test, op: Op) -> Op:
        try:
            with self.conn.with_conn() as c:
                if op.f == "read":
                    raw = c.call("GET", KEY)
                    value = int(raw) if raw is not None else None
                    return op.with_(type="ok", value=value)
                if op.f == "write":
                    c.call("SET", KEY, op.value)
                    return op.with_(type="ok")
                raise ValueError(f"unknown op {op.f!r}")
        except redis_proto.RespError as e:
            # "no leader" means the write was rejected — definite fail
            # (raftis.clj:46-49)
            if op.f == "read" or "no leader" in str(e):
                return op.with_(type="fail", error=str(e))
            return op.with_(type="info", error=str(e))
        except (socket.timeout, TimeoutError):
            return op.with_(
                type="fail" if op.f == "read" else "info", error="timeout")
        except ConnectionError as e:
            # socket closed: the reference treats this as :fail too
            return op.with_(type="fail", error=str(e))
        except OSError as e:
            return op.with_(
                type="fail" if op.f == "read" else "info", error=str(e))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randrange(5)}


def raftis_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = RaftisDB(archive_url=opts.get("archive_url"))
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": "raftis",
            "os": osdist.debian,
            "db": db_,
            "client": RaftisClient(),
            "nemesis": cmn.pick_nemesis(db_, opts),
            "model": models.Register(),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "timeline": checker_mod.timeline_html(),
                "linear": checker_mod.linearizable(),
            }),
            "generator": gen.time_limit(
                opts.get("time_limit", 60),
                gen.nemesis(
                    gen.seq(itertools.cycle([
                        gen.sleep(5),
                        {"type": "info", "f": "start"},
                        gen.sleep(5),
                        {"type": "info", "f": "stop"},
                    ])),
                    gen.stagger(1 / 10, gen.mix([r, w])),
                ),
            ),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--archive-url", dest="archive_url", default=None,
                   help="raftis release archive (or the in-repo sim "
                        "archive for hermetic runs).")


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(raftis_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
