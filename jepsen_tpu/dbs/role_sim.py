"""A placeholder daemon for non-client-facing cluster roles.

Multi-daemon systems put processes beside the SQL/client server that
tests must be able to start, health-check, kill, and restart
independently — tidb's pd-server and tikv-server
(/root/reference/tidb/src/tidb/db.clj:14-31), mysql cluster's ndb_mgmd
and ndbd (/root/reference/mysql-cluster/src/jepsen/mysql_cluster.clj:
53-57). Their internal protocols aren't what the framework checks;
what matters is the PROCESS TOPOLOGY: distinct pids, distinct ports,
distinct logs, ordered bring-up, and component-targeted fault
injection. This sim binds the role's port, answers `ping` with
`pong\n` (the readiness probe), and otherwise just stays alive.

The port is taken from whichever of the real binaries' addressing
flags appears (so suite daemon args can mirror the reference verbatim):
`--port N`, `--client-urls http://0.0.0.0:N` (pd-server), or
`--addr 0.0.0.0:N` (tikv-server / ndbd-style). Unknown flags are
accepted and ignored, like the real binaries' rich option surfaces.
"""

from __future__ import annotations

import argparse
import socketserver
import sys


class Handler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            while True:
                line = self.rfile.readline()
                if not line:
                    return
                if line.strip().lower() == b"ping":
                    self.wfile.write(b"pong\n")
                else:
                    self.wfile.write(b"ok\n")
        except OSError:
            pass


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def _port_from_args(args) -> int:
    if args.port is not None:
        return args.port
    for url in (args.client_urls, args.addr):
        if url:
            tail = url.rsplit(":", 1)[-1].strip("/")
            if tail.isdigit():
                return int(tail)
    raise SystemExit("role_sim: no --port/--client-urls/--addr given")


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--client-urls", dest="client_urls", default=None)
    p.add_argument("--addr", default=None)
    p.add_argument("--role", default="role")
    # shared launcher-script flags + the real binaries' surfaces
    p.add_argument("--data", default=None)
    p.add_argument("--mean-latency", dest="mean_latency", type=float,
                   default=0.0)
    args, _unknown = p.parse_known_args(argv)
    port = _port_from_args(args)
    srv = Server(("0.0.0.0", port), Handler)
    print(f"role_sim {args.role} listening on {port}", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()


if __name__ == "__main__":
    main(sys.argv[1:])
