"""Disque test suite: at-least-once distributed job queue driven with
enqueue/dequeue/drain ops and checked with total-queue (reference:
/root/reference/disque/src/jepsen/disque.clj:1-321).

Pieces, mirroring the reference:
  - DisqueDB      — build-or-install + daemon lifecycle + CLUSTER MEET
                    join to the primary (disque.clj:40-135)
  - DisqueClient  — ADDJOB/GETJOB/ACKJOB over RESP with a
                    reconnect-on-failure wrapper (the reference's
                    goldfish-replacing reconnecting-client,
                    disque.clj:163-192); dequeue acks what it takes;
                    drain loops until a poll comes back empty
                    (disque.clj:194-240)
  - disque_test   — test map with partitioner nemesis and the final
                    heal-then-drain phase; total-queue checker
"""

from __future__ import annotations

import itertools
import logging
import socket
import time

from .. import checker as checker_mod
from . import common as cmn
from .. import cli, client, db, generator as gen, osdist, reconnect
from ..history import Op
from . import redis_proto
from .common import ArchiveDB, SuiteCfg, ready_gated_final, resp_ping_ready

log = logging.getLogger("jepsen_tpu.dbs.disque")

PORT = 7711
QUEUE = "jepsen"
CLIENT_TIMEOUT_MS = 100  # job poll timeout


_suite = SuiteCfg("disque", PORT, "/opt/disque")
node_host = _suite.host
node_port = _suite.port


class DisqueDB(ArchiveDB):
    """disque-server per node, joined via CLUSTER MEET to the primary
    (disque.clj:40-135). The reference builds from source on-node;
    archive mode installs a prebuilt (or sim) tarball through the same
    daemon machinery."""

    binary = "disque-server"
    log_name = "disque.log"
    pid_name = "disque.pid"

    def __init__(self, archive_url: str | None = None,
                 ready_timeout: float = 30.0):
        super().__init__(_suite, archive_url, ready_timeout)

    def daemon_args(self, test, node) -> list:
        return ["--port", str(node_port(test, node))]

    def probe_ready(self, test, node) -> bool:
        return resp_ping_ready(_suite, test, node)

    def post_start(self, test, node) -> None:
        # join everyone to the primary (disque.clj:96-105)
        primary = test["nodes"][0]
        if node == primary:
            return
        conn = redis_proto.RespConn(
            node_host(test, node), node_port(test, node))
        try:
            res = conn.call("CLUSTER", "MEET",
                            node_host(test, primary),
                            node_port(test, primary))
            if res != "OK":
                raise db.SetupFailed(f"cluster meet said {res!r}")
        finally:
            conn.close()


class DisqueClient(client.Client):
    """enqueue = ADDJOB, dequeue = GETJOB+ACKJOB, drain = dequeue until
    empty (disque.clj:194-262). An empty poll is a definite :fail; any
    connection trouble on enqueue/dequeue is :info (the job may or may
    not be in)."""

    def __init__(self, conn=None, queue: str = QUEUE):
        self.conn = conn
        self.queue = queue

    def open(self, test, node):
        wrapped = reconnect.wrapper(
            open=lambda: redis_proto.RespConn(
                node_host(test, node), node_port(test, node)),
            close=lambda c: c.close(),
            name=f"disque {node}",
        ).open()
        return DisqueClient(wrapped, self.queue)

    def _dequeue_once(self, c):
        """(job-id, body) or None."""
        got = c.call("GETJOB", "TIMEOUT", CLIENT_TIMEOUT_MS, "COUNT", 1,
                     "FROM", self.queue)
        if not got:
            return None
        _q, jid, body = got[0]
        c.call("ACKJOB", jid)
        return jid, body

    def _drain(self, op: Op) -> Op:
        """Dequeue until empty. Errors mid-drain keep the values already
        ACKed — dropping them would make the queue checker count
        definitely-consumed jobs as lost."""
        values = []
        deadline = time.monotonic() + 10.0
        try:
            with self.conn.with_conn() as c:
                while time.monotonic() < deadline:
                    got = self._dequeue_once(c)
                    if got is None:
                        return op.with_(type="ok", value=values)
                    values.append(int(got[1].decode()))
            return op.with_(type="info", error="drain-timeout",
                            value=values)
        except (redis_proto.RespError, ConnectionError, socket.timeout,
                TimeoutError, OSError) as e:
            return op.with_(type="info", error=str(e), value=values)

    def invoke(self, test, op: Op) -> Op:
        if op.f == "drain":
            return self._drain(op)
        try:
            with self.conn.with_conn() as c:
                if op.f == "enqueue":
                    c.call("ADDJOB", self.queue, str(op.value), 100)
                    return op.with_(type="ok")
                if op.f == "dequeue":
                    got = self._dequeue_once(c)
                    if got is None:
                        return op.with_(type="fail", error="empty")
                    return op.with_(type="ok", value=int(got[1].decode()))
                raise ValueError(f"unknown op {op.f!r}")
        except redis_proto.RespError as e:
            return op.with_(type="info", error=str(e))
        except (socket.timeout, TimeoutError):
            return op.with_(type="info", error="timeout")
        except (ConnectionError, OSError) as e:
            return op.with_(type="info", error=str(e))

    def close(self, test):
        if self.conn:
            self.conn.close()


def queue_gen() -> gen.Generator:
    counter = itertools.count()

    def enqueue(test, process):
        return {"type": "invoke", "f": "enqueue", "value": next(counter)}

    return gen.mix([enqueue, {"type": "invoke", "f": "dequeue"}])


def disque_test(opts: dict) -> dict:
    from ..testlib import noop_test

    db_ = DisqueDB(archive_url=opts.get("archive_url"))
    test = noop_test()
    test.update(opts)
    test.update(
        {
            "name": "disque",
            "os": osdist.debian,
            "db": db_,
            "client": DisqueClient(),
            "nemesis": cmn.pick_nemesis(db_, opts),
            "generator": gen.phases(
                gen.time_limit(
                    opts.get("time_limit", 60),
                    gen.nemesis(
                        gen.start_stop(10, 10),
                        gen.stagger(opts.get("stagger", 1 / 10),
                                    queue_gen()),
                    ),
                ),
                gen.log("Healing cluster"),
                gen.nemesis(gen.once({"type": "info", "f": "stop"})),
                gen.sleep(opts.get("quiesce", 10)),
                ready_gated_final(
                    db_,
                    gen.clients(gen.each(
                        lambda: gen.once(
                            {"type": "invoke", "f": "drain"}))),
                    opts),
            ),
            "checker": checker_mod.compose({
                "perf": checker_mod.perf_checker(),
                "queue": checker_mod.total_queue(),
            }),
        }
    )
    return test


def _opt_spec(p) -> None:
    cmn.nemesis_opt(p)
    p.add_argument("--archive-url", dest="archive_url", default=None,
                   help="disque release archive (or the in-repo sim "
                        "archive for hermetic runs).")


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(disque_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
