"""TiDB test suite: register, bank, and sets workloads over the MySQL
protocol (reference: /root/reference/tidb/src/tidb/{core,db,register,
bank,sets,sql}.clj; clients live in mysql_common.py).

The deployment is the real TRIPLE: every node runs pd-server (placement
driver, client port 2379 / peer port 2380), tikv-server (storage,
20160), and tidb-server (SQL, 4000), brought up in order — pd on every
node, then tikv once the pd quorum answers, then tidb (tidb/db.clj:
14-223; the reference synchronizes between stages with fixed sleeps,
here each stage polls every node's ports until ready). Each component
has its own pid/log, and the kill-pd / kill-tikv / kill-tidb nemeses
target them independently — a tikv kill must leave the node's
tidb-server alive (replicated reads keep serving), which
tests/test_mysql_suites.py exercises end-to-end.

Hermetic runs install dbs/tidb_sim's archive: pd/tikv as role
placeholders with real pids/ports/logs, tidb as the MySQL-protocol sim.
"""

from __future__ import annotations

from .. import cli
from ..control import util as cu
from .mysql_common import make_sql_suite

PD_CLIENT_PORT = 2379
PD_PEER_PORT = 2380
TIKV_PORT = 20160

ROLES = ("pd", "tikv", "tidb")
_ROLE_TAG = {"pd": "jepsen-pd", "tikv": "jepsen-kv", "tidb": "jepsen-db"}
_ROLE_BIN = {"pd": "pd-server", "tikv": "tikv-server",
             "tidb": "tidb-server"}


def _make_db(suite):
    from .common import MultiDaemonDB

    class TidbDB(MultiDaemonDB):
        """pd/tikv/tidb triple per node, ordered readiness-gated
        bring-up (tidb/db.clj:76-223). The base-class single-daemon
        surface (binary/pid_name/start) points at the SQL daemon, so
        the shared start-kill/hammer-time nemeses keep working — they
        hit tidb-server while pd and tikv stay up."""

        binary = "tidb-server"
        log_name = "jepsen-db.log"
        pid_name = "jepsen-db.pid"

        ROLES = ROLES
        ROLE_TAG = _ROLE_TAG
        ROLE_BIN = _ROLE_BIN
        # reference stop! order: tidb, tikv, pd (db.clj:123-128)
        STOP_ORDER = ("tidb", "tikv", "pd")

        def __init__(self, archive_url=None, ready_timeout=60.0):
            super().__init__(suite, archive_url, ready_timeout)

        # ---- per-role addressing ----

        def role_port(self, test, node, role) -> int:
            if role == "tidb":
                return suite.port(test, node)
            ports = suite.cfg(test).get(f"{role}_ports")
            if ports:
                return ports[node]
            return PD_CLIENT_PORT if role == "pd" else TIKV_PORT

        def pd_peer_port(self, test, node) -> int:
            ports = suite.cfg(test).get("pd_peer_ports")
            return ports[node] if ports else PD_PEER_PORT

        def pd_endpoints(self, test) -> str:
            return ",".join(
                f"{suite.host(test, n)}:{self.role_port(test, n, 'pd')}"
                for n in test["nodes"])

        def role_args(self, test, node, role) -> list:
            d = suite.dir(test, node)
            host = suite.host(test, node)
            if role == "pd":
                i = list(test["nodes"]).index(node) + 1
                initial = ",".join(
                    f"pd{k + 1}=http://{suite.host(test, n)}:"
                    f"{self.pd_peer_port(test, n)}"
                    for k, n in enumerate(test["nodes"]))
                cport = self.role_port(test, node, "pd")
                return [
                    "--name", f"pd{i}",
                    "--data-dir", f"{d}/pd{i}",
                    "--client-urls", f"http://0.0.0.0:{cport}",
                    "--peer-urls",
                    f"http://0.0.0.0:{self.pd_peer_port(test, node)}",
                    "--advertise-client-urls", f"http://{host}:{cport}",
                    "--advertise-peer-urls",
                    f"http://{host}:{self.pd_peer_port(test, node)}",
                    "--initial-cluster", initial,
                ]
            if role == "tikv":
                i = list(test["nodes"]).index(node) + 1
                kport = self.role_port(test, node, "tikv")
                return [
                    "--pd", self.pd_endpoints(test),
                    "--addr", f"0.0.0.0:{kport}",
                    "--advertise-addr", f"{host}:{kport}",
                    "--data-dir", f"{d}/tikv{i}",
                ]
            return ["--port", str(suite.port(test, node)),
                    "--store", "tikv",
                    "--path", self.pd_endpoints(test)]

        # base-class hook: start() launches self.binary with these —
        # identical to start_component(..., "tidb")
        def daemon_args(self, test, node) -> list:
            return self.role_args(test, node, "tidb")

        # ---- ordered bring-up (db.clj:76-223) ----

        def setup(self, test, node) -> None:
            remote = test["remote"]
            d = suite.dir(test, node)
            cu.install_archive(remote, node, self.resolve_url(test), d,
                               sudo=suite.sudo(test))
            self.start_component(test, node, "pd")
            self._await_ports(test, "pd", self.ready_timeout)
            self.start_component(test, node, "tikv")
            self._await_ports(test, "tikv", self.ready_timeout)
            self.start_component(test, node, "tidb")
            self.await_ready(test, node)

        def probe_ready(self, test, node) -> bool:
            from .mysql_common import probe_mysql_ready

            return probe_mysql_ready(suite, test, node)

    return TidbDB


from .common import ComponentKiller  # noqa: E402 — shared with ndb

COMPONENT_NEMESES = ("kill-pd", "kill-tikv", "kill-tidb")


def _extra_nemeses(db) -> dict:
    return {
        f"kill-{role}": (lambda role=role: ComponentKiller(db, role))
        for role in ROLES
    }


def _daemon_args(suite, test, node) -> list:
    # retained for factory-API compatibility; the triple DB overrides
    # daemon_args with its per-role builder
    pd = ",".join(f"{suite.host(test, n)}:{PD_CLIENT_PORT}"
                  for n in test["nodes"])
    return ["--port", str(suite.port(test, node)),
            "--store", "tikv", "--path", pd]


suite, TidbDB, workloads, tidb_test, _opt_spec = make_sql_suite(
    "tidb", 4000, "tidb-server", _daemon_args,
    ("register", "bank", "sets"),
    db_cls=_make_db,
    extra_nemeses=_extra_nemeses,
    extra_nemesis_names=COMPONENT_NEMESES)


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(tidb_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
