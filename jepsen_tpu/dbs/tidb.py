"""TiDB test suite: register, bank, and sets workloads over the MySQL
protocol (reference: /root/reference/tidb/src/tidb/{core,db,register,
bank,sets,sql}.clj; clients live in mysql_common.py).

TiDB listens on 4000; the real deployment is a pd/tikv/tidb triple per
node (tidb/db.clj:1-223) — the archive's `tidb-server` binary is
expected to wrap that bring-up; the hermetic path runs dbs/mysql_sim
through the same daemon machinery."""

from __future__ import annotations

from .. import cli
from .mysql_common import make_sql_suite


def _daemon_args(suite, test, node) -> list:
    pd = ",".join(f"{suite.host(test, n)}:2379" for n in test["nodes"])
    return ["--port", str(suite.port(test, node)),
            "--store", "tikv",
            "--path", pd]


suite, TidbDB, workloads, tidb_test, _opt_spec = make_sql_suite(
    "tidb", 4000, "tidb-server", _daemon_args,
    ("register", "bank", "sets"))


def main(argv=None) -> None:
    cli.main(
        {**cli.single_test_cmd(tidb_test, opt_spec=_opt_spec),
         **cli.serve_cmd()},
        argv,
    )


if __name__ == "__main__":
    main()
